"""Headline benchmark: solve a 50k-pod burst against a 500-type catalog.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

The reference's enforced floor is 100 pods/sec for the Go FFD loop
(scheduling_benchmark_test.go:55); `vs_baseline` reports our throughput as a
multiple of that floor. The BASELINE.md target is <200 ms wall clock for the
full solve (snapshot compile + device kernel + decode) on one TPU chip.

Resilience: the TPU backend on this image is reached through a tunnel that
can be contended or down, and a blocked PJRT init sleeps FOREVER (round 1
died exactly this way, BENCH_r01.json rc=1). Every engine attempt therefore
runs in a watchdog subprocess with a hard timeout, retries with backoff,
and falls down a ladder — axon (TPU) → jax CPU → native C++ — so once the
ladder starts this script always prints a benchmark record and exits 0.
Diagnostics for every failed attempt ride along in detail.attempts.

ONE deliberate exception precedes the ladder: the graftlint preflight
(tier-1 gate, ISSUE 1). An unsuppressed static-analysis finding is a repo
bug, not an environment hazard, so it exits 2 with the findings on stderr
in milliseconds — failing fast is the point, and no engine record exists
to report.

A second exception follows the record: the REGRESSION SENTINEL. After the
benchmark line prints, the fresh headline is compared against the newest
committed BENCH_r*.json (same-engine records only — a CPU-ladder rescue
is an environment event, not a regression) and, under `--consolidation`,
a fresh `python -m perf --json 4` run is compared against the newest
PERF_r*.json consolidation row, and a fresh `python -m perf global` run
must hold the ISSUE-13/14 global-consolidation acceptance as a HARD gate:
the joint 2000-node convergence inside its wall-clock budget
(PERF_GLOBAL_BUDGET_MS, default 7.5 s since ISSUE 19), end cost ≤ the
per-candidate ladder oracle's on an identical fleet, exactly one
confirming simulation per executed joint command, and at most one probe
dispatch per cluster-state generation — exit 3 on any violation. `--multitenant` adds the multi-tenant
fleet leg the same way: a fresh `python -m perf multitenant` run vs the
newest committed multitenant row, on BOTH total wall clock and the
concurrent worst-tenant p99 (baseline-gated — no committed row, no fresh
run). `--multichip` adds the partitioned mesh leg: a fresh `python -m
perf multichip` run must show parity=exact on the gate row (the merged
partitioned end state bit-identical to its unsharded oracle), sharded
<= 0.8x unsharded on real accelerator meshes (the virtual-CPU mesh is
exempted to parity-only), zero host-routed pods on the 500k burst row,
and its sharded_ms is regression-compared against the newest committed
MULTICHIP_r*.json (both the legacy dryrun-tail schema and the new
perf-row schema parse). `--priority` adds the admission leg: a fresh
`python -m perf priority` run must hold the ISSUE-12 acceptance — tier
order never violated, gangs all-or-nothing (the starved-budget case
routed, zero partial binds), node count ≤ the tiered-FFD oracle +2%,
every preemption confirmed by real simulation before execute — and each
row's ms regression-compares against the newest committed PERF_r*.json
row of the same config. `--spot` adds the spot-resilience leg (ISSUE 15):
a fresh `python -m perf spot` 1000-node seeded storm must converge with
the risk-aware end cost strictly below the risk-blind (λ=0) baseline on
the same seed, churn bounded by the storm's interruption events, and
zero pods lost to reclaims whose notice arrived with ≥1 round of lead —
exit 3 on any violation. A >15% regression on any leg prints a delta
table on stderr and
exits 3 — the record is still on stdout, so drivers always get their
line. KARPENTER_BENCH_SENTINEL=0 disables the gate (noisy shared boxes).

`--replay-verify` adds the replay-capsule leg (obs/capsule.py): one fresh
interpreter re-solves the headline row inside a round trace and writes
its capsule (`--child-capture`), a second fresh interpreter replays it
(`python -m karpenter_tpu.obs replay --json`), and the run exits 3 when
the replay is not bit-identical to the captured outputs or the capture
child solved on a different solver.route rung than the benched record —
the "capture here, reproduce anywhere" contract, machine-checked.

The sentinel also gates on the DECISION PLANE (obs/decisions.py): the
fresh record carries the timed solves' rung summary (detail.rungs), and a
site that ran a rung strictly below the committed baseline's — the
headline solved on the host rung, the multichip gate row on the
replicated or unsharded rung — exits 3 loudly even when the wall clock
happens to pass (same-engine/same-metric gated, like the ms pair;
baselines older than the ledger anchor on device_stats.engine).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

GIB = 2**30


def build_workload(n_pods=50_000, n_types=500):
    from karpenter_tpu.api import labels as wk
    from karpenter_tpu.api.nodepool import NodePool
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.cloudprovider.catalog import benchmark_catalog
    from karpenter_tpu.models.inflight import ClaimTemplate

    catalog = benchmark_catalog(n_types)
    pools = [NodePool(metadata=ObjectMeta(name="general"))]
    spot = NodePool(metadata=ObjectMeta(name="spot"))
    spot.spec.weight = 10
    pools.append(spot)

    # burst dominated by ~24 deployment shapes (the realistic regime the
    # grouped kernel exploits), mixing selectors like the reference's
    # benchmark pod mix (scheduling_benchmark_test.go:234-248)
    shapes = []
    sizes = [(0.1, 0.25), (0.25, 0.5), (0.5, 1.0), (1.0, 2.0), (2.0, 8.0), (4.0, 16.0)]
    selectors = [
        {},
        {wk.ARCH_LABEL: "amd64"},
        {wk.ARCH_LABEL: "arm64"},
        {wk.CAPACITY_TYPE_LABEL: "spot"},
    ]
    for cpu, mem in sizes:
        for sel in selectors:
            shapes.append(({"cpu": cpu, "memory": mem * GIB}, sel))

    pods = []
    for i in range(n_pods):
        req, sel = shapes[i % len(shapes)]
        # shared-by-reference spec sub-objects, exactly like clone-stamped
        # replicas (Pod.clone shares requests/node_selector): the burst's
        # first-sight signature pass dedups by identity instead of paying
        # a per-pod hash
        pods.append(
            Pod(metadata=ObjectMeta(name=f"p{i}"), requests=req, node_selector=sel)
        )
    templates = [ClaimTemplate(p) for p in pools]
    its = {p.name: catalog for p in pools}
    return pods, templates, its


def _force_cpu_jax():
    """The image's sitecustomize latches jax_platforms=axon into live config
    (env var alone is ignored); force it back and drop the device-plugin
    factories so no op can touch the tunneled chip."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge

    for plat in ("axon", "tpu"):
        getattr(xla_bridge, "_backend_factories", {}).pop(plat, None)


def run_bench(engine: str, n_pods: int, n_types: int) -> dict:
    """Child-process body: build the workload, warm up, time one solve."""
    if engine == "probe":
        # tiny device op: proves the tunneled TPU backend can initialize
        # and execute at all, without paying a full workload timeout
        import jax.numpy as jnp

        assert float(jnp.ones(8).sum()) == 8.0
        return {"metric": "probe", "value": 1, "unit": "ok", "vs_baseline": None}
    if engine == "cpu":
        _force_cpu_jax()
    if engine == "native":
        from karpenter_tpu.models import NativeSolver as Solver
    else:
        from karpenter_tpu.models import TPUSolver as Solver

    pods, templates, its = build_workload(n_pods, n_types)
    solver = Solver()

    # warmup: compile the shape bucket (first TPU compile can take 20-40s)
    solver.solve(pods, templates, its)

    # best of 5: the chip rides a shared tunnel whose round-trip latency
    # jitters by tens of ms between polls; the minimum is the solve's
    # actual capability (every run does identical work)
    from karpenter_tpu.obs import decisions

    dec0 = decisions.counts()
    elapsed = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        res = solver.solve(pods, templates, its)
        elapsed = min(elapsed, time.perf_counter() - t0)
    # the timed solves' rung summary (obs/decisions.py): the sentinel
    # fails the run when a site is off its committed baseline top rung —
    # a headline "solved" on the host rung is a routing regression even
    # when the wall clock happens to pass
    rungs = decisions.rung_delta(dec0, decisions.counts())

    # pallas A/B on the real chip: the Mosaic compat kernel is kept as a
    # measured reference (ops/pallas_kernels.py STATUS); record both sides
    # so every round carries the evidence for the off-by-default choice
    pallas = None
    prior_pallas = os.environ.get("KARPENTER_PALLAS")
    if engine == "axon" and prior_pallas != "1":
        os.environ["KARPENTER_PALLAS"] = "1"
        try:
            solver.solve(pods, templates, its)  # compile the pallas bucket
            # same rep count as the headline loop: tunnel jitter is tens of
            # ms, so an unequal best-of would bias the A/B by itself
            on_ms = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                solver.solve(pods, templates, its)
                on_ms = min(on_ms, time.perf_counter() - t0)
            pallas = {"on_ms": round(on_ms * 1000, 2),
                      "off_ms": round(elapsed * 1000, 2),
                      "default": "off (XLA fusion wins; see ops/pallas_kernels.py)"}
        except Exception as e:
            pallas = {"error": str(e)[:200]}
        finally:
            if prior_pallas is None:
                del os.environ["KARPENTER_PALLAS"]
            else:
                os.environ["KARPENTER_PALLAS"] = prior_pallas
    elif engine == "axon":
        # pallas_enabled() honors only "1": the user forced the pallas path
        # for the whole run, so the headline number IS pallas-on; no A/B
        # (their environment is not ours to clear)
        pallas = {"forced": prior_pallas}

    assert res.scheduled_pod_count() + len(res.pod_errors) == n_pods
    pods_per_sec = n_pods / elapsed
    return {
        "metric": f"solve_wall_clock_{n_pods}pods_x_{n_types}types",
        "value": round(elapsed * 1000, 2),
        "unit": "ms",
        # reference floor: 100 pods/sec (scheduling_benchmark_test.go:55)
        "vs_baseline": round(pods_per_sec / 100.0, 1),
        "detail": {
            "engine": engine,
            "pods_per_sec": round(pods_per_sec),
            "nodes": res.node_count(),
            "scheduled": res.scheduled_pod_count(),
            "device_stats": solver.last_device_stats,
            "rungs": rungs,
            # decomposition context (device engine only): the tunneled chip
            # pays a FIXED ~64ms round trip per solve (kernel compute
            # itself is single-digit ms); host-side tensorize+decode is
            # ~55ms. On co-located hardware the device path's floor is the
            # host-side work alone.
            **({"harness_note": "wall clock includes one ~64ms tunnel round trip"}
               if engine == "axon" else {}),
            **({"pallas": pallas} if pallas is not None else {}),
        },
    }


def run_capture(engine: str, n_pods: int, n_types: int, path: str) -> dict:
    """--child-capture body: solve the headline workload once inside a
    round trace and serialize the solve's replay capsule (obs/capsule.py)
    to ``path`` — the capture half of the --replay-verify leg."""
    if engine == "cpu":
        _force_cpu_jax()
    if engine == "native":
        from karpenter_tpu.models import NativeSolver as Solver
    else:
        from karpenter_tpu.models import TPUSolver as Solver

    from karpenter_tpu import obs
    from karpenter_tpu.obs import capsule, decisions

    pods, templates, its = build_workload(n_pods, n_types)
    solver = Solver()
    solver.solve(pods, templates, its)  # warm the compile families
    dec0 = decisions.counts()
    with obs.round_trace("bench-headline") as tr:
        solver.solve(pods, templates, its)
    rungs = decisions.rung_delta(dec0, decisions.counts())
    # the thread's last-capture slot outlives the round (a clean round
    # releases its own pending reference at close — obs/capsule.py)
    rec = capsule.last_capture()
    written = None
    if rec is not None:
        written = capsule.write_capsule(rec, trace=tr, path=path,
                                        why="forced")
    return {
        "capsule": written,
        "engine": (rec or {}).get("meta", {}).get("engine"),
        "rungs": rungs,
    }


def replay_verify_problems(record: dict, capture: dict,
                           reply: dict) -> list:
    """Pure evaluation of the --replay-verify leg: the capture child must
    have solved on the same solver.route rung the benched record did (a
    fresh interpreter routing differently is a decision-rung mismatch, not
    a replay bug), and the fresh-interpreter replay must reproduce the
    captured outputs bit-identically."""
    problems = []
    if not capture.get("capsule"):
        problems.append("replay-verify: the capture child produced no "
                        "capsule (its output tail follows)")
        return problems
    rec_rungs = _record_rungs(record).get("solver.route")
    cap_rungs = (capture.get("rungs") or {}).get("solver.route")
    rec_worst = _worst_rung("solver.route", rec_rungs)
    cap_worst = _worst_rung("solver.route", cap_rungs)
    if rec_worst is not None and cap_worst is not None and (
            rec_worst != cap_worst):
        problems.append(
            f"replay-verify: the capture child solved on the {cap_worst} "
            f"rung but the benched record ran {rec_worst} — decision-rung "
            "mismatch")
    r = (reply or {}).get("replay") or {}
    if r.get("error"):
        problems.append(f"replay-verify: replay failed: {r['error']}")
    elif r.get("parity") != "exact":
        problems.append(
            f"replay-verify: parity={r.get('parity')!r} (nodes "
            f"{r.get('nodes')} vs captured {r.get('captured_nodes')}) — "
            "the captured solve did not reproduce bit-identically")
    elif not r.get("rung_match", True):
        problems.append(
            f"replay-verify: replay executed the {r.get('rung')} rung but "
            f"the capture ran {r.get('captured_rung')}")
    return problems


def replay_verify(record: dict, n_pods: int, n_types: int) -> int:
    """The --replay-verify leg: capture the headline row's solve in one
    fresh interpreter, replay the capsule in ANOTHER fresh interpreter
    (`python -m karpenter_tpu.obs replay --json`), and exit 3 on any
    parity or decision-rung mismatch. Engine-gated like the sentinel: a
    run that never produced an engine record has nothing to verify."""
    import tempfile

    engine = (record.get("detail") or {}).get("engine")
    if engine in (None, "none", "probe"):
        print("bench: replay-verify skipped (no engine record)",
              file=sys.stderr)
        return 0
    path = os.path.join(tempfile.mkdtemp(prefix="bench-capsule-"),
                        "headline.capsule.npz")
    env = dict(os.environ)
    if engine != "axon":
        env["JAX_PLATFORMS"] = "cpu"
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        cap_proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child-capture",
             engine, str(n_pods), str(n_types), path],
            capture_output=True, text=True, timeout=900, env=env, cwd=here)
    except subprocess.TimeoutExpired:
        print("bench: replay-verify: capture child timed out",
              file=sys.stderr)
        return 3
    def _tail(proc, label):
        # the children run with captured output: on failure their stderr
        # must reach the operator or the exit-3 is undiagnosable
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
        for line in tail:
            print(f"bench:   {label}: {line}", file=sys.stderr)

    capture: dict = {}
    for line in reversed(cap_proc.stdout.strip().splitlines()):
        try:
            capture = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    reply: dict = {}
    rep_proc = None
    if capture.get("capsule"):
        try:
            rep_proc = subprocess.run(
                [sys.executable, "-m", "karpenter_tpu.obs", "replay",
                 path, "--json"],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=here)
        except subprocess.TimeoutExpired:
            print("bench: replay-verify: replay child timed out",
                  file=sys.stderr)
            return 3
        for line in reversed(rep_proc.stdout.strip().splitlines()):
            try:
                reply = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    problems = replay_verify_problems(record, capture, reply)
    if problems:
        print("bench: replay-verify gate failed:", file=sys.stderr)
        for p in problems:
            print(f"bench:   {p}", file=sys.stderr)
        if not capture.get("capsule"):
            _tail(cap_proc, "capture child")
        elif rep_proc is not None and not reply:
            _tail(rep_proc, "replay child")
        return 3
    r = (reply.get("replay") or {})
    print(f"bench: replay-verify ok (rung={r.get('rung')} parity=exact, "
          f"capsule {path})", file=sys.stderr)
    return 0


# --------------------------------------------------------------------------
# regression sentinel: the fresh record vs the newest committed baseline
# --------------------------------------------------------------------------

SENTINEL_THRESHOLD = 0.15  # >15% slower than the baseline record fails


def regression_table(pairs, threshold: float = SENTINEL_THRESHOLD):
    """pairs: [(label, baseline_ms, fresh_ms)] -> (regressed, table lines).
    Pure so the sentinel logic is unit-testable without a benchmark run."""
    lines = [f"{'metric':44s} {'baseline':>10} {'fresh':>10} {'delta':>8}"]
    regressed = False
    for label, base, fresh in pairs:
        if base is None or fresh is None or base <= 0:
            continue
        delta = (fresh - base) / base
        bad = delta > threshold
        regressed = regressed or bad
        lines.append(
            f"{label:44s} {base:>10.2f} {fresh:>10.2f} {100 * delta:>+7.1f}%"
            f"{'  <-- REGRESSION' if bad else ''}"
        )
    return regressed, lines


def _newest(pattern: str):
    import glob

    files = sorted(glob.glob(
        os.path.join(os.path.dirname(os.path.abspath(__file__)), pattern)))
    return files[-1] if files else None


def _baseline_headline():
    """(value_ms, engine, metric) of the newest BENCH_r*.json, or None."""
    rec = _baseline_headline_record()
    if rec is None:
        return None
    value = rec.get("value")
    if not isinstance(value, (int, float)):
        return None
    return (float(value), (rec.get("detail") or {}).get("engine"),
            rec.get("metric"))


def _baseline_headline_record() -> dict | None:
    """The newest BENCH_r*.json's parsed record (full dict), or None."""
    path = _newest("BENCH_r*.json")
    if path is None:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    rec = doc.get("parsed")
    return rec if isinstance(rec, dict) else None


# pre-decision-ledger records carry only device_stats.engine: map it onto
# the solver.route rung vocabulary so old baselines still anchor the gate
# ("device" is the XLA kernel; a mesh-routed solve also reported "device",
# so mapping to xla can only under-claim the baseline — safe direction)
_ENGINE_RUNG = {"device": "xla", "native": "native", "host": "host",
                "remote": "service", "mesh": "mesh"}


def _record_rungs(rec: dict) -> dict:
    """A bench record's {site: {rung: n}} summary; synthesized from
    device_stats.engine for records older than the decision ledger."""
    detail = rec.get("detail") or {}
    rungs = detail.get("rungs")
    if isinstance(rungs, dict) and rungs:
        return rungs
    engine = (detail.get("device_stats") or {}).get("engine")
    rung = _ENGINE_RUNG.get(engine)
    return {"solver.route": {rung: 1}} if rung else {}


def _worst_rung(site: str, mix: dict) -> str | None:
    """Worst-ranked rung present in one site's {rung: n} mix."""
    from karpenter_tpu.obs import decisions

    rungs = [r for r in (mix or {}) if r in decisions.SITES[site]["rungs"]]
    if not rungs:
        return None
    return max(rungs, key=lambda r: decisions.rung_rank(site, r))


def _headline_rung_problems(record: dict) -> list:
    """Hard-gate problems when the fresh headline ran a site on a rung
    strictly below the committed baseline's worst rung for that site
    (e.g. the 50k solve landing on the host rung). Engine- and
    metric-gated exactly like the wall-clock pair — an axon baseline
    never judges a cpu-ladder rescue."""
    from karpenter_tpu.obs import decisions

    base = _baseline_headline_record()
    if base is None:
        return []
    if (base.get("detail") or {}).get("engine") != (
            record.get("detail") or {}).get("engine"):
        return []
    if base.get("metric") != record.get("metric"):
        return []
    fresh_rungs = _record_rungs(record)
    base_rungs = _record_rungs(base)
    problems = []
    for site in fresh_rungs:
        if site not in decisions.SITES:
            continue
        fresh_worst = _worst_rung(site, fresh_rungs.get(site))
        base_worst = _worst_rung(site, base_rungs.get(site))
        if fresh_worst is None or base_worst is None:
            continue
        if (decisions.rung_rank(site, fresh_worst)
                > decisions.rung_rank(site, base_worst)):
            problems.append(
                f"headline: {site} ran the {fresh_worst} rung (baseline "
                f"top rung {base_worst}) — a routing regression, not a "
                "wall-clock one")
    return problems


def _perf_baseline_rows() -> dict:
    """{config: row} of the newest PERF_r*.json results."""
    path = _newest("PERF_r*.json")
    if path is None:
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    return {
        r["config"]: r
        for r in doc.get("results", ())
        if isinstance(r, dict) and "config" in r
    }


def _fresh_perf_rows(perf_args: list, env: dict | None = None,
                     timeout: float = 900) -> dict:
    """{config: row} from one fresh `python -m perf <args>` run."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "perf", *perf_args],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, **env} if env else None,
        )
    except subprocess.TimeoutExpired:
        # say WHY no rows exist: a leg's missing-row hard gate would
        # otherwise misread a slow box as a broken perf harness
        print(f"bench: perf {' '.join(perf_args)} timed out after "
              f"{timeout:.0f}s — no rows to gate on", file=sys.stderr)
        return {}
    out = {}
    for line in proc.stdout.strip().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "config" in rec:
            out[rec["config"]] = rec
    return out


def _baseline_consolidation() -> dict:
    """{config: total_ms} consolidation rows of the newest PERF_r*.json."""
    return {
        cfg: float(r["total_ms"])
        for cfg, r in _perf_baseline_rows().items()
        if "total_ms" in r and not cfg.startswith("multitenant")
    }


def _fresh_consolidation() -> dict:
    """{config: total_ms} from one fresh `python -m perf --json 4` run."""
    return {
        cfg: float(r["total_ms"])
        for cfg, r in _fresh_perf_rows(["--json", "4"]).items()
        if "total_ms" in r
    }


def _priority_pairs():
    """(sentinel pairs, hard-gate problems) for the admission leg
    (`--priority`): one fresh `python -m perf priority` run must hold the
    ISSUE-12 acceptance — tier order never violated, gangs all-or-nothing
    (zero partial binds, the starved-budget gang routed), node count
    ≤ the tiered-FFD oracle +2%, and every preemption confirmed by real
    simulation before execute. Regression pairs compare each row's ms
    against the newest committed PERF_r*.json rows of the same config."""
    fresh = _fresh_perf_rows(["priority"])
    problems, pairs = [], []
    if not fresh:
        problems.append("priority: no rows produced")
        return pairs, problems
    saw_gang = saw_preempt = False
    for cfg, row in fresh.items():
        if row.get("tier_order_ok") is False:
            problems.append(
                f"priority: {cfg} violated tier order (a lower-tier pod "
                "placed while a feasible higher-tier pod host-routed)")
        if row.get("gang_atomic_ok") is False:
            problems.append(
                f"priority: {cfg} partially bound "
                f"{row.get('gang_partial_binds')} pod-group(s) — gangs "
                "must place all-or-nothing")
        if cfg.startswith("gang-"):
            saw_gang = True
            if not row.get("gangs_routed"):
                problems.append(
                    f"priority: {cfg} routed no gang — the starved-budget "
                    "all-or-nothing case was never exercised")
        overhead = row.get("node_overhead_pct")
        if isinstance(overhead, (int, float)) and overhead > 2.0:
            problems.append(
                f"priority: {cfg} node overhead {overhead}% vs the "
                "tiered-FFD oracle (bar: 2%)")
        # fused cluster round: the gang-free mixed config must collapse
        # to ONE solve dispatch per round (deploy/README.md "Fused
        # cluster round") — gang configs legitimately pay one dispatch
        # per gang, so only priority-mix is gated. Gated only when the
        # row carries the key, so pre-fused rows still parse.
        if (cfg.startswith("priority-")
                and isinstance(row.get("dispatches_per_round"), int)
                and row["dispatches_per_round"] > 1):
            problems.append(
                f"priority: {cfg} paid {row['dispatches_per_round']} "
                "solve dispatches in one round — the fused-round "
                "one-dispatch contract broke")
        if cfg.startswith("preempt-"):
            saw_preempt = True
            if row.get("confirm_contract_ok") is False:
                problems.append(
                    f"priority: {cfg} shipped evictions without a "
                    "confirming simulation")
            if not row.get("preemptions_confirmed"):
                problems.append(
                    f"priority: {cfg} confirmed no preemption — the "
                    "ladder was never exercised")
    if not saw_gang or not saw_preempt:
        problems.append(
            "priority: a grid family is missing "
            f"(gang={saw_gang}, preempt={saw_preempt}) — "
            "a gate that never ran must not pass by absence")
    base = _perf_baseline_rows()
    for cfg, row in fresh.items():
        b = base.get(cfg)
        if b is not None and "ms" in b and "ms" in row:
            pairs.append((cfg, float(b["ms"]), float(row["ms"])))
    return pairs, problems


def _global_pairs():
    """(sentinel pairs, hard-gate problems) for the global-consolidation
    leg (rides `--consolidation`): one fresh `python -m perf global` run
    must hold the ISSUE-13/14 acceptance — the joint 2000-node
    convergence inside its wall-clock budget (PERF_GLOBAL_BUDGET_MS,
    default 7.5 s since ISSUE 19 — measured same-box against the unfused
    parent: fused 5.5-6.9 s vs 7.7 s unfused, so the default passes the
    fused round and fails the baseline), end-state cost ≤ the per-candidate
    ladder oracle's on the identical fleet, exactly one confirming
    simulation per executed joint command, and at most ONE probe
    dispatch per cluster-state generation (the short-circuit contract —
    gated only when the row carries the ISSUE-14 key, so pre-ISSUE-14
    rows still parse). Regression pairs compare the joint total_ms
    against the newest committed PERF_r*.json row of the same config,
    old or new schema alike (both carry total_ms)."""
    fresh = _fresh_perf_rows(["global"])
    problems, pairs = [], []
    row = next((r for r in fresh.values()
                if r.get("config", "").endswith("-global")), None)
    if row is None:
        problems.append(
            "global: no row produced — the joint-consolidation gate was "
            "never evaluated")
        return pairs, problems
    cfg = row["config"]
    if row.get("within_budget_ms") is False:
        problems.append(
            f"global: {cfg} joint convergence {row.get('total_ms')}ms "
            "exceeded the wall-clock budget (PERF_GLOBAL_BUDGET_MS)")
    if row.get("cost_le_ladder") is False:
        problems.append(
            f"global: {cfg} joint end cost {row.get('end_cost')} exceeds "
            f"the ladder oracle's {(row.get('ladder') or {}).get('end_cost')}"
            " — the joint selection shipped a worse end state")
    if row.get("confirm_contract_ok") is False:
        problems.append(
            f"global: {cfg} ran {row.get('confirm_count')} confirming "
            f"simulations for {row.get('joint_commands')} joint "
            "command(s) — the one-confirm-per-command contract broke")
    if row.get("dispatch_contract_ok") is False:
        problems.append(
            f"global: {cfg} paid "
            f"{row.get('max_dispatches_per_generation')} probe dispatches "
            "in one cluster-state generation — the short-circuit's "
            "max-one-dispatch-per-generation contract broke")
    # fused cluster round: the eviction wave must stay on the snapshot
    # cache's journal-delta path — any "rebuild" verdict means a drain
    # delta forced a full fleet re-tensorization (the ~0.6 s/wave the
    # fused round reclaims). Gated only when the row carries the key.
    if row.get("delta_path_ok") is False:
        problems.append(
            f"global: {cfg} paid {row.get('snapshot_rebuilds')} full "
            "snapshot rebuild(s) across the eviction wave — the "
            "journal-delta path declined mid-wave")
    # fleet ledger: both legs' end-of-run live rate must match the row's
    # end-cost sweep within 1% (same catalog walk — any gap is a missed
    # launch/retire event). Gated only when the row carries the key.
    if row.get("cost_reconciled_ok") is False:
        ledger = (row.get("ledger") or {})
        problems.append(
            f"global: {cfg} fleet-ledger live rate "
            f"{ledger.get('live_rate')} did not reconcile with the "
            f"end-cost sweep {row.get('end_cost')} within 1% — a "
            "lifecycle event escaped the ledger")
    base = _perf_baseline_rows().get(cfg)
    if base is not None and "total_ms" in base and "total_ms" in row:
        pairs.append((cfg, float(base["total_ms"]), float(row["total_ms"])))
    return pairs, problems


def _global_xl_pairs():
    """(sentinel pairs, hard-gate problems) for the 10k-node LP-rung
    sentinel (`python -m perf global-xl`, deploy/README.md "LP
    relaxation rung"). Baseline-gated like the multitenant leg — no
    committed ``-global-xl`` row, no fresh multi-minute run. When it
    runs, two verdicts hard-gate: the relax leg must ship its joint
    round (``relax_completed``) and the ladder subprocess must NOT
    finish inside the timeout (``ladder_completed`` false) — a ladder
    that completes first means the shape no longer demonstrates the LP
    rung's asymptotic edge and the row needs re-tuning, loudly. The
    relax round wall clock regression-compares against the committed
    row."""
    base = {cfg: r for cfg, r in _perf_baseline_rows().items()
            if cfg.endswith("-global-xl")}
    if not base:
        return [], []
    fresh = _fresh_perf_rows(["global-xl"], timeout=3600)
    problems, pairs = [], []
    row = next((r for r in fresh.values()
                if r.get("config", "").endswith("-global-xl")), None)
    if row is None:
        problems.append(
            "global-xl: no row produced — the LP-rung sentinel was "
            "never evaluated")
        return pairs, problems
    cfg = row["config"]
    if not row.get("relax_completed"):
        problems.append(
            f"global-xl: {cfg} relax leg shipped no joint command "
            f"(relax stats: {(row.get('relax') or {}).get('relax')}) — "
            "the LP rung failed the fleet it exists for")
    if row.get("ladder_completed"):
        problems.append(
            f"global-xl: {cfg} ladder leg finished inside the timeout "
            f"({(row.get('ladder') or {}).get('round_ms')}ms) — the "
            "sentinel shape no longer separates the solvers")
    b = base.get(cfg)
    if (b is not None and isinstance((b.get("relax") or {}), dict)
            and "round_ms" in (b.get("relax") or {})
            and "round_ms" in (row.get("relax") or {})):
        pairs.append((f"{cfg}:round", float(b["relax"]["round_ms"]),
                      float(row["relax"]["round_ms"])))
    return pairs, problems


def _spot_pairs():
    """(sentinel pairs, hard-gate problems) for the spot-resilience leg
    (`--spot`): one fresh `python -m perf spot` run must hold the
    ISSUE-15 acceptance — the 1000-node seeded storm converges with the
    risk-aware end cost strictly below the risk-blind (λ=0) baseline on
    the same seed, churn bounded by the storm's interruption events, and
    zero pods lost to reclaims whose notice arrived with ≥1 round of
    lead. Regression pairs compare the row's total_ms against the newest
    committed PERF_r*.json row of the same config."""
    # the risk-blind leg alone measures ~30 min on the reference box (its
    # churn IS the point): give the child real headroom over that
    fresh = _fresh_perf_rows(["spot"], timeout=4500)
    problems, pairs = [], []
    row = next((r for r in fresh.values()
                if r.get("config", "").startswith("spot-")), None)
    if row is None:
        problems.append(
            "spot: no row produced — the spot-resilience gate was never "
            "evaluated")
        return pairs, problems
    cfg = row["config"]
    if row.get("cost_beats_blind") is False:
        aware = (row.get("risk_aware") or {}).get("end_cost")
        blind = (row.get("risk_blind") or {}).get("end_cost")
        problems.append(
            f"spot: {cfg} risk-aware end cost {aware} did not beat the "
            f"risk-blind baseline {blind} — the risk discount bought "
            "nothing")
    if row.get("churn_bound_ok") is False:
        problems.append(
            f"spot: {cfg} created {(row.get('risk_aware') or {}).get('creates')} "
            f"nodes against a churn bound of {row.get('churn_bound')} — "
            "the storm cascaded")
    if row.get("zero_late_drain_ok") is False:
        lost = ((row.get("risk_aware") or {}).get("pods_lost_with_lead", 0)
                + (row.get("risk_blind") or {}).get("pods_lost_with_lead", 0))
        problems.append(
            f"spot: {cfg} lost {lost} pod(s) to reclaims whose notice "
            "arrived with >=1 round of lead — the proactive drain "
            "machinery failed")
    # fleet ledger (deploy/README.md "Fleet ledger"): the storm's
    # realized-cost integral must close on a live rate within 1% of the
    # row's own end-cost sweep on BOTH legs — a gap means a lifecycle
    # event (launch/retire) escaped the ledger. Gated only when the row
    # carries the key, so pre-ledger committed rows still parse.
    if row.get("cost_reconciled_ok") is False:
        aware_l = (row.get("risk_aware") or {})
        blind_l = (row.get("risk_blind") or {})
        problems.append(
            f"spot: {cfg} fleet-ledger live rate did not reconcile with "
            "the end-cost sweep within 1% (risk-aware "
            f"{aware_l.get('ledger_live_rate')} vs {aware_l.get('end_cost')}, "
            f"risk-blind {blind_l.get('ledger_live_rate')} vs "
            f"{blind_l.get('end_cost')}) — a lifecycle event escaped the "
            "ledger")
    base = _perf_baseline_rows().get(cfg)
    if base is not None and "total_ms" in base and "total_ms" in row:
        pairs.append((cfg, float(base["total_ms"]), float(row["total_ms"])))
    return pairs, problems


def _multitenant_pairs():
    """(sentinel pairs, hard-gate problems) for the multi-tenant fleet
    row: wall clock AND the concurrent worst-tenant p99 (a queueing/
    coalescing regression shows up in p99 long before total wall clock
    moves), plus the fleet-ledger billing reconciliation — the server's
    per-tenant billed device seconds must sum to its own devplane
    dispatch ledger within rounding. Baseline-gated like the
    consolidation leg: no committed multitenant row, no fresh run."""
    base = {
        cfg: r for cfg, r in _perf_baseline_rows().items()
        # a degraded committed row (client fallbacks — its latencies never
        # crossed the wire) must not become the yardstick either
        if cfg.startswith("multitenant") and "total_ms" in r
        and not r.get("degraded")
    }
    if not base:
        return [], []
    pairs, problems = [], []
    fresh_rows = _fresh_perf_rows(["multitenant"])
    for cfg, fresh in fresh_rows.items():
        # billing gate first: it holds on degraded rows too (the billed
        # seconds describe dispatches that DID happen server-side), and
        # only when the row carries the key (pre-ledger rows still parse)
        if fresh.get("billing_sums_ok") is False:
            b_plane = fresh.get("billing") or {}
            problems.append(
                f"multitenant: {cfg} per-tenant billed device seconds "
                f"{b_plane.get('total_device_seconds')} did not sum to "
                "the server's devplane dispatch ledger "
                f"{b_plane.get('devplane_dispatch_seconds')} within "
                "rounding — a dispatch escaped tenant attribution")
        b = base.get(cfg)
        if b is None or "total_ms" not in fresh:
            continue
        if fresh.get("degraded"):
            # client fallbacks mean the latencies never crossed the
            # service — not a number to gate on (or to pass on)
            print(f"bench: multitenant sentinel: fresh {cfg} row is "
                  "degraded (client fallbacks) — not compared",
                  file=sys.stderr)
            continue
        pairs.append((cfg, float(b["total_ms"]), float(fresh["total_ms"])))
        if "worst_p99_ms" in b and "worst_p99_ms" in fresh:
            pairs.append((f"{cfg}:p99", float(b["worst_p99_ms"]),
                          float(fresh["worst_p99_ms"])))
    if not pairs:
        # a committed baseline exists, the fresh run was paid, and NOTHING
        # matched (config shape drift — different PERF_TENANTS etc.): a
        # silently-green no-op gate is worse than a loud one
        print("bench: multitenant sentinel: no fresh row matched the "
              f"committed configs {sorted(base)} (fresh: "
              f"{sorted(fresh_rows)}) — nothing was compared",
              file=sys.stderr)
    return pairs, problems


def _baseline_multichip() -> list:
    """[(label, sharded_ms)] from the newest committed MULTICHIP_r*.json.
    Recognizes BOTH schemas: the legacy dryrun capture ({"tail":
    "...sharded_ms=X unsharded_ms=Y"}) and the perf-row schema the
    partitioned rows emit — {"results": [row,...]}, a bare row list, or a
    single row dict, each row keyed by "config" with "sharded_ms"."""
    import re

    path = _newest("MULTICHIP_r*.json")
    if path is None:
        return []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return []
    rows = []
    if isinstance(doc, dict) and isinstance(doc.get("results"), list):
        rows = doc["results"]
    elif isinstance(doc, list):
        rows = doc
    elif isinstance(doc, dict) and "sharded_ms" in doc:
        rows = [doc]
    out = []
    for r in rows:
        if isinstance(r, dict) and isinstance(r.get("sharded_ms"), (int, float)):
            out.append((r.get("config", "multichip"), float(r["sharded_ms"])))
    if out:
        return out
    # legacy schema: the dryrun's stderr/stdout tail with the timing line
    tail = doc.get("tail", "") if isinstance(doc, dict) else ""
    m = re.search(r"sharded_ms=([0-9.]+)", tail)
    if m:
        return [("multichip:legacy-dryrun-tail", float(m.group(1)))]
    return []


def _baseline_multichip_engines() -> dict:
    """{config: engine} of the newest committed MULTICHIP_r*.json rows —
    the baseline side of the mesh.partition rung gate (legacy dryrun-tail
    captures carry no engine and leave the gate dormant)."""
    path = _newest("MULTICHIP_r*.json")
    if path is None:
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    rows = []
    if isinstance(doc, dict) and isinstance(doc.get("results"), list):
        rows = doc["results"]
    elif isinstance(doc, list):
        rows = doc
    elif isinstance(doc, dict) and "sharded_ms" in doc:
        rows = [doc]
    return {
        r["config"]: r["engine"]
        for r in rows
        if isinstance(r, dict) and r.get("config") and r.get("engine")
    }


def _multichip_pairs():
    """(sentinel pairs, hard-gate problems) for the partitioned multichip
    leg. The GATE row must be parity=exact always; on a real accelerator
    mesh sharded must be <= 0.8x unsharded (the virtual-CPU mesh is
    exempted to parity-only — its "devices" are host threads, so the
    ratio measures scheduler noise, not the interconnect); the burst row
    must have routed zero pods to the host. Regression pairs compare
    sharded_ms against the newest committed MULTICHIP_r*.json rows."""
    # skip the burst row's informational oracle replay: this leg never
    # reads burst parity (only gate-row parity + burst host routing),
    # and the replay costs about as much as the burst itself against the
    # subprocess's fixed 900s budget
    fresh = _fresh_perf_rows(["multichip"],
                             env={"PERF_MULTICHIP_BURST_PARITY": "0"})
    problems, pairs = [], []
    rows = [r for r in fresh.values() if "sharded_ms" in r]
    gate = next((r for r in rows if r.get("gate")), None)
    if gate is None:
        skipped = next((r.get("skipped") for r in fresh.values()
                        if r.get("skipped")), None)
        problems.append(
            f"multichip: no gate row produced ({skipped or 'no output'})")
        return pairs, problems
    if gate.get("parity") != "exact":
        if gate.get("parity") is None:
            # perf only computes parity on the partitioned rung — a None
            # here means the gate row FELL BACK (blocker, or
            # KARPENTER_SHARD_PARTITION=0 leaked into CI), which is a
            # routing regression, not a numerical divergence
            problems.append(
                f"multichip: gate row ran engine={gate.get('engine')!r} "
                "with no parity check — expected the partitioned rung")
        else:
            problems.append(
                f"multichip: gate row parity={gate.get('parity')!r} — the "
                "partitioned merge/repair diverged from its unsharded "
                "oracle")
    sh, un = gate.get("sharded_ms"), gate.get("unsharded_ms")
    if not gate.get("virtual", True):
        if (isinstance(sh, (int, float)) and isinstance(un, (int, float))
                and un > 0 and sh > 0.8 * un):
            problems.append(
                f"multichip: sharded {sh}ms > 0.8x unsharded {un}ms on a "
                "real accelerator mesh")
    burst_rows = [r for r in rows if not r.get("gate")]
    for r in burst_rows:
        if r.get("host_routed_pods"):
            problems.append(
                f"multichip: burst row {r.get('config')} routed "
                f"{r['host_routed_pods']} pods to the host")
    from karpenter_tpu.service.session import env_int

    if not burst_rows and env_int("PERF_MULTICHIP_PODS", 500000) > 0:
        # the zero-host-routing gate is a HARD gate: a burst row that was
        # supposed to run but never printed must fail loudly, not pass by
        # absence (mirrors the gate-row-missing problem above)
        problems.append(
            "multichip: no burst row produced (PERF_MULTICHIP_PODS did not "
            "disable it) — the zero-host-routing gate was never evaluated")
    by_config = {r.get("config"): r for r in rows}
    # mesh.partition rung gate: a fresh row running a rung strictly below
    # its committed baseline row's (partitioned → replicated/unsharded)
    # is a routing regression even when its wall clock slides under the
    # 15% bar — exactly the failure mode that made MULTICHIP_r05 a
    # replicated no-op for two PRs
    from karpenter_tpu.obs import decisions as _decisions

    for cfg, base_engine in _baseline_multichip_engines().items():
        match = by_config.get(cfg)
        if match is None or not match.get("engine"):
            continue
        if (_decisions.rung_rank("mesh.partition", match["engine"])
                > _decisions.rung_rank("mesh.partition", base_engine)):
            problems.append(
                f"multichip: {cfg} ran the {match['engine']} rung "
                f"(baseline top rung {base_engine}) — off the committed "
                "mesh.partition rung")
    for label, base_ms in _baseline_multichip():
        # only the legacy dryrun capture (no config key) may judge the gate
        # row; a row-schema label with no matching fresh config must not be
        # cross-compared against a different-shaped row
        if label.startswith("multichip:legacy"):
            match = by_config.get(label, gate)
        elif label in by_config:
            match = by_config[label]
        else:
            print(f"bench: multichip sentinel: committed baseline "
                  f"{label!r} matched no fresh row (fresh: "
                  f"{sorted(by_config)}) — not compared", file=sys.stderr)
            continue
        if isinstance(match.get("sharded_ms"), (int, float)):
            pairs.append((label, base_ms, float(match["sharded_ms"])))
    return pairs, problems


def sentinel(record: dict, consolidation: bool = False,
             multitenant: bool = False, multichip: bool = False,
             priority: bool = False, spot: bool = False) -> int:
    """Exit code for the regression gate: 0 clean/ungated, 3 on a >15%
    headline-solve, consolidation, or multi-tenant-fleet regression vs
    the newest committed records. Headline comparison is ENGINE-GATED (an
    axon baseline never gates a cpu/native rescue run).
    KARPENTER_BENCH_SENTINEL=0 disables."""
    if os.environ.get("KARPENTER_BENCH_SENTINEL", "1").strip().lower() in (
        "0", "false", "off", "no",
    ):
        return 0
    pairs = []
    base = _baseline_headline()
    fresh_value = record.get("value")
    fresh_engine = (record.get("detail") or {}).get("engine")
    # gate on BOTH engine and metric: an axon baseline never judges a
    # cpu-ladder rescue, and the 50k headline never judges an ad-hoc
    # `bench.py 2000 100` run
    if (base is not None and fresh_value is not None
            and base[1] == fresh_engine
            and base[2] == record.get("metric")):
        pairs.append((record.get("metric", "headline"), base[0],
                      float(fresh_value)))
    # decision-plane gate: a site off its baseline top rung fails even
    # when the wall clock passes (same engine/metric gating as the pair)
    h_problems = _headline_rung_problems(record)
    if h_problems:
        print("bench: headline rung gate failed "
              "(KARPENTER_BENCH_SENTINEL=0 to disable):", file=sys.stderr)
        for p in h_problems:
            print(f"bench:   {p}", file=sys.stderr)
        return 3
    if consolidation:
        base_c = _baseline_consolidation()
        # only pay the fresh multi-minute perf run when a baseline exists
        # to judge it against
        if base_c:
            for cfg, ms in _fresh_consolidation().items():
                if cfg in base_c:
                    pairs.append((cfg, base_c[cfg], ms))
        # the global-consolidation leg is a HARD gate (like --priority):
        # the joint 2k-node acceptance must hold on every gated run, not
        # only when a committed baseline row exists
        g_pairs, g_problems = _global_pairs()
        pairs.extend(g_pairs)
        if g_problems:
            print("bench: global consolidation gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):",
                  file=sys.stderr)
            for p in g_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
        # the 10k-node LP-rung sentinel rides the same flag,
        # baseline-gated (no committed -global-xl row, no fresh run)
        x_pairs, x_problems = _global_xl_pairs()
        pairs.extend(x_pairs)
        if x_problems:
            print("bench: global-xl LP-rung gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):",
                  file=sys.stderr)
            for p in x_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
    if multitenant:
        t_pairs, t_problems = _multitenant_pairs()
        pairs.extend(t_pairs)
        if t_problems:
            print("bench: multitenant billing gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):",
                  file=sys.stderr)
            for p in t_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
    if multichip:
        m_pairs, m_problems = _multichip_pairs()
        pairs.extend(m_pairs)
        if m_problems:
            print("bench: multichip gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):", file=sys.stderr)
            for p in m_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
    if priority:
        p_pairs, p_problems = _priority_pairs()
        pairs.extend(p_pairs)
        if p_problems:
            print("bench: priority/gang admission gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):", file=sys.stderr)
            for p in p_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
    if spot:
        s_pairs, s_problems = _spot_pairs()
        pairs.extend(s_pairs)
        if s_problems:
            print("bench: spot-resilience gate failed "
                  "(KARPENTER_BENCH_SENTINEL=0 to disable):", file=sys.stderr)
            for p in s_problems:
                print(f"bench:   {p}", file=sys.stderr)
            return 3
    if not pairs:
        return 0
    regressed, lines = regression_table(pairs)
    if not regressed:
        return 0
    print(f"bench: regression sentinel: >={SENTINEL_THRESHOLD:.0%} slower "
          "than the newest committed baseline record "
          "(KARPENTER_BENCH_SENTINEL=0 to disable)", file=sys.stderr)
    for line in lines:
        print(f"bench:   {line}", file=sys.stderr)
    return 3


# (engine, attempts, per-attempt timeout seconds, backoff between attempts).
# native (C++ host kernel) outranks jax-on-CPU as the fallback: same
# tensorize→kernel→decode pipeline and identical results, ~5x faster than
# the XLA CPU backend on the 50k workload.
LADDER = (
    ("axon", 2, 420, 20),
    ("native", 1, 600, 0),
    ("cpu", 1, 420, 5),
)


def _attempt(engine: str, n_pods: int, n_types: int, timeout: float):
    """One watchdog-guarded child run. Returns (record|None, diagnostic)."""
    env = dict(os.environ)
    if engine not in ("axon", "probe"):
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--child", engine,
           str(n_pods), str(n_types)]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, {"engine": engine, "outcome": "timeout", "seconds": round(timeout)}
    dt = round(time.perf_counter() - t0, 1)
    if proc.returncode == 0:
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                rec = json.loads(line)
                if isinstance(rec, dict) and "metric" in rec:
                    return rec, {"engine": engine, "outcome": "ok", "seconds": dt}
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
    return None, {
        "engine": engine,
        "outcome": f"rc={proc.returncode}",
        "seconds": dt,
        "tail": tail,
    }


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    if "--child-capture" in sys.argv:
        # bench.py --child-capture <engine> <n_pods> <n_types> <path>
        engine, n_pods, n_types, path = (
            args[0], int(args[1]), int(args[2]), args[3])
        print(json.dumps(run_capture(engine, n_pods, n_types, path)))
        return
    if "--child" in sys.argv:
        engine = sys.argv[sys.argv.index("--child") + 1]
        n_pods = int(args[1]) if len(args) > 1 else 50_000
        n_types = int(args[2]) if len(args) > 2 else 500
        print(json.dumps(run_bench(engine, n_pods, n_types)))
        return

    n_pods = int(args[0]) if args else 50_000
    n_types = int(args[1]) if len(args) > 1 else 500

    # graftlint preflight: an unsuppressed static-analysis finding fails in
    # milliseconds here instead of after minutes of ladder attempts — the
    # same tier-1 gate tests/test_static_analysis.py enforces. stdlib-only,
    # so it cannot wedge on the tunnel the way a jax import can. Full rule
    # set (GL1xx-GL5xx) through the machine-readable report, honoring the
    # committed baseline (empty: the tree is clean and must stay so).
    from karpenter_tpu.analysis import preflight_report

    # anchored on the script, not the cwd: `python /path/to/bench.py` from
    # anywhere must analyze the real tree, not silently scan nothing
    here = os.path.dirname(os.path.abspath(__file__))
    report = preflight_report(
        [os.path.join(here, "karpenter_tpu")],
        baseline_path=os.path.join(here, "graftlint-baseline.txt"))
    if not report["ok"]:
        print(json.dumps({k: report[k] for k in
                          ("findings", "baselined", "suppressed")},
                         indent=2), file=sys.stderr)
        print("bench: graftlint preflight failed — fix or suppress (with "
              "justification) before benching", file=sys.stderr)
        sys.exit(2)
    census = report["census"]
    if census["producers"] < census["site_count"]:
        print(f"bench: GL502 census regression — {census['producers']} "
              f"checked producers < {census['site_count']} registry sites",
              file=sys.stderr)
        sys.exit(2)

    attempts = []
    for engine, tries, timeout, backoff in LADDER:
        for i in range(tries):
            if i:
                time.sleep(backoff)
            if engine == "axon":
                # cheap liveness probe first: a wedged tunnel blocks PJRT
                # init forever, so don't pay the full workload timeout on it
                _, pdiag = _attempt("probe", 0, 0, 90)
                pdiag["probe_for"] = "axon"
                attempts.append(pdiag)
                if pdiag["outcome"] != "ok":
                    print(f"bench: axon probe {i + 1}: {pdiag['outcome']}", file=sys.stderr)
                    continue
            rec, diag = _attempt(engine, n_pods, n_types, timeout)
            attempts.append(diag)
            print(f"bench: {engine} attempt {i + 1}: {diag['outcome']}", file=sys.stderr)
            if rec is not None:
                rec.setdefault("detail", {})["attempts"] = attempts
                print(json.dumps(rec))
                # the record is out; now gate on the committed baselines
                rc = sentinel(
                    rec, consolidation="--consolidation" in sys.argv,
                    multitenant="--multitenant" in sys.argv,
                    multichip="--multichip" in sys.argv,
                    priority="--priority" in sys.argv,
                    spot="--spot" in sys.argv)
                if rc == 0 and "--replay-verify" in sys.argv:
                    # capture the headline solve, replay it in a fresh
                    # interpreter, exit 3 on parity/rung mismatch
                    rc = replay_verify(rec, n_pods, n_types)
                sys.exit(rc)
    # every engine failed: still emit a parseable record (value null) with
    # the full diagnostic trail — never exit silent/nonzero without one
    print(
        json.dumps(
            {
                "metric": f"solve_wall_clock_{n_pods}pods_x_{n_types}types",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "detail": {"engine": "none", "attempts": attempts},
            }
        )
    )


if __name__ == "__main__":
    main()
