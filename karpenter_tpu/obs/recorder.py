"""Per-round flight recorder: trace ring buffer + Chrome trace dumps.

Keeps the last N completed round traces (:mod:`karpenter_tpu.obs.trace`)
in memory and writes a `Chrome trace-event
<chrome://tracing / Perfetto "trace event format">`_ JSON file for every
round that fired an anomaly trigger (or every round, under
``KARPENTER_TRACE_DUMP=1`` / ``dump_all``). The point is the *one bad
round*: when a bench regresses or a probe falls back in production, the
causal span tree of that exact round is already on disk — no repro run
needed.

Dump format: ``{"traceEvents": [...], "displayTimeUnit": "ms",
"otherData": {...}}``. Spans are complete events (``"ph": "X"``, ``ts``/
``dur`` in microseconds relative to the round start); anomalies are
global instant events (``"ph": "i"``, named ``anomaly:<kind>``); span
``kind`` rides the ``cat`` field so Perfetto can color host vs device vs
cache stages.

Disk writes never take down a reconcile loop: a failed dump logs a
WARNING (the stderr lastResort handler reaches it) and the round
continues. Each recorded trace dumps at most once per round — re-dumping
on demand (``dump(trace)``) reuses the path.

An anomalous round also serializes its pending **replay capsule** — the
round's most recent hot-path solve as a runnable artifact (exact tensor
inputs, outputs, engine/rung, env knobs) — next to the Chrome dump; see
:mod:`karpenter_tpu.obs.capsule` and deploy/README.md "Replay capsules"
(``python -m karpenter_tpu.obs replay <capsule>`` re-executes it
bit-identically offline, ``replay --ab`` races every eligible rung).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque

__all__ = ["FlightRecorder", "chrome_events"]


def chrome_events(trace) -> list:
    """The trace's span tree + anomaly marks as Chrome trace events."""
    base = trace.root.t0
    events = []
    for sp in trace.spans():
        ev = {
            "name": sp.name,
            "cat": sp.kind,
            "ph": "X",
            "ts": round((sp.t0 - base) * 1e6, 3),
            "dur": round((sp.dur or 0.0) * 1e6, 3),
            "pid": trace.pid,
            "tid": sp.tid,
        }
        if sp.attrs:
            ev["args"] = {k: _jsonable(v) for k, v in sp.attrs.items()}
        events.append(ev)
    for kind, attrs, at in trace.anomalies:
        ev = {
            "name": f"anomaly:{kind}",
            "cat": "anomaly",
            "ph": "i",
            "s": "g",
            "ts": round((at - base) * 1e6, 3),
            "pid": trace.pid,
            "tid": trace.root.tid,
        }
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        events.append(ev)
    return events


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


class FlightRecorder:
    """Ring buffer of the last N round traces + the anomaly dump policy."""

    def __init__(self, capacity: int = 32, dump_dir: str | None = None,
                 dump_all: bool = False):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self.dump_dir = dump_dir
        self.dump_all = dump_all

    def configure(self, dump_dir=None, capacity=None, dump_all=None):
        with self._lock:
            if dump_dir is not None:
                self.dump_dir = dump_dir
            if dump_all is not None:
                self.dump_all = dump_all
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(capacity, 1))

    # -- recording --------------------------------------------------------
    def record(self, trace):
        """Retain a completed round. Rounds that opened no child span and
        fired no anomaly are pure tracer overhead (idle ticks) and are
        skipped so they cannot churn real rounds out of the ring."""
        if not trace.root.children and not trace.anomalies:
            return
        with self._lock:
            self._ring.append(trace)
        if trace.anomalies or self.dump_all:
            self.dump(trace)
        # replay capsule (obs/capsule.py): an anomalous round's pending
        # solve capture serializes next to the Chrome dump written above
        # (KARPENTER_CAPSULE=1 forces it for every recorded round); the
        # writer never raises — a capsule failure must not fail the round
        from karpenter_tpu.obs import capsule as _capsule

        _capsule.maybe_write_round(trace, self.dump_dir)

    def traces(self) -> list:
        """Retained traces, oldest first."""
        with self._lock:
            return list(self._ring)

    def last(self, name: str | None = None):
        """Most recent retained trace (optionally of a given round name)."""
        with self._lock:
            for tr in reversed(self._ring):
                if name is None or tr.name == name:
                    return tr
        return None

    def clear(self):
        with self._lock:
            self._ring.clear()

    # -- dumping ----------------------------------------------------------
    def dump(self, trace, path: str | None = None) -> str | None:
        """Write one Chrome trace-event JSON file for ``trace``; returns
        the path (idempotent per trace unless an explicit path forces a
        re-write). Never raises: a dump failure must not fail the round
        that triggered it."""
        if path is None and trace.dump_path is not None:
            return trace.dump_path
        try:
            directory = self.dump_dir or "."
            if path is None:
                os.makedirs(directory, exist_ok=True)
                path = os.path.join(
                    directory, f"{trace.name}-{trace.trace_id}.trace.json"
                )
            doc = {
                "traceEvents": chrome_events(trace),
                "displayTimeUnit": "ms",
                "otherData": {
                    "trace_id": trace.trace_id,
                    "round": trace.name,
                    "wall_start": trace.wall_start,
                    "anomalies": [k for k, _, _ in trace.anomalies],
                    "dropped_spans": trace.dropped,
                    # the round's decision-ledger verdicts (obs/decisions):
                    # which rungs this round ran, right next to its spans
                    "decisions": [
                        {"site": s, "rung": r, "reason": why, "n": n}
                        for (s, r, why), n in sorted(
                            getattr(trace, "decisions", {}).items())
                    ],
                },
            }
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except OSError:
            import logging

            logging.getLogger(__name__).warning(
                "flight recorder failed to dump trace %s", trace.trace_id,
                exc_info=True)
            return None
        trace.dump_path = path
        if trace.registry is not None:
            from karpenter_tpu.operator import metrics as m

            trace.registry.counter(
                m.TRACE_DUMPS, "flight-recorder trace files written"
            ).inc(round=trace.name)
        return path
