"""``python -m karpenter_tpu.obs report`` — human rendering of the fleet
introspection surface.

Fetches the ``/introspect`` JSON (decision-ledger rung mixes, last-K round
rung summaries, the solve-quality series, per-tenant rung mixes, retained
anomalous rounds — obs/decisions.py) from a running metrics server
(``--url http://host:port``) or reads a saved snapshot (``--file``), and
with neither renders THIS process's ledger (useful from a REPL or a test).

    python -m karpenter_tpu.obs report --url http://127.0.0.1:8080
    python -m karpenter_tpu.obs report --file introspect.json
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["render_report", "main"]


def _fmt_mix(rungs: dict) -> str:
    parts = []
    for rung, reasons in sorted(rungs.items()):
        if isinstance(reasons, dict):
            n = sum(reasons.values())
            why = ",".join(
                f"{r}:{c}" for r, c in sorted(reasons.items()) if r != "ok"
            )
            parts.append(f"{rung}={n}" + (f" ({why})" if why else ""))
        else:
            parts.append(f"{rung}={reasons}")
    return "  ".join(parts)


def render_report(snapshot: dict) -> str:
    """The introspect JSON as a human-readable report (pure — the CLI
    smoke test feeds it a canned snapshot)."""
    lines = ["decision plane"]
    lines.append("=" * 64)
    sites = snapshot.get("sites") or {}
    if not sites:
        lines.append("  (no decisions recorded)")
    for site, srow in sorted(sites.items()):
        last = srow.get("last") or {}
        held = srow.get("held") or {}
        head = f"  {site:18s} last={last.get('rung', '-')}"
        if last.get("reason") and last.get("reason") != "ok":
            head += f"/{last['reason']}"
        if held:
            head += f"  held={held.get('rung')}x{held.get('streak')}"
        lines.append(head)
        lines.append(f"    {_fmt_mix(srow.get('rungs') or {})}")
    quality = snapshot.get("quality") or {}
    series = quality.get("series") or []
    if series:
        lines.append("")
        lines.append("solve quality (nodes / pods-cap floor)")
        for fam, st in sorted((quality.get("families") or {}).items()):
            flag = "  DRIFTING" if st.get("violating") else ""
            lines.append(
                f"  {fam:12s} baseline={st.get('baseline')} "
                f"streak={st.get('streak')}{flag}")
        tail = series[-5:]
        lines.append("  recent: " + "  ".join(
            f"{s.get('nodes')}/{s.get('floor')}={s.get('ratio')}"
            for s in tail))
    rounds = snapshot.get("rounds") or []
    if rounds:
        lines.append("")
        lines.append(f"last {len(rounds)} rounds")
        for r in rounds:
            mix = "; ".join(
                f"{site}:" + ",".join(
                    f"{rung}x{sum(reasons.values())}"
                    for rung, reasons in sorted(srow.items()))
                for site, srow in sorted((r.get("decisions") or {}).items())
            )
            lines.append(f"  {r.get('round')} [{r.get('trace_id')}]  {mix}")
    tenants = snapshot.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append("per-tenant rung mix")
        for tenant, mix in sorted(tenants.items()):
            row = "; ".join(
                f"{site}:" + _fmt_mix(rungs)
                for site, rungs in sorted(mix.items()))
            lines.append(f"  {tenant:16s} {row}")
    anomalies = snapshot.get("anomalies") or []
    if anomalies:
        lines.append("")
        lines.append("active anomalies (flight-recorder ring)")
        for a in anomalies:
            lines.append(
                f"  {a.get('round')} [{a.get('trace_id')}]  "
                f"{','.join(a.get('kinds') or [])}  "
                f"dump={a.get('dump') or '-'}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="karpenter_tpu.obs")
    sub = ap.add_subparsers(dest="cmd")
    rep = sub.add_parser(
        "report", help="render the /introspect decision-plane snapshot")
    rep.add_argument("--url", default=None,
                     help="metrics-server base URL (fetches <url>/introspect)")
    rep.add_argument("--file", default=None,
                     help="read a saved introspect JSON instead of fetching")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw JSON instead of the rendered report")
    rep.add_argument("-k", type=int, default=16,
                     help="rounds/anomalies to include (in-process source)")
    args = ap.parse_args(argv)
    if args.cmd != "report":
        ap.print_help()
        return 2
    if args.url:
        import urllib.request

        with urllib.request.urlopen(
            args.url.rstrip("/") + "/introspect", timeout=10
        ) as r:
            snapshot = json.loads(r.read().decode())
    elif args.file:
        with open(args.file) as f:
            snapshot = json.load(f)
    else:
        from karpenter_tpu.obs import decisions

        snapshot = decisions.introspect_snapshot(k=args.k)
    if args.json:
        print(json.dumps(snapshot, indent=2, sort_keys=True))
    else:
        print(render_report(snapshot))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
