"""``python -m karpenter_tpu.obs report|replay`` — the obs-plane CLI.

``report`` renders the ``/introspect`` JSON (decision-ledger rung mixes,
last-K round rung summaries, the solve-quality series, per-tenant rung
mixes, retained anomalous rounds, the replay-capsule index —
obs/decisions.py) from a running metrics server (``--url``), a saved
snapshot (``--file``), or THIS process's ledger.

``replay`` re-executes a captured hot-path solve offline (obs/capsule.py)
and asserts bit-parity against the capsule's recorded outputs; ``--ab``
additionally races the same capsule across every eligible rung
(partitioned / replicated / xla / native / host-FFD) and prints a
parity + nodes + wall-clock + decision table. Exit codes: 0 parity exact,
1 parity mismatch or replay failure — bench.py's ``--replay-verify`` leg
drives this in a fresh interpreter.

    python -m karpenter_tpu.obs report --url http://127.0.0.1:8080
    python -m karpenter_tpu.obs replay /tmp/karpenter-traces/x.capsule.npz
    python -m karpenter_tpu.obs replay x.capsule.npz --ab
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["render_report", "render_timeline", "render_ab", "run_replay",
           "main"]


def _fmt_mix(rungs: dict) -> str:
    parts = []
    for rung, reasons in sorted(rungs.items()):
        if isinstance(reasons, dict):
            n = sum(reasons.values())
            why = ",".join(
                f"{r}:{c}" for r, c in sorted(reasons.items()) if r != "ok"
            )
            parts.append(f"{rung}={n}" + (f" ({why})" if why else ""))
        else:
            parts.append(f"{rung}={reasons}")
    return "  ".join(parts)


def render_timeline(section: dict) -> str:
    """The fleet-ledger ``timeline`` section of the introspect JSON
    (obs/timeline.py) as a human-readable report (pure — the CLI smoke
    test feeds it a canned section)."""
    lines = ["fleet ledger"]
    lines.append("=" * 64)
    ring = section.get("ring") or {}
    kinds = ring.get("kinds") or {}
    mix = "  ".join(f"{k}={n}" for k, n in sorted(kinds.items()))
    lines.append(
        f"  ring {ring.get('size', 0)}/{ring.get('capacity', 0)} "
        f"(dropped={ring.get('dropped', 0)})" + (f"  {mix}" if mix else ""))
    for ev in section.get("events") or []:
        cause = ev.get("cause") or {}
        why = ""
        if cause:
            why = (f"  <- {cause.get('site', '-')}/{cause.get('rung', '-')}"
                   f"/{cause.get('reason', '-')}")
            if cause.get("command"):
                why += f" [{cause['command']}]"
        tid = f" [{ev['trace_id']}]" if ev.get("trace_id") else ""
        lines.append(f"  {ev.get('kind', '?'):9s} {ev.get('node')}{tid}{why}")
    cost = section.get("cost") or {}
    lines.append("")
    lines.append(
        f"realized cost: total={cost.get('realized_total', 0.0)} "
        f"live_rate={cost.get('live_rate', 0.0)} "
        f"({cost.get('live_nodes', 0)} nodes)")
    for key, amt in sorted((cost.get("realized") or {}).items()):
        lines.append(f"  {key:32s} {amt}")
    commands = section.get("commands") or {}
    reconciled = commands.get("reconciled") or []
    if reconciled or commands.get("pending"):
        lines.append("")
        lines.append(
            f"commands: pending={commands.get('pending', 0)} "
            f"reconciled={len(reconciled)}")
        for c in reconciled:
            verdict = ("within" if c.get("ok")
                       else "DRIFT" if c.get("ok") is False else "unpriced")
            lines.append(
                f"  {c.get('command')} {c.get('site') or '-'}"
                f"/{c.get('rung') or '-'}  predicted={c.get('predicted')} "
                f"realized={c.get('realized')}  {verdict}")
    interruptions = section.get("interruptions") or {}
    if interruptions:
        lines.append("")
        lines.append("observed interruption rates")
        for key, row in sorted(interruptions.items()):
            lines.append(
                f"  {key:24s} notices={row.get('notices', 0)} "
                f"reclaims={row.get('reclaims', 0)} "
                f"exposure_h={row.get('exposure_hours', 0.0)} "
                f"reclaims/h={row.get('reclaims_per_hour', 0.0)}")
    billing = section.get("billing") or {}
    tenants = billing.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append(
            f"tenant billing (total={billing.get('total_device_seconds')}s "
            f"devplane={billing.get('devplane_dispatch_seconds')}s "
            f"dropped={billing.get('dropped_device_seconds')}s)")
        for t, row in sorted(tenants.items()):
            lines.append(
                f"  {t:16s} {row.get('device_seconds', 0.0)}s over "
                f"{row.get('dispatches', 0)} dispatches")
    return "\n".join(lines)


def render_report(snapshot: dict, timeline: bool = False) -> str:
    """The introspect JSON as a human-readable report (pure — the CLI
    smoke test feeds it a canned snapshot). ``timeline`` appends the
    fleet-ledger section (``report --timeline``)."""
    lines = ["decision plane"]
    lines.append("=" * 64)
    sites = snapshot.get("sites") or {}
    if not sites:
        lines.append("  (no decisions recorded)")
    for site, srow in sorted(sites.items()):
        last = srow.get("last") or {}
        held = srow.get("held") or {}
        head = f"  {site:18s} last={last.get('rung', '-')}"
        if last.get("reason") and last.get("reason") != "ok":
            head += f"/{last['reason']}"
        if held:
            head += f"  held={held.get('rung')}x{held.get('streak')}"
        lines.append(head)
        lines.append(f"    {_fmt_mix(srow.get('rungs') or {})}")
    quality = snapshot.get("quality") or {}
    series = quality.get("series") or []
    if series:
        lines.append("")
        lines.append("solve quality (nodes / pods-cap floor)")
        for fam, st in sorted((quality.get("families") or {}).items()):
            flag = "  DRIFTING" if st.get("violating") else ""
            lines.append(
                f"  {fam:12s} baseline={st.get('baseline')} "
                f"streak={st.get('streak')}{flag}")
        tail = series[-5:]
        lines.append("  recent: " + "  ".join(
            f"{s.get('nodes')}/{s.get('floor')}={s.get('ratio')}"
            for s in tail))
    rounds = snapshot.get("rounds") or []
    if rounds:
        lines.append("")
        lines.append(f"last {len(rounds)} rounds")
        for r in rounds:
            mix = "; ".join(
                f"{site}:" + ",".join(
                    f"{rung}x{sum(reasons.values())}"
                    for rung, reasons in sorted(srow.items()))
                for site, srow in sorted((r.get("decisions") or {}).items())
            )
            lines.append(f"  {r.get('round')} [{r.get('trace_id')}]  {mix}")
    tenants = snapshot.get("tenants") or {}
    if tenants:
        lines.append("")
        lines.append("per-tenant rung mix")
        for tenant, mix in sorted(tenants.items()):
            row = "; ".join(
                f"{site}:" + _fmt_mix(rungs)
                for site, rungs in sorted(mix.items()))
            lines.append(f"  {tenant:16s} {row}")
    anomalies = snapshot.get("anomalies") or []
    if anomalies:
        lines.append("")
        lines.append("active anomalies (flight-recorder ring)")
        for a in anomalies:
            lines.append(
                f"  {a.get('round')} [{a.get('trace_id')}]  "
                f"{','.join(a.get('kinds') or [])}  "
                f"dump={a.get('dump') or '-'}"
                + (f"  capsule={a['capsule']}" if a.get("capsule") else ""))
    capsules = snapshot.get("capsules") or []
    if capsules:
        lines.append("")
        lines.append("replay capsules (python -m karpenter_tpu.obs replay)")
        for c in capsules:
            tenant = f" tenant={c['tenant']}" if c.get("tenant") else ""
            lines.append(
                f"  {c.get('round') or '-'} [{c.get('trace_id') or '-'}]  "
                f"seam={c.get('seam')} engine={c.get('engine')}{tenant}  "
                f"{c.get('why')}  {c.get('path')}")
    if timeline:
        lines.append("")
        lines.append(render_timeline(snapshot.get("timeline") or {}))
    return "\n".join(lines)


def render_ab(rows: list) -> str:
    """The ``replay --ab`` table: one line per rung — parity vs the
    captured outputs, nodes, wall clock, and whether the rung matches the
    one the capture actually ran (the decision diff)."""
    lines = [f"{'rung':12s} {'parity':8s} {'nodes':>7s} {'ms':>10s}  decision"]
    for r in rows:
        if not r.get("eligible", True):
            lines.append(f"{r['rung']:12s} {'-':8s} {'-':>7s} {'-':>10s}  "
                         f"ineligible: {r.get('why')}")
            continue
        decision = ("= captured rung" if r.get("rung_match")
                    else f"captured rung was {r.get('captured_rung')}")
        nodes = r.get("nodes")
        lines.append(
            f"{r['rung']:12s} {r.get('parity', '?'):8s} "
            f"{nodes if nodes is not None else '-':>7} "
            f"{r.get('ms', 0.0):>10.2f}  {decision}")
    return "\n".join(lines)


def run_replay(path: str, ab: bool = False, rung: str | None = None,
               as_json: bool = False) -> int:
    """The ``replay`` subcommand body (pure-ish: prints + returns the
    exit code, so tests drive it in-process)."""
    from karpenter_tpu.obs import capsule as _capsule

    try:
        cap = _capsule.load(path)
    except (OSError, ValueError) as e:
        print(f"replay: {e}", file=sys.stderr)
        return 1
    out: dict = {
        "capsule": path,
        "seam": cap.seam,
        "engine": cap.engine,
        "round": cap.meta.get("round"),
        "trace_id": cap.meta.get("trace_id"),
        "anomalies": cap.meta.get("anomalies") or [],
        "decisions": cap.meta.get("decisions") or [],
    }
    try:
        out["replay"] = _capsule.replay(cap, rung=rung)
    except Exception as e:
        print(f"replay: {type(e).__name__}: {e}", file=sys.stderr)
        out["replay"] = {"error": f"{type(e).__name__}: {e}"}
        if as_json:
            print(json.dumps(out))
        return 1
    if ab:
        out["ab"] = _capsule.ab_compare(cap)
    if as_json:
        print(json.dumps(out))
    else:
        r = out["replay"]
        print(f"capsule {path}")
        print(f"  seam={cap.seam} engine={cap.engine} "
              f"round={cap.meta.get('round')} "
              f"anomalies={','.join(out['anomalies']) or '-'}")
        print(f"  replay rung={r['rung']} parity={r['parity']} "
              f"nodes={r['nodes']} (captured {r['captured_nodes']}) "
              f"ms={r['ms']}")
        if ab:
            print()
            print(render_ab(out["ab"]))
    return 0 if out["replay"].get("parity") == "exact" else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="karpenter_tpu.obs")
    sub = ap.add_subparsers(dest="cmd")
    rep = sub.add_parser(
        "report", help="render the /introspect decision-plane snapshot")
    rep.add_argument("--url", default=None,
                     help="metrics-server base URL (fetches <url>/introspect)")
    rep.add_argument("--file", default=None,
                     help="read a saved introspect JSON instead of fetching")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw JSON instead of the rendered report")
    rep.add_argument("-k", type=int, default=16,
                     help="rounds/anomalies to include (in-process source)")
    rep.add_argument("--timeline", action="store_true",
                     help="append the fleet-ledger section (lifecycle "
                          "events with cause chains, realized cost, "
                          "command reconciliation, interruption rates, "
                          "tenant billing — obs/timeline.py)")
    rpl = sub.add_parser(
        "replay", help="re-execute a replay capsule offline (bit-parity "
                       "asserted against its captured outputs)")
    rpl.add_argument("capsule", help="path to a .capsule.npz file")
    rpl.add_argument("--ab", action="store_true",
                     help="also run the capsule across every eligible rung "
                          "and print the parity/nodes/wall-clock table")
    rpl.add_argument("--rung", default=None,
                     help="override the replay rung (partitioned/replicated/"
                          "xla/native/host; probe capsules: device/native)")
    rpl.add_argument("--json", action="store_true",
                     help="emit the result as one JSON line")
    args = ap.parse_args(argv)
    if args.cmd == "replay":
        return run_replay(args.capsule, ab=args.ab, rung=args.rung,
                          as_json=args.json)
    if args.cmd != "report":
        ap.print_help()
        return 2
    if args.url:
        import urllib.request

        with urllib.request.urlopen(
            args.url.rstrip("/") + "/introspect", timeout=10
        ) as r:
            snapshot = json.loads(r.read().decode())
    elif args.file:
        with open(args.file) as f:
            snapshot = json.load(f)
    else:
        from karpenter_tpu.obs import decisions

        snapshot = decisions.introspect_snapshot(k=args.k)
    if args.json:
        print(json.dumps(snapshot, indent=2, sort_keys=True))
    else:
        print(render_report(snapshot, timeline=args.timeline))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
