"""Anomaly replay capsules: capture any hot-path solve, replay it offline.

The flight recorder (obs/recorder.py) can show *where* a bad round spent
its time, the devplane *what it compiled*, and the decision ledger *which
rung it fell to* — but none of them can reproduce the round: a
rung-regression dump is a Chrome trace, not a runnable artifact. This
module closes that gap. Every hot-path dispatch seam records a **capture**
— the solver's exact tensorized inputs, its outputs, the engine/rung
route, and enough static parameters (max_bins / level_bits / max_minv /
shard count) to re-execute the dispatch — by REFERENCE onto the open
round's trace (``Trace.add_capture``). Anomaly-free rounds pay only that
reference (no copy, no serialization — the same ≤2% stance as the tracer,
pinned by the slow overhead test in tests/test_capsule.py). When a round
closes **anomalous** (any recorder trigger: rung-regression,
solve-overhead-drift, snapshot-rebuild, probe-fallback, host-routed,
cold-compile-in-steady-state, …) — or always, under ``KARPENTER_CAPSULE=1``
— the pending capture serializes to ONE schema-versioned ``.capsule.npz``
file next to the round's Chrome dump, carrying the env-knob snapshot
(:func:`karpenter_tpu.utils.envknobs.snapshot`), the shape-family key, and
the round's decision-ledger verdicts.

Capture seams (each one host-side hook per dispatch; graftlint's GL405
rule proves them jit-unreachable):

- ``solver.invoke`` — models/solver.py ``TPUSolver._run_and_decode``
  (xla / native / remote engines; the mesh rung defers to the seam below).
- ``mesh.solve`` — parallel/mesh.py ``sharded_solve_host`` (partitioned /
  replicated / unsharded rungs, with the shard count).
- ``probe.dispatch`` — ops/consolidate.py ``DisruptionSnapshot.dispatch`` (the batched
  counterfactual rows, their zeroed-column sets, and the master
  existing-node tensor).
- ``global.dispatch`` — the SAME dispatch when the global consolidation
  mode runs it as one joint ladder over every candidate
  (ops/consolidate.py ``joint_retirement_plan``): identical tensor
  layout, so an anomalous joint round replays through the identical
  chunked program and the A/B table races its device/native pair.
- ``interruption.dispatch`` — the SAME dispatch again when the
  ``InterruptionDrain`` method probes whether the survivors absorb a
  noticed node's pods before its reclaim deadline
  (controllers/disruption/methods.py): identical row layout, so a
  storm round's replacement solve replays offline after the storm.
- ``service.solve`` — service/solver_service.py (tenant-scoped: the
  capsule carries and is filed under the tenant).
- ``relax.dispatch`` — ops/relax.py ``joint_relax_plan`` (the LP
  relaxation rung, deploy/README.md "LP relaxation rung"): the padded
  LP tensors plus the STANDARD counterfactual-row sidecars on one
  capture, so its A/B ladder races the device LP+window decision
  (``relax``), the FFD prefix ladder over the same rows (``ladder``),
  and the host greedy oracle (``host``) — all graded on the retirement
  prefix each would pick.

Replay (``python -m karpenter_tpu.obs replay <capsule>``) re-executes the
capture offline and asserts bit-parity against the captured outputs:
xla/service captures re-run the same jitted packed kernel, native captures
the C++ engine, probe captures the same chunked vmapped dispatch
(ops/consolidate.py ``dispatch_counterfactual_rows`` — shared code, not a
re-implementation), and mesh captures replay through
``partitioned_reference`` — the sequential one-device oracle that is
bit-identical to the multi-device execution by the partitioned-mesh
contract, which is exactly what makes "capture on real ICI hardware,
replay on the dev box" work. ``replay --ab`` additionally runs the same
capsule across every *eligible* rung — partitioned / replicated / xla /
native / host-FFD — and reports a parity + nodes + wall-clock + decision
table (parity grades: ``exact`` bit-equal, ``placed`` same per-group
placement totals and node count on a different bin axis, ``differs``).

Size budget: a capture whose arrays exceed ``KARPENTER_CAPSULE_BYTES``
(default 256 MiB) is skipped, counted on
``karpenter_capsule_skipped_total{reason="bytes"}``, and logged — a 500k
burst must not wedge the reconcile loop on disk I/O. Written capsules
count on ``karpenter_capsule_writes_total{seam,why}`` and join the
in-process index served by ``/introspect`` and rendered by
``python -m karpenter_tpu.obs report``. See deploy/README.md
("Replay capsules").

Seam coverage is a static contract, not a convention: graftlint's GL503
(analysis/contracts.py) flags any function that dispatches through the
shared device primitives without a reachable ``record_capture``, and
validates literal seam names against ``SEAMS`` — so a new dispatch path
cannot silently opt out of replay, and a typo'd seam name fails the
tier-1 gate (rule table: deploy/README.md § Static analysis). This
module itself is exempt (the replay half re-executes dispatches and must
not capture its own replays).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

import numpy as np

from karpenter_tpu.utils import envknobs

__all__ = [
    "SCHEMA_VERSION",
    "SEAMS",
    "Capsule",
    "ReplayError",
    "record_capture",
    "write_capsule",
    "maybe_write_round",
    "last_capture",
    "load",
    "replay",
    "ab_compare",
    "parity_of",
    "index",
    "capture_enabled",
    "force_all",
    "byte_budget",
    "STATS",
    "reset",
]

SCHEMA_VERSION = 1
META_KEY = "__capsule__"
IN_PREFIX = "in//"
OUT_PREFIX = "out//"
# replay-only sidecar arrays (probe counterfactual rows etc.) that are not
# kernel args; the prefix keeps them from colliding with snapshot names
CF_PREFIX = "cf//"

SEAMS = ("solver.invoke", "mesh.solve", "probe.dispatch", "service.solve",
         "preempt.dispatch", "global.dispatch", "interruption.dispatch",
         "relax.dispatch")

# knobs from the captured env snapshot that replay re-applies around the
# mesh rungs: they decide whether/how the snapshot partitions, so a dev
# box with different settings must still reproduce the captured plan
_REPLAY_ENV = ("KARPENTER_SHARD_PARTITION", "KARPENTER_SHARD_REPAIR_MAX")

_LOCK = threading.Lock()
_TLS = threading.local()
_INDEX: deque = deque(maxlen=64)
STATS = {"captures": 0, "writes": 0, "skipped_bytes": 0}


class ReplayError(RuntimeError):
    """A capsule cannot be replayed here (engine unavailable, snapshot no
    longer partitions, schema unknown)."""


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def capture_enabled() -> bool:
    """KARPENTER_CAPSULE=0 disables capture entirely; anything else (incl.
    unset) keeps the cheap reference-capture on — writes still gate on an
    anomaly unless :func:`force_all`."""
    return envknobs.env_bool("KARPENTER_CAPSULE", True)


def force_all() -> bool:
    """KARPENTER_CAPSULE=1: write a capsule for every recorded round, not
    only anomalous ones (the opt-in knob)."""
    return (envknobs.env_str("KARPENTER_CAPSULE", "") or "").strip().lower() in (
        "1", "true", "on", "yes", "all",
    )


def byte_budget() -> int:
    """KARPENTER_CAPSULE_BYTES: array-byte cap per capsule (0 = uncapped)."""
    return envknobs.env_int("KARPENTER_CAPSULE_BYTES", 256 << 20, minimum=0)


# ---------------------------------------------------------------------------
# capture (the host-side hook — GL405 proves it jit-unreachable)
# ---------------------------------------------------------------------------


def record_capture(seam: str, inputs: dict, outputs: dict,
                   tenant: str | None = None, **meta):
    """One dispatch's replay record, attached by reference to the open
    round trace (and kept as this thread's ``last_capture``). ``inputs``
    and ``outputs`` are host numpy dicts at every call site; only the
    DICTS are copied here — the arrays are shared, so the hook costs one
    small dict build per dispatch."""
    if seam not in SEAMS:
        raise ValueError(f"unknown capture seam {seam!r}")
    if not capture_enabled():
        return None
    rec = {
        "seam": seam,
        "tenant": tenant,
        "meta": dict(meta),
        "inputs": dict(inputs),
        "outputs": dict(outputs),
        "at": time.time(),
    }
    with _LOCK:
        STATS["captures"] += 1
    _TLS.last = rec
    from karpenter_tpu.obs import trace as _trace

    tr = _trace.TRACER.current_trace()
    if tr is not None:
        tr.add_capture(rec)
    return rec


def last_capture():
    """This thread's most recent capture record (bench --replay-verify's
    capture child writes it explicitly)."""
    return getattr(_TLS, "last", None)


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


def _array_bytes(rec: dict) -> int:
    return int(sum(np.asarray(v).nbytes
                   for d in (rec["inputs"], rec["outputs"])
                   for v in d.values()))


def write_capsule(rec: dict, directory: str | None = None, trace=None,
                  path: str | None = None, why: str = "anomaly",
                  registry=None) -> str | None:
    """Serialize one capture record to a ``.capsule.npz`` file. Returns
    the path, or None when the size budget refused it or the write failed
    (a capsule failure must never fail the round that triggered it)."""
    nbytes = _array_bytes(rec)
    budget = byte_budget()
    if budget and nbytes > budget:
        with _LOCK:
            STATS["skipped_bytes"] += 1
        _count(registry, trace, skipped=True, seam=rec["seam"],
               reason="bytes")
        import logging

        logging.getLogger(__name__).warning(
            "replay capsule skipped: %d array bytes exceed "
            "KARPENTER_CAPSULE_BYTES=%d (seam %s)", nbytes, budget,
            rec["seam"])
        return None
    meta = {
        "schema": SCHEMA_VERSION,
        "seam": rec["seam"],
        "tenant": rec["tenant"],
        "meta": _jsonable_dict(rec["meta"]),
        "env": envknobs.snapshot(),
        "at": rec["at"],
        "nbytes": nbytes,
        "why": why,
    }
    if trace is not None:
        meta.update(
            round=trace.name,
            trace_id=trace.trace_id,
            anomalies=[k for k, _, _ in trace.anomalies],
            decisions=[
                {"site": s, "rung": r, "reason": why_, "n": n}
                for (s, r, why_), n in sorted(
                    getattr(trace, "decisions", {}).items())
            ],
            dump=trace.dump_path,
        )
    try:
        if path is None:
            directory = directory or "."
            os.makedirs(directory, exist_ok=True)
            tenant_tag = f"-{rec['tenant']}" if rec.get("tenant") else ""
            stem = (f"{meta.get('round', 'capsule')}{tenant_tag}-"
                    f"{meta.get('trace_id') or format(os.getpid(), 'x')}")
            path = os.path.join(directory, f"{stem}.capsule.npz")
        payload: dict = {}
        for k, v in rec["inputs"].items():
            payload[IN_PREFIX + k] = np.asarray(v)
        for k, v in rec["outputs"].items():
            payload[OUT_PREFIX + k] = np.asarray(v)
        payload[META_KEY] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)
        with open(path, "wb") as f:
            np.savez(f, **payload)
    except OSError:
        import logging

        logging.getLogger(__name__).warning(
            "replay capsule write failed (seam %s)", rec["seam"],
            exc_info=True)
        return None
    entry = {
        "path": path,
        "seam": rec["seam"],
        "tenant": rec.get("tenant"),
        "round": meta.get("round"),
        "trace_id": meta.get("trace_id"),
        "engine": rec["meta"].get("engine"),
        "anomalies": meta.get("anomalies") or [],
        "nbytes": nbytes,
        "at": rec["at"],
        "why": why,
    }
    with _LOCK:
        STATS["writes"] += 1
        _INDEX.append(entry)
    _count(registry, trace, skipped=False, seam=rec["seam"], reason=why)
    return path


def _jsonable_dict(d: dict) -> dict:
    out = {}
    for k, v in d.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        elif isinstance(v, (list, tuple)):
            out[k] = [x if isinstance(x, (str, int, float, bool)) else str(x)
                      for x in v]
        else:
            out[k] = str(v)
    return out


def _count(registry, trace, skipped: bool, seam: str, reason: str):
    reg = registry
    if reg is None and trace is not None:
        reg = trace.registry
    if reg is None:
        return
    from karpenter_tpu.operator import metrics as m

    if skipped:
        reg.counter(
            m.CAPSULE_SKIPPED,
            "replay captures refused by the KARPENTER_CAPSULE_BYTES budget",
        ).inc(seam=seam, reason=reason)
    else:
        reg.counter(
            m.CAPSULE_WRITES, "replay capsule files written",
        ).inc(seam=seam, why=reason)


def maybe_write_round(trace, directory: str | None) -> str | None:
    """The flight recorder's hook: serialize the round's pending capture
    when the round is anomalous (or KARPENTER_CAPSULE=1 forces it).
    Idempotent per trace — a re-recorded round reuses its path. A round
    that writes NOTHING drops its pending reference here: the anomaly
    decision is final at record time, and the recorder ring retains up to
    32 rounds — pinning every clean round's full tensor set (tens of MB
    at 50k scale) purely for observability would be a slow leak. The
    thread's ``last_capture`` slot still holds the most recent one."""
    rec = getattr(trace, "capsule_pending", None)
    if rec is None:
        return None
    if trace.capsule_path is not None:
        return trace.capsule_path
    if trace.anomalies:
        why = "anomaly"
    elif force_all():
        why = "forced"
    else:
        trace.capsule_pending = None
        return None
    path = write_capsule(rec, directory, trace=trace, why=why)
    if path is not None:
        trace.capsule_path = path
        trace.capsule_pending = None  # on disk now; don't pin the arrays
    return path


def index(k: int | None = None) -> list:
    """The in-process capsule index (newest last) — joined into
    ``/introspect`` and ``obs report``."""
    with _LOCK:
        out = list(_INDEX)
    return out[-k:] if k else out


def reset():
    """Test isolation: clear the index/stats and this thread's capture."""
    with _LOCK:
        _INDEX.clear()
        STATS.update(captures=0, writes=0, skipped_bytes=0)
    _TLS.last = None


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


class Capsule:
    """One loaded capsule: ``meta`` (the JSON header), ``inputs`` and
    ``outputs`` (host numpy dicts), and the source ``path``."""

    def __init__(self, meta: dict, inputs: dict, outputs: dict,
                 path: str | None = None):
        self.meta = meta
        self.inputs = inputs
        self.outputs = outputs
        self.path = path

    @property
    def seam(self) -> str:
        return self.meta.get("seam", "")

    @property
    def engine(self) -> str:
        return (self.meta.get("meta") or {}).get("engine", "")

    def args(self) -> dict:
        """The kernel-arg dict (replay-only ``cf//`` sidecars stripped)."""
        return {k: np.asarray(v) for k, v in self.inputs.items()
                if not k.startswith(CF_PREFIX)}

    def sidecar(self, name: str):
        return self.inputs.get(CF_PREFIX + name)

    def static(self, name: str, default=None):
        return (self.meta.get("meta") or {}).get(name, default)


def load(path: str) -> Capsule:
    """Load + schema-check a capsule file. Forward versions are rejected
    (a capsule written by a NEWER build may carry fields this replayer
    would silently misinterpret — refusing is the only bit-safe answer)."""
    with np.load(path, allow_pickle=False) as z:
        if META_KEY not in z.files:
            raise ValueError(f"{path}: not a replay capsule (no {META_KEY})")
        meta = json.loads(bytes(z[META_KEY]).decode())
        schema = int(meta.get("schema", -1))
        if schema < 1:
            raise ValueError(f"{path}: malformed capsule schema {schema}")
        if schema > SCHEMA_VERSION:
            raise ValueError(
                f"{path}: capsule schema {schema} is newer than this "
                f"build's {SCHEMA_VERSION} — replay with a matching build")
        inputs = {k[len(IN_PREFIX):]: z[k]
                  for k in z.files if k.startswith(IN_PREFIX)}
        outputs = {k[len(OUT_PREFIX):]: z[k]
                   for k in z.files if k.startswith(OUT_PREFIX)}
    return Capsule(meta, inputs, outputs, path)


# ---------------------------------------------------------------------------
# replay engines
# ---------------------------------------------------------------------------

_OUT_KEYS = ("assign", "assign_e", "used", "tmpl", "F")


class _applied_env(envknobs.applied_env):
    """Temporarily apply the capture-time values of selected env knobs
    (mesh partition/repair) so replay reproduces the captured plan — the
    save/apply/restore machinery lives with the other env-knob semantics
    in utils/envknobs.py (the one module allowed to touch os.environ)."""

    def __init__(self, cap: Capsule, names=_REPLAY_ENV):
        super().__init__(cap.meta.get("env") or {}, names)


# seams whose capture is the chunked counterfactual-row dispatch (shared
# replay body `_run_probe`): the per-candidate probe, the preemption
# counterfactual, the global joint consolidation ladder, and the
# interruption-drain absorb probe
_ROW_SEAMS = ("probe.dispatch", "preempt.dispatch", "global.dispatch",
              "interruption.dispatch")


def _captured_rung(cap: Capsule) -> str:
    """The replayable rung the capture actually ran."""
    engine = cap.engine
    if cap.seam == "relax.dispatch":
        return "relax"
    if cap.seam in _ROW_SEAMS:
        return "native" if engine == "native" else "device"
    if cap.seam == "mesh.solve":
        return {"partitioned": "partitioned",
                "replicated": "replicated"}.get(engine, "xla")
    return {"native": "native"}.get(engine, "xla")


def _consume(out: dict) -> dict:
    """A lazy kernel output dict → the host 5-key dict."""
    import jax

    return {k: np.asarray(v) for k, v in jax.device_get(
        {k: out[k] for k in _OUT_KEYS if k in out}).items()}


def _run_xla(cap: Capsule) -> dict:
    args = cap.args()
    max_bins = int(cap.static("max_bins"))
    level_bits = int(cap.static("level_bits", 20))
    max_minv = int(cap.static("max_minv", 0))
    if cap.seam == "mesh.solve":
        # the mesh seam dispatched the raw solve_step executable — replay
        # the SAME jitted wrapper so the compiled program is identical
        # (its unsharded rung reads max_minv off the args, like the
        # degenerate-mesh dispatch did)
        from karpenter_tpu.parallel.mesh import _jitted_solve_step

        max_minv = (int(np.asarray(args["m_minv"]).max())
                    if "m_minv" in args else 0)
        out = _jitted_solve_step(max_bins, max_minv, level_bits)(args)
        return _consume(out)
    from karpenter_tpu.models.solver import TPUSolver, _packed_kernel

    pallas = bool(cap.static("pallas", False))
    fn = _packed_kernel(max_bins, pallas, level_bits=level_bits,
                        max_minv=max_minv)
    flat = np.asarray(fn(args))
    return TPUSolver._unpack(flat, args, max_bins)


def _run_native(cap: Capsule) -> dict:
    from karpenter_tpu import native

    if not native.available():
        raise ReplayError("native engine unavailable on this host")
    return native.solve_step(cap.args(), int(cap.static("max_bins")))


def _replay_n_shards(cap: Capsule) -> int:
    n = cap.static("n_shards")
    if n:
        return int(n)
    try:
        import jax

        return max(len(jax.devices()), 2)
    except Exception:
        return 2


def _run_partitioned(cap: Capsule) -> dict:
    from karpenter_tpu.parallel.mesh import partitioned_reference

    with _applied_env(cap):
        merged = partitioned_reference(
            cap.args(), int(cap.static("max_bins")), _replay_n_shards(cap),
            level_bits=int(cap.static("level_bits", 20)))
    if merged is None:
        raise ReplayError(
            "snapshot does not partition here (plan refused or repair "
            "overflow) — the partitioned rung is ineligible")
    return {k: np.asarray(v) for k, v in merged.items() if k in _OUT_KEYS}


def _run_replicated(cap: Capsule) -> dict:
    """The replicated rung offline: over a real >1-device mesh when one is
    attached, else the plain unsharded kernel — bit-identical by the
    replicated program's contract (parallel/mesh.py)."""
    import jax

    args = cap.args()
    max_bins = int(cap.static("max_bins"))
    level_bits = int(cap.static("level_bits", 20))
    if len(jax.devices()) > 1:
        from karpenter_tpu.parallel.mesh import _replicated_solve, make_mesh

        out = _replicated_solve(make_mesh(), args, max_bins,
                                level_bits=level_bits)
        return _consume(out)
    from karpenter_tpu.parallel.mesh import _jitted_solve_step

    max_minv = (int(np.asarray(args["m_minv"]).max())
                if "m_minv" in args else 0)
    return _consume(_jitted_solve_step(max_bins, max_minv, level_bits)(args))


def _run_probe(cap: Capsule, engine: str) -> dict:
    from karpenter_tpu.ops import consolidate as _cons

    shared = cap.args()
    g_count_k = np.asarray(cap.sidecar("g_count_rows"))
    e_avail = np.asarray(cap.sidecar("e_avail"))
    idx = np.asarray(cap.sidecar("e_zero_idx"))
    lens = np.asarray(cap.sidecar("e_zero_len"))
    e_zero_cols: list = []
    off = 0
    for n in lens.tolist():
        if n < 0:
            e_zero_cols.append(None)
        else:
            e_zero_cols.append(idx[off:off + n])
            off += n
    Gp = int(cap.static("Gp"))
    Ep = int(cap.static("Ep"))
    max_minv = int(cap.static("max_minv", 0))
    e_free = None
    if cap.seam == "preempt.dispatch":
        # the preemption counterfactual's per-row capacity releases:
        # (col, delta[R]) pairs flattened into two sidecars, -1 = None
        cols = np.asarray(cap.sidecar("e_free_col"))
        deltas = np.asarray(cap.sidecar("e_free_delta"))
        e_free = [
            None if int(c) < 0 else (int(c), deltas[i])
            for i, c in enumerate(cols.tolist())
        ]
    if engine == "native":
        from karpenter_tpu import native

        if not native.available():
            raise ReplayError("native engine unavailable on this host")
        placed_g, used = _cons.dispatch_counterfactual_rows_native(
            shared, Gp, Ep, e_avail, max_minv, g_count_k, e_zero_cols,
            e_free=e_free)
    else:
        placed_g, used = _cons.dispatch_counterfactual_rows(
            shared, Gp, Ep, e_avail, max_minv, g_count_k, e_zero_cols,
            e_free=e_free)
    return {"placed_g": placed_g, "used": used}


# ---------------------------------------------------------------------------
# the relax.dispatch seam's A/B ladder (ops/relax.py — deploy/README.md
# "LP relaxation rung"): the LP+window device decision, the FFD prefix
# ladder over the SAME counterfactual-row sidecars, the host-FFD greedy
# oracle. All three rungs emit {"k_sel"} — the retirement prefix each
# would pick — so parity_of grades them against the captured device
# selection directly.
# ---------------------------------------------------------------------------


def _run_relax(cap: Capsule) -> dict:
    from karpenter_tpu.ops.relax import replay_joint

    return replay_joint(cap)


def _run_relax_ladder(cap: Capsule) -> dict:
    """The FFD prefix ladder's verdict on the captured round: dispatch
    the counterfactual rows (``_run_probe`` verbatim — the capture keeps
    the standard row sidecars alongside the LP tensors), then apply the
    shared prefix criterion (coverage + the price gate bits the capture
    pinned) and report the LARGEST feasible prefix."""
    out = _run_probe(cap, "device")
    placed_g = np.asarray(out["placed_g"])
    used = np.asarray(out["used"])
    required = np.asarray(cap.sidecar("rx_required"))
    gate = np.asarray(cap.sidecar("rx_claim_gate")).astype(bool)
    G = int(cap.static("rx_g"))
    feasible = (placed_g[:, :G] >= required[:, :G]).all(axis=1)
    feasible &= (np.asarray(used).reshape(-1) == 0) | gate
    ks = np.flatnonzero(feasible) + 1  # row i <-> prefix k=i+1
    ks = ks[ks >= 2]
    return {"k_sel": np.int64(ks.max()) if ks.size else np.int64(0)}


def _run_relax_host(cap: Capsule) -> dict:
    from karpenter_tpu.ops.relax import replay_host_round

    return replay_host_round(cap)


# ---------------------------------------------------------------------------
# the host-FFD reference (the A/B ladder's bottom rung)
# ---------------------------------------------------------------------------


def _host_feasibility(args: dict) -> np.ndarray:
    """[G,T] bool — numpy mirror of the kernel's group-vs-type feasibility
    (requirement overlap with the Intersects tolerance rule, plus one
    offering jointly satisfying availability + the group's zone/ct allowed
    sets). Chunked over G so a 1024x1024 snapshot stays tens of MB."""
    g_mask = np.asarray(args["g_mask"])
    g_has = np.asarray(args["g_has"])
    g_tol = np.asarray(args.get("g_tol", np.zeros_like(g_has)))
    t_mask = np.asarray(args["t_mask"])
    t_has = np.asarray(args["t_has"])
    t_tol = np.asarray(args.get("t_tol", np.zeros_like(t_has)))
    off_zone = np.asarray(args["off_zone"])
    off_ct = np.asarray(args["off_ct"])
    off_avail = np.asarray(args["off_avail"]).astype(bool)
    gz = np.asarray(args["g_zone_allowed"]).astype(bool)
    gc = np.asarray(args["g_ct_allowed"]).astype(bool)
    G, T = g_mask.shape[0], t_mask.shape[0]
    F = np.zeros((G, T), dtype=bool)
    for lo in range(0, G, 64):
        hi = min(lo + 64, G)
        shared = g_has[lo:hi, None, :] & t_has[None, :, :]
        ov = ((g_mask[lo:hi, None] & t_mask[None, :]) != 0).any(axis=3)
        both = g_tol[lo:hi, None, :] & t_tol[None, :, :]
        req_ok = (~shared | ov | both).all(axis=2)  # [g,T]
        # offerings: any offering available ∧ zone/ct inside the group's
        # allowed sets (-1 = the offering leaves that label undefined)
        z_ok = _off_label_ok(gz[lo:hi], off_zone)
        c_ok = _off_label_ok(gc[lo:hi], off_ct)
        off_ok = (off_avail[None] & z_ok & c_ok).any(axis=2)
        F[lo:hi] = req_ok & off_ok
    return F


def _off_label_ok(allowed: np.ndarray, off_idx: np.ndarray) -> np.ndarray:
    """[g, T, O] bool: per-offering label admissibility — allowed[g, idx]
    where idx >= 0, True where the offering leaves the label undefined."""
    if allowed.shape[1] == 0:
        return np.ones((allowed.shape[0],) + off_idx.shape, dtype=bool)
    idx = np.clip(off_idx, 0, allowed.shape[1] - 1)
    ok = allowed[:, idx]  # [g, T, O]
    return np.where(off_idx[None] >= 0, ok, True)


def _run_host_ffd(cap: Capsule) -> dict:
    """Pure-numpy first-fit-decreasing over the capsule's tensors: the
    reference algorithm's stance (groups in FFD order, each pod lands on
    the first open bin with a surviving compatible type, new bins open
    from the weight-best template). Informational — the A/B table's
    oracle row; identical-pod groups place in batches exactly like the
    mesh repair pass, so the math mirrors ``_repair_merged``."""
    from karpenter_tpu.parallel.mesh import (
        _EPS,
        _partition_blockers,
        _tmpl_full_rows,
    )

    args = cap.args()
    blocker = _partition_blockers(args)
    if blocker is not None:
        raise ReplayError(f"host-FFD rung ineligible: {blocker}")
    g_count = np.asarray(args["g_count"]).astype(np.int64)
    g_demand = np.asarray(args["g_demand"], dtype=np.float32)
    t_alloc = np.asarray(args["t_alloc"], dtype=np.float32)
    t_tmpl = np.asarray(args["t_tmpl"])
    m_overhead = np.asarray(args["m_overhead"], dtype=np.float32)
    bin_cap = np.asarray(args["g_bin_cap"]) if "g_bin_cap" in args else None
    F = _host_feasibility(args)
    G, T = F.shape
    M = m_overhead.shape[0]
    assign_cols: list = []  # per-bin [G] int32 columns
    loads: list = []
    tmpls: list = []
    typesets: list = []
    for g in range(G):
        n = int(g_count[g])
        if n <= 0:
            continue
        d = g_demand[g]
        pos = d > 0
        if not pos.any():
            continue
        tf = _tmpl_full_rows(args, g)
        for b in range(len(assign_cols)):
            if n <= 0:
                break
            tok = typesets[b] & F[g]
            if not tok.any():
                continue
            adp = t_alloc[:, pos] / d[pos]
            ldp = loads[b][pos] / d[pos]
            room_t = np.floor((adp - ldp[None, :]).min(axis=1)
                              + _EPS).astype(np.int64)
            room_t = np.where(tok, np.maximum(room_t, 0), 0)
            room = int(room_t.max())
            if bin_cap is not None:
                room = min(room, int(bin_cap[g]) - int(assign_cols[b][g]))
            take = min(n, room)
            if take <= 0:
                continue
            assign_cols[b][g] += take
            loads[b] = loads[b] + take * d
            typesets[b] = tok & (room_t >= take)
            n -= take
        while n > 0:
            opened = False
            for m in range(M):
                if not tf[m]:
                    continue
                ovh_ok = (m_overhead[m][None, :] <= t_alloc + _EPS).all(axis=1)
                fresh = t_alloc - m_overhead[m][None, :]
                fr = np.floor((fresh[:, pos] / d[pos]).min(axis=1)
                              + _EPS).astype(np.int64)
                ok_t = F[g] & (t_tmpl == m) & ovh_ok & (fr > 0)
                if not ok_t.any():
                    continue
                per_node = int(fr[ok_t].max())
                if bin_cap is not None:
                    per_node = min(per_node, int(bin_cap[g]))
                if per_node <= 0:
                    continue
                take = min(n, per_node)
                col = np.zeros(G, dtype=np.int32)
                col[g] = take
                assign_cols.append(col)
                loads.append(m_overhead[m] + take * d)
                tmpls.append(m)
                typesets.append(ok_t & (fr >= take))
                n -= take
                opened = True
                break
            if not opened:
                break  # unplaceable remainder — reported via placed totals
    B = max(len(assign_cols), 1)
    assign = (np.stack(assign_cols, axis=1) if assign_cols
              else np.zeros((G, B), dtype=np.int32))
    return {
        "assign": assign,
        "assign_e": np.zeros((G, 1), dtype=np.int32),
        "used": np.arange(assign.shape[1]) < len(assign_cols),
        "tmpl": np.asarray(tmpls + [0] * (B - len(tmpls)), dtype=np.int32),
        "F": F,
    }


# ---------------------------------------------------------------------------
# replay + A/B
# ---------------------------------------------------------------------------

_SOLVE_RUNGS = ("partitioned", "replicated", "xla", "native", "host")
_PROBE_RUNGS = ("device", "native")
_RELAX_RUNGS = ("relax", "ladder", "host")


def _execute(cap: Capsule, rung: str) -> dict:
    if cap.seam == "relax.dispatch":
        return {
            "relax": _run_relax,
            "ladder": _run_relax_ladder,
            "host": _run_relax_host,
        }[rung](cap)
    if cap.seam in _ROW_SEAMS:
        return _run_probe(cap, rung)
    return {
        "partitioned": _run_partitioned,
        "replicated": _run_replicated,
        "xla": _run_xla,
        "native": _run_native,
        "host": _run_host_ffd,
    }[rung](cap)


def parity_of(captured: dict, out: dict) -> str:
    """Bit-parity grade of a replay against the captured outputs:
    ``exact`` (every shared key bit-equal), ``placed`` (different bin
    axis, but per-group placement totals and used-bin count agree — the
    end-state equivalence the A/B ladder compares), ``differs``."""
    keys = [k for k in captured if k in out]
    if not keys:
        return "differs"
    exact = True
    for k in keys:
        a, b = np.asarray(captured[k]), np.asarray(out[k])
        if a.shape != b.shape or not np.array_equal(a, b):
            exact = False
            break
    if exact:
        return "exact"
    if "placed_g" in captured:  # probe captures have no placement fallback
        return "differs"
    try:
        pa = np.asarray(captured["assign"]).sum(axis=1)
        pb = np.asarray(out["assign"]).sum(axis=1)
        if "assign_e" in captured and "assign_e" in out:
            pa = pa + np.asarray(captured["assign_e"]).sum(axis=1)
            pb = pb + np.asarray(out["assign_e"]).sum(axis=1)
        ua = int(np.asarray(captured["used"]).sum())
        ub = int(np.asarray(out["used"]).sum())
        if pa.shape == pb.shape and np.array_equal(pa, pb) and ua == ub:
            return "placed"
    except (KeyError, ValueError):
        pass
    return "differs"


def _nodes_of(out: dict) -> int | None:
    if "used" in out:
        return int(np.asarray(out["used"]).sum())
    return None


def replay(cap: Capsule, rung: str | None = None) -> dict:
    """Re-execute the capture (on its own rung unless overridden) and
    grade the result against the captured outputs. Returns
    ``{rung, parity, ms, nodes, captured_rung, rung_match}``."""
    want = rung or _captured_rung(cap)
    t0 = time.perf_counter()
    out = _execute(cap, want)
    ms = (time.perf_counter() - t0) * 1000.0
    return {
        "rung": want,
        "captured_rung": _captured_rung(cap),
        "rung_match": want == _captured_rung(cap),
        "parity": parity_of(cap.outputs, out),
        "ms": round(ms, 2),
        "nodes": _nodes_of(out),
        "captured_nodes": _nodes_of(cap.outputs),
    }


def ab_compare(cap: Capsule) -> list:
    """Run the capsule across every eligible rung; one row per rung with
    parity vs the captured outputs, node count, wall clock, and the
    decision diff vs the captured rung. Ineligible/failed rungs report
    why instead of silently vanishing (the no-silent-caps stance)."""
    if cap.seam == "relax.dispatch":
        rungs: tuple = _RELAX_RUNGS
    elif cap.seam in _ROW_SEAMS:
        rungs = _PROBE_RUNGS
    else:
        rungs = _SOLVE_RUNGS
    rows = []
    for rung in rungs:
        try:
            t0 = time.perf_counter()
            out = _execute(cap, rung)
            ms = (time.perf_counter() - t0) * 1000.0
        except ReplayError as e:
            rows.append({"rung": rung, "eligible": False, "why": str(e)})
            continue
        except Exception as e:  # a rung crashing must not kill the table
            rows.append({"rung": rung, "eligible": False,
                         "why": f"{type(e).__name__}: {e}"})
            continue
        rows.append({
            "rung": rung,
            "eligible": True,
            "parity": parity_of(cap.outputs, out),
            "nodes": _nodes_of(out),
            "ms": round(ms, 2),
            "captured_rung": _captured_rung(cap),
            "rung_match": rung == _captured_rung(cap),
        })
    return rows
