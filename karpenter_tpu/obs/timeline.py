"""Fleet ledger: causal node-lifecycle timeline + realized-cost accounting.

The flight recorder (obs/trace.py) made *time* observable, the device
plane (obs/devplane.py) *compiles and padding*, the decision ledger
(obs/decisions.py) *decisions*, and the capsule plane (obs/capsule.py)
*replayability*; this module is the fifth leg — it makes the **fleet's
money and lifecycle** observable:

- **Causal node-lifecycle timeline.** Every StateNode transition —
  ``launch``/``register``/``bind``/``drain``/``evict``/``interrupt``/
  ``retire`` (the closed ``EVENT_KINDS`` enum; unknown kinds raise) —
  appends ONE bounded-ring event carrying its cause chain: the
  decision-ledger ``(site, rung, reason)`` that shipped the command, the
  round's trace id, the originating command id, and the round's replay
  capsule ref when one exists. "Why does this node exist / why did it
  die" is a query on ``/introspect`` (the ``timeline`` section) and
  ``python -m karpenter_tpu.obs report --timeline``, not archaeology.
  Events raised inside an open round stage on the round's trace and only
  reach the ring when the round keeps (``Tracer._finish`` →
  ``note_round``) — an idle round that called ``obs.discard_round()``
  cannot grow the ring, mirroring the recorder's idle-round stance.
- **Realized-cost accounting.** ``observe_fleet`` integrates
  ``effective_price`` over node lifetimes (piecewise-constant between
  observations) into ``karpenter_fleet_cost_realized_total{nodepool,
  zone,capacity_type}``. Disruption commands record their
  criterion-predicted savings at confirm time (``begin_command``); when
  every replacement has launched and every retired node is gone, the
  command reconciles predicted vs realized (retired-rate minus
  launch-rate) and records one ``fleet.reconcile`` verdict. Sustained
  drift — a command outside ``KARPENTER_SAVINGS_DRIFT_TOL`` after a
  ``KARPENTER_SAVINGS_STEADY_AFTER`` in-tolerance streak — fires the
  **savings-drift** anomaly through the existing recorder (one Chrome
  dump + capsule; first-sight exempt; fires once per crossing, the same
  stance as rung-regression and solve-overhead-drift).
- **Per-tenant device-time billing.** ``devplane.record_dispatch``
  forwards every dispatch's device seconds here (``record_billing``);
  tenant resolution is explicit arg > the open round's ``tenant`` attr
  (the solver service's per-session rounds) > ``"untenanted"``. Seconds
  land on ``karpenter_tenant_device_seconds_total{tenant}`` and the
  ``karpenter_tenant_dispatch_seconds{tenant}`` histogram; the bounded
  per-tenant table (LRU at 256, evicted seconds fold into a dropped
  accumulator so totals stay exact) is the ``/usage`` endpoint's body on
  BOTH metrics servers. When a tenant's SloTracker sub-window LRU-drops,
  ``drop_tenant`` retires its histogram/quantile series
  (``Histogram.remove`` — the Gauge.remove parity the billing plane
  needed).
- **Observed interruption-rate feed.** Interrupt events count notices
  per ``(instance_type, zone)``; a retire of a noticed node counts a
  reclaim; ``observe_fleet`` integrates exposure-hours per key — the
  measured-risk input the ROADMAP's adaptive-spot item consumes
  (``interruption_rates()``; surfaced in the timeline snapshot).

All hooks are host-side by construction: graftlint's GL406 rule
(analysis/tracing.py) fails the tier-1 gate if ``record_event``/
``record_billing`` (or a verb on a timeline receiver) becomes reachable
from jit/pallas-traced code. Event schema, cause-chain contract, anomaly
trigger, ``/usage`` schema, and the knob table are documented in
deploy/README.md ("Fleet ledger").

Knobs (utils/envknobs.py accessors; re-read by ``reset()``):

- ``KARPENTER_TIMELINE_RING`` — event-ring capacity (default 4096).
- ``KARPENTER_SAVINGS_DRIFT_TOL`` — relative predicted-vs-realized
  tolerance per reconciled command (default 0.25).
- ``KARPENTER_SAVINGS_STEADY_AFTER`` — in-tolerance streak arming the
  savings-drift anomaly (default 16).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from karpenter_tpu.utils.envknobs import env_float, env_int

__all__ = [
    "EVENT_KINDS",
    "FleetTimeline",
    "TIMELINE",
    "record_event",
    "record_billing",
    "note_launch",
    "pend_cause",
    "begin_command",
    "observe_fleet",
    "note_round",
    "drop_tenant",
    "interruption_rates",
    "usage_snapshot",
    "timeline_snapshot",
    "reset",
]

# the closed lifecycle-transition enum: event kinds are code constants and
# a typo must fail tests, not mint a series (the SITES stance)
EVENT_KINDS = (
    "launch", "register", "bind", "drain", "evict", "interrupt", "retire",
)

# bounded in-flight state: commands awaiting reconciliation, staged cause
# links for replacement claims, and per-tenant billing rows (the SloTracker
# _TENANT_CAP stance — client-supplied ids must not grow memory unbounded)
_COMMAND_CAP = 256
_CAUSE_CAP = 1024
_TENANT_CAP = 256


def _env_ring() -> int:
    return env_int("KARPENTER_TIMELINE_RING", 4096, minimum=16)


def _env_drift_tol() -> float:
    return env_float("KARPENTER_SAVINGS_DRIFT_TOL", 0.25, minimum=0.0)


def _env_steady_after() -> int:
    return env_int("KARPENTER_SAVINGS_STEADY_AFTER", 16, minimum=1)


def _resolve_registry(registry):
    from karpenter_tpu.obs import devplane

    return devplane._resolve_registry(registry)


class FleetTimeline:
    """Process-wide fleet ledger: the event ring, the cost integrator, the
    command reconciler, the billing table, and the interruption feed. One
    module instance (``TIMELINE``) is the production default; tests
    construct their own or ``reset()`` it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._init_state()

    def _init_state(self):
        self.ring_capacity = _env_ring()
        self.drift_tol = _env_drift_tol()
        self.steady_after = _env_steady_after()
        with self._lock:
            self._ring: deque = deque(maxlen=self.ring_capacity)
            self._dropped = 0
            self._kind_counts: dict = {}  # kind -> committed events ever
            # replacement-claim name -> cause dict staged by the disruption
            # controller at command execution, popped by note_launch
            self._causes: "OrderedDict[str, dict]" = OrderedDict()
            # command id -> pending reconciliation state
            self._cmd_seq = 0
            self._commands: "OrderedDict[str, dict]" = OrderedDict()
            self._completed: deque = deque(maxlen=_COMMAND_CAP)
            # site -> {streak, violating}: the savings-drift detector (the
            # observe_quality template — in-tolerance extends the streak, a
            # violation fires only off a steady streak, then re-arms)
            self._drift: dict = {}
            # cost integrator: node name -> rate record, advanced by
            # observe_fleet between observations
            self._live: dict = {}
            self._last_now: float | None = None
            self._realized: dict = {}  # (pool, zone, ctype) -> effective $
            self._realized_total = 0.0
            self._exposure: dict = {}  # (itype, zone) -> hours
            # interruption feed
            self._notices: dict = {}  # (itype, zone) -> notices
            self._reclaims: dict = {}  # (itype, zone) -> reclaims
            self._interrupted: dict = {}  # node -> (itype, zone)
            # billing: tenant -> {device_seconds, dispatches, families}
            self._billing: "OrderedDict[str, dict]" = OrderedDict()
            self._billing_dropped = 0.0

    # -- the lifecycle event hook -----------------------------------------

    def record_event(self, kind: str, node: str, cause: dict | None = None,
                     registry=None, **attrs) -> dict:
        """One node-lifecycle transition. Inside an open round the event
        stages on the trace (committed at round close unless the round
        was discarded); with no round open it commits directly."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown timeline event kind {kind!r}")
        from karpenter_tpu.obs import trace as _trace

        tr = _trace.TRACER.current_trace()
        ev = {
            "kind": kind,
            "node": str(node),
            "at": time.time(),
            "trace_id": tr.trace_id if tr is not None else None,
            "cause": dict(cause) if cause else None,
        }
        if attrs:
            ev.update(attrs)
        if tr is not None:
            tr.add_event(ev)
        else:
            self._commit([ev], registry)
        return ev

    def note_round(self, trace) -> None:
        """Commit a kept round's staged events (called by the tracer at
        round close, AFTER the idle-discard gate and the recorder dump —
        so the round's capsule ref, when one was written, rides along)."""
        events = getattr(trace, "events", None)
        if not events:
            return
        if trace.capsule_path:
            for ev in events:
                ev.setdefault("capsule", trace.capsule_path)
        self._commit(list(events), trace.registry)

    def _commit(self, events: list, registry) -> None:
        counts: dict = {}
        retired: list = []
        with self._lock:
            for ev in events:
                if len(self._ring) == self._ring.maxlen:
                    self._dropped += 1
                self._ring.append(ev)
                kind = ev["kind"]
                self._kind_counts[kind] = self._kind_counts.get(kind, 0) + 1
                counts[kind] = counts.get(kind, 0) + 1
                node = ev["node"]
                if kind == "interrupt":
                    key = (ev.get("instance_type", ""), ev.get("zone", ""))
                    self._notices[key] = self._notices.get(key, 0) + 1
                    self._interrupted[node] = key
                elif kind == "retire":
                    key = self._interrupted.pop(node, None)
                    if key is not None:
                        self._reclaims[key] = self._reclaims.get(key, 0) + 1
                    retired.append(node)
        from karpenter_tpu.operator import metrics as _m

        c = _resolve_registry(registry).counter(
            _m.TIMELINE_EVENTS,
            "node-lifecycle events committed to the fleet-ledger timeline",
        )
        for kind, n in counts.items():
            c.inc(n, kind=kind)
        for node in retired:
            self._note_retired(node, registry)

    # -- command reconciliation -------------------------------------------

    def pend_cause(self, name: str, cause: dict) -> None:
        """Stage a cause chain for a replacement claim the disruption
        controller just created; ``note_launch`` pops it when the claim's
        node launches so the launch event carries its provenance."""
        with self._lock:
            self._causes.pop(name, None)
            if len(self._causes) >= _CAUSE_CAP:
                self._causes.popitem(last=False)
            self._causes[name] = dict(cause)

    def begin_command(self, site: str = "", rung: str = "", reason: str = "",
                      predicted: float | None = None,
                      retired_rate: float | None = None,
                      claims=(), nodes=(), registry=None) -> str:
        """Open one disruption command's ledger entry at confirm time:
        the criterion-predicted savings, the retired candidates' summed
        effective rate, and the replacement claims / candidate nodes whose
        completion closes the reconciliation. Returns the command id the
        cause chains carry."""
        with self._lock:
            self._cmd_seq += 1
            cmd_id = f"cmd-{self._cmd_seq:05d}"
            if len(self._commands) >= _COMMAND_CAP:
                self._commands.popitem(last=False)
            self._commands[cmd_id] = {
                "site": site or "",
                "rung": rung or "",
                "reason": reason or "",
                "predicted": (
                    float(predicted) if predicted is not None else None
                ),
                "retired_rate": (
                    float(retired_rate) if retired_rate is not None else 0.0
                ),
                "launch_rate": 0.0,
                "pending_claims": set(str(c) for c in claims),
                "pending_nodes": set(str(n) for n in nodes),
                "began": time.time(),
            }
        return cmd_id

    def note_launch(self, claim: str, node: str | None = None,
                    price: float = 0.0, registry=None, **attrs) -> dict:
        """One replacement launch: pops the claim's staged cause, records
        the launch event with it, and feeds the owning command's realized
        launch rate."""
        claim = str(claim)
        with self._lock:
            cause = self._causes.pop(claim, None)
        ev = self.record_event(
            "launch", node or claim, cause=cause, registry=registry,
            claim=claim, price=round(float(price), 6), **attrs,
        )
        done: list = []
        cmd_id = (cause or {}).get("command")
        if cmd_id:
            with self._lock:
                st = self._commands.get(cmd_id)
                if st is not None:
                    st["launch_rate"] += float(price)
                    st["pending_claims"].discard(claim)
                    if not st["pending_claims"] and not st["pending_nodes"]:
                        done.append((cmd_id, self._commands.pop(cmd_id)))
        for cid, st in done:
            self._reconcile(cid, st, registry)
        return ev

    def _note_retired(self, node: str, registry) -> None:
        """A node left the fleet (retire event, or vanished between fleet
        observations — the self-healing path): commands waiting on it
        advance, completing when nothing is pending."""
        done: list = []
        with self._lock:
            for cmd_id in list(self._commands):
                st = self._commands[cmd_id]
                if node in st["pending_nodes"]:
                    st["pending_nodes"].discard(node)
                    if not st["pending_claims"] and not st["pending_nodes"]:
                        done.append((cmd_id, self._commands.pop(cmd_id)))
        for cid, st in done:
            self._reconcile(cid, st, registry)

    def _reconcile(self, cmd_id: str, st: dict, registry) -> None:
        """Close one command: realized savings = retired rate − launch
        rate; record the fleet.reconcile verdict and arm/fire the
        savings-drift detector."""
        site = st["site"]
        predicted = st["predicted"]
        realized = st["retired_rate"] - st["launch_rate"]
        rec = {
            "command": cmd_id,
            "site": site,
            "rung": st["rung"],
            "reason": st["reason"],
            "predicted": (
                round(predicted, 6) if predicted is not None else None
            ),
            "realized": round(realized, 6),
            "ok": None,
        }
        if predicted is None:
            # no criterion prediction existed (a candidate without a
            # priced offering): keep the realized record, skip the drift
            # detector — there is nothing to reconcile against
            with self._lock:
                self._completed.append(rec)
            return
        ok = abs(realized - predicted) <= self.drift_tol * max(
            abs(predicted), 1e-9
        )
        rec["ok"] = ok
        fire = None
        with self._lock:
            ent = self._drift.setdefault(
                site, {"streak": 0, "violating": False}
            )
            if ok:
                ent["streak"] += 1
                ent["violating"] = False
            else:
                if ent["streak"] >= self.steady_after and not ent["violating"]:
                    fire = ent["streak"]
                ent["violating"] = True
                ent["streak"] = 0
            self._completed.append(rec)
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.counter(
            _m.FLEET_SAVINGS_PREDICTED,
            "criterion-predicted savings rate of reconciled disruption "
            "commands",
        ).inc(max(predicted, 0.0), site=site or "unknown")
        reg.counter(
            _m.FLEET_SAVINGS_REALIZED,
            "realized savings rate (retired minus launched effective "
            "price) of reconciled disruption commands",
        ).inc(max(realized, 0.0), site=site or "unknown")
        from karpenter_tpu.obs import decisions as _decisions

        _decisions.record_decision(
            "fleet.reconcile",
            "within" if ok else "drift",
            "interruption" if site == "disrupt.interruption"
            else "consolidation",
            registry=reg,
        )
        if fire is not None:
            from karpenter_tpu.obs import trace as _trace

            _trace.anomaly(
                "savings-drift", registry=reg, site=site or "unknown",
                command=cmd_id,
                predicted=round(predicted, 6), realized=round(realized, 6),
                held=fire,
            )

    # -- realized-cost integrator -----------------------------------------

    def observe_fleet(self, nodes, catalog, now: float, registry=None) -> dict:
        """Advance the cost integral: the PREVIOUS live set's effective
        rates accrue over ``now − last_now`` (piecewise-constant), then
        the live set rebuilds from ``nodes`` (store nodes with ``labels``)
        via ``catalog`` (a CatalogView — one per pass, not per node).
        Nodes that vanished since the last observation self-heal command
        reconciliation. Returns the live-cost summary."""
        from karpenter_tpu.api import labels as wk
        from karpenter_tpu.cloudprovider.types import (
            effective_price,
            risk_lambda,
        )

        lam = risk_lambda()
        new_live: dict = {}
        for node in nodes:
            labels = getattr(node, "labels", None) or {}
            off = catalog.offering(labels)
            if off is None:
                continue
            name = str(getattr(node, "name", "") or "")
            new_live[name] = {
                "pool": labels.get(wk.NODEPOOL_LABEL, ""),
                "zone": labels.get(wk.TOPOLOGY_ZONE_LABEL, ""),
                "ctype": labels.get(wk.CAPACITY_TYPE_LABEL, ""),
                "itype": labels.get(wk.INSTANCE_TYPE_LABEL, ""),
                "nominal": float(off.price),
                "effective": float(effective_price(off, lam)),
            }
        deltas: dict = {}
        vanished: list = []
        with self._lock:
            if self._last_now is not None:
                hours = max(float(now) - self._last_now, 0.0) / 3600.0
                if hours > 0.0:
                    for rec in self._live.values():
                        key = (rec["pool"], rec["zone"], rec["ctype"])
                        amt = rec["effective"] * hours
                        self._realized[key] = (
                            self._realized.get(key, 0.0) + amt
                        )
                        self._realized_total += amt
                        deltas[key] = deltas.get(key, 0.0) + amt
                        ekey = (rec["itype"], rec["zone"])
                        self._exposure[ekey] = (
                            self._exposure.get(ekey, 0.0) + hours
                        )
            vanished = [n for n in self._live if n not in new_live]
            self._live = new_live
            self._last_now = float(now)
        if deltas:
            from karpenter_tpu.operator import metrics as _m

            c = _resolve_registry(registry).counter(
                _m.FLEET_COST_REALIZED,
                "effective-price dollars integrated over node lifetimes "
                "by the fleet-ledger timeline",
            )
            for (pool, zone, ctype), amt in deltas.items():
                c.inc(amt, nodepool=pool, zone=zone, capacity_type=ctype)
        for n in vanished:
            self._note_retired(n, registry)
        return self.live_cost()

    def live_cost(self) -> dict:
        """The current fleet's summed rates + the realized integral —
        ``live_rate`` (nominal) is what reconciles against the perf
        harness's end-of-leg fleet-cost sweep."""
        with self._lock:
            rate = sum(r["nominal"] for r in self._live.values())
            eff = sum(r["effective"] for r in self._live.values())
            realized = {
                "/".join(k): round(v, 6) for k, v in self._realized.items()
            }
            total = self._realized_total
            n = len(self._live)
        return {
            "live_nodes": n,
            "live_rate": round(rate, 6),
            "live_rate_effective": round(eff, 6),
            "realized": realized,
            "realized_total": round(total, 6),
        }

    # -- per-tenant device-time billing -----------------------------------

    def record_billing(self, family: str, seconds: float,
                       tenant: str | None = None, registry=None) -> str:
        """One dispatch's device seconds, attributed to a tenant. Returns
        the resolved tenant."""
        seconds = max(float(seconds), 0.0)
        if tenant is None:
            from karpenter_tpu.obs import trace as _trace

            tr = _trace.TRACER.current_trace()
            if tr is not None and tr.root.attrs:
                tenant = tr.root.attrs.get("tenant")
        t = str(tenant) if tenant else "untenanted"
        with self._lock:
            rec = self._billing.pop(t, None)
            if rec is None:
                if len(self._billing) >= _TENANT_CAP:
                    _, evicted = self._billing.popitem(last=False)
                    # evicted seconds fold into the dropped accumulator so
                    # the usage total stays exact under tenant churn
                    self._billing_dropped += evicted["device_seconds"]
                rec = {"device_seconds": 0.0, "dispatches": 0,
                       "families": {}}
            self._billing[t] = rec
            rec["device_seconds"] += seconds
            rec["dispatches"] += 1
            fam = str(family)
            rec["families"][fam] = rec["families"].get(fam, 0.0) + seconds
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.counter(
            _m.TENANT_DEVICE_SECONDS,
            "device seconds billed per tenant by the fleet ledger",
        ).inc(seconds, tenant=t)
        reg.histogram(
            _m.TENANT_DISPATCH_SECONDS,
            "per-dispatch device seconds by tenant",
        ).observe(seconds, tenant=t)
        return t

    def drop_tenant(self, tenant: str, slo: str | None = None,
                    registry=None) -> None:
        """A tenant's SloTracker sub-window LRU-dropped: retire its
        billing series (Histogram.remove) and, when the tracker is named,
        its rolling-quantile gauges — the label-cardinality bound under
        tenant churn."""
        t = str(tenant)
        with self._lock:
            rec = self._billing.pop(t, None)
            if rec is not None:
                self._billing_dropped += rec["device_seconds"]
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.histogram(
            _m.TENANT_DISPATCH_SECONDS,
            "per-dispatch device seconds by tenant",
        ).remove(tenant=t)
        if slo:
            q = reg.gauge(
                _m.SOLVER_REQUEST_QUANTILE,
                "rolling request-latency quantiles over the SLO window",
            )
            for label in ("p50", "p95", "p99"):
                q.remove(slo=slo, tenant=t, q=label)

    # -- reads -------------------------------------------------------------

    def interruption_rates(self) -> dict:
        """Observed notices/reclaims vs exposure-hours per
        (instance_type, zone) — the adaptive-spot prior's measured-risk
        input."""
        with self._lock:
            keys = (set(self._notices) | set(self._reclaims)
                    | set(self._exposure))
            out = {}
            for k in sorted(keys):
                itype, zone = k
                n = self._notices.get(k, 0)
                r = self._reclaims.get(k, 0)
                h = self._exposure.get(k, 0.0)
                out[f"{itype}/{zone}"] = {
                    "instance_type": itype,
                    "zone": zone,
                    "notices": n,
                    "reclaims": r,
                    "exposure_hours": round(h, 6),
                    "reclaims_per_hour": (
                        round(r / h, 6) if h > 0.0 else 0.0
                    ),
                }
        return out

    def usage_snapshot(self) -> dict:
        """The ``/usage`` endpoint body: per-tenant billed device seconds
        (+ the dropped accumulator so the total matches the devplane
        dispatch-seconds ledger within rounding)."""
        with self._lock:
            tenants = {
                t: {
                    "device_seconds": round(r["device_seconds"], 6),
                    "dispatches": r["dispatches"],
                    "families": {
                        f: round(s, 6) for f, s in r["families"].items()
                    },
                }
                for t, r in self._billing.items()
            }
            dropped = self._billing_dropped
        total = sum(r["device_seconds"] for r in tenants.values()) + dropped
        from karpenter_tpu.obs import devplane as _devplane

        with _devplane._STATS_LOCK:
            ledger = _devplane.STATS.get("dispatch_seconds", 0.0)
        return {
            "tenants": tenants,
            "total_device_seconds": round(total, 6),
            "dropped_device_seconds": round(dropped, 6),
            "devplane_dispatch_seconds": round(ledger, 6),
        }

    def snapshot(self, k: int = 64) -> dict:
        """The ``/introspect`` ``timeline`` section + the report CLI's
        ``--timeline`` body."""
        with self._lock:
            events = list(self._ring)[-max(int(k), 0):]
            ring = {
                "capacity": self.ring_capacity,
                "size": len(self._ring),
                "dropped": self._dropped,
                "kinds": dict(self._kind_counts),
            }
            pending = len(self._commands)
            completed = list(self._completed)[-max(int(k), 0):]
        return {
            "events": events,
            "ring": ring,
            "cost": self.live_cost(),
            "commands": {"pending": pending, "reconciled": completed},
            "interruptions": self.interruption_rates(),
            "billing": self.usage_snapshot(),
        }

    def reset(self) -> None:
        """Test isolation: clear every plane and re-read the env knobs."""
        self._init_state()


TIMELINE = FleetTimeline()


def record_event(kind: str, node: str, cause: dict | None = None,
                 registry=None, **attrs) -> dict:
    return TIMELINE.record_event(kind, node, cause=cause, registry=registry,
                                 **attrs)


def record_billing(family: str, seconds: float, tenant: str | None = None,
                   registry=None) -> str:
    return TIMELINE.record_billing(family, seconds, tenant=tenant,
                                   registry=registry)


def note_launch(claim: str, node: str | None = None, price: float = 0.0,
                registry=None, **attrs) -> dict:
    return TIMELINE.note_launch(claim, node=node, price=price,
                                registry=registry, **attrs)


def pend_cause(name: str, cause: dict) -> None:
    TIMELINE.pend_cause(name, cause)


def begin_command(site: str = "", rung: str = "", reason: str = "",
                  predicted: float | None = None,
                  retired_rate: float | None = None,
                  claims=(), nodes=(), registry=None) -> str:
    return TIMELINE.begin_command(
        site=site, rung=rung, reason=reason, predicted=predicted,
        retired_rate=retired_rate, claims=claims, nodes=nodes,
        registry=registry,
    )


def observe_fleet(nodes, catalog, now: float, registry=None) -> dict:
    return TIMELINE.observe_fleet(nodes, catalog, now, registry=registry)


def note_round(trace) -> None:
    TIMELINE.note_round(trace)


def drop_tenant(tenant: str, slo: str | None = None, registry=None) -> None:
    TIMELINE.drop_tenant(tenant, slo=slo, registry=registry)


def interruption_rates() -> dict:
    return TIMELINE.interruption_rates()


def usage_snapshot() -> dict:
    return TIMELINE.usage_snapshot()


def timeline_snapshot(k: int = 64) -> dict:
    return TIMELINE.snapshot(k)


def reset() -> None:
    TIMELINE.reset()
