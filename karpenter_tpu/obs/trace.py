"""In-process span tracer for the provision/disrupt hot paths.

The perf stack (ROADMAP PRs 2-4) is a ladder of caches, delta paths, and
probe-confirm rungs whose SLOW edges — opaque snapshot rebuilds, extra
host confirms, host-routed pods, the sequential waves oracle — used to
surface only as scattered counters. This module gives every reconcile
round a causal story instead: a tree of context-manager spans with
monotonic timing, parent links, and structured attributes, cheap enough
to stay on by default (see the slow overhead test in tests/test_obs.py:
tracer-on grid-1000 stays within 2% of tracer-off).

Model
-----

- A **round** (``round_trace``) is the root of one trace: one provisioner
  solve batch, one disruption poll, one binder pass. Rounds hand their
  finished trace to the :class:`~karpenter_tpu.obs.recorder.FlightRecorder`
  ring buffer and feed span self-time histograms into the round's metrics
  registry (``karpenter_trace_span_self_seconds{span,kind}``).
- A **span** (``span``) nests under the thread's innermost open span.
  Spans carry a ``kind`` separating where wall clock is spent:
  ``host`` (Python control flow, decode, FFD), ``device`` (kernel
  dispatch and the ``block_until_ready``-equivalent host pull — the
  bracketing in models/solver.py ``_invoke_inner`` and
  ops/consolidate.py ``dispatch``), and ``cache`` (tensorization,
  snapshot build/delta-advance — the stages whose hit/miss behavior the
  PR 2-4 caches govern).
- An **anomaly** (``anomaly``) marks the current trace as worth keeping:
  the recorder dumps exactly one Chrome trace-event JSON file per
  anomalous round. The wired triggers are ``probe-fallback`` (a device
  consolidation probe raised and the sequential search took over),
  ``multi-host-confirms`` (>1 confirming simulation in one MultiNode
  round — the batched ladder's ≤1 target missed), ``snapshot-rebuild``
  (the disruption snapshot cache paid a full tensorization while holding
  a prior bundle — the delta path declined), ``host-routed`` (a live
  provisioning batch sent pods to the host engine), ``negative-avail``
  (tensorize_existing clamped a negative availability), and
  ``cold-compile-in-steady-state`` (the device-plane compile ledger,
  :mod:`karpenter_tpu.obs.devplane`, saw a cold XLA compile after a long
  warm streak — shape-key churn or cache eviction in what should be a
  compiled-once steady state). Each also counts in
  ``karpenter_trace_anomalies_total{kind}``.

Threading: spans are attached via a thread-local stack, so concurrent
threads can never corrupt each other's parent links; a worker thread can
join an existing trace with ``attach(trace)``. Mutation of the shared
trace structure is guarded by the trace's lock. A thread with no active
trace gets no-op spans (a shared singleton — no allocation).

Safety: span enter/exit must NEVER execute inside jit/pallas-traced code
(it would freeze one trace's timing into the compiled program and race
the tracer from XLA's runtime). graftlint's GL4xx family
(analysis/tracing.py) proves this statically over the package.

Knobs (resolved at import; ``configure()`` overrides in-process):

- ``KARPENTER_TRACE=0`` disables the tracer entirely (no-op spans).
- ``KARPENTER_TRACE_DIR`` — dump directory (default
  ``<tempdir>/karpenter-traces``).
- ``KARPENTER_TRACE_DUMP=1`` — dump every recorded round, not just
  anomalous ones (the on-demand flag; ``python -m perf --json`` uses the
  equivalent API to attach a dump per bench row).
- ``KARPENTER_TRACE_RING`` — flight-recorder capacity (default 32).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

from karpenter_tpu.utils import envknobs

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "TRACER",
    "RECORDER",
    "span",
    "round_trace",
    "anomaly",
    "attach",
    "current_trace_id",
    "configure",
    "discard_round",
    "reset",
]

# spans a single trace may hold before new ones are dropped (and counted
# in Trace.dropped): a runaway loop must degrade the trace, not memory
MAX_SPANS_PER_TRACE = 20000


class Span:
    """One timed region. ``dur`` is None while the span is open; ``t0`` is
    a monotonic perf_counter reading (the trace anchors it to wall time)."""

    __slots__ = ("name", "kind", "t0", "dur", "tid", "attrs", "children")

    def __init__(self, name: str, kind: str, tid: int, attrs: dict | None):
        self.name = name
        self.kind = kind
        self.t0 = time.perf_counter()
        self.dur = None
        self.tid = tid
        self.attrs = attrs
        self.children: list = []

    def self_seconds(self) -> float:
        """Duration minus the time spent inside child spans."""
        d = self.dur or 0.0
        return max(d - sum(c.dur or 0.0 for c in self.children), 0.0)


class Trace:
    """One finished-or-in-flight round: a root span, its tree, and the
    anomaly marks that decide whether the recorder dumps it."""

    def __init__(self, trace_id: str, name: str, registry=None,
                 attrs: dict | None = None):
        self.trace_id = trace_id
        self.name = name
        self.registry = registry
        self.pid = os.getpid()
        self.wall_start = time.time()
        self.root = Span(name, "host", threading.get_ident(), attrs)
        self.anomalies: list = []  # (kind, attrs, perf_counter stamp)
        # (site, rung, reason) -> count: the round's decision-ledger
        # verdicts (obs/decisions.py), carried into the Chrome dump
        self.decisions: dict = {}
        self.dropped = 0
        self.dump_path: str | None = None
        # the round's pending replay capture (obs/capsule.py): the most
        # recent hot-path solve's tensorized inputs+outputs, kept by
        # REFERENCE (no copy, no serialization) so anomaly-free rounds pay
        # ~nothing; serialized to a capsule file next to the Chrome dump
        # only when the round closes anomalous (or KARPENTER_CAPSULE=1)
        self.capsule_pending: dict | None = None
        self.capsule_path: str | None = None
        # node-lifecycle events staged by the fleet ledger
        # (obs/timeline.py): committed to the timeline ring only when the
        # round keeps, so an idle round cannot grow it
        self.events: list = []
        # an idle round (the owner found nothing to do) opts out of the
        # ring and the histograms so it cannot churn real rounds out; an
        # anomaly overrides the discard — anomalous rounds always keep
        self.discarded = False
        self._lock = threading.Lock()
        self._n = 1

    # -- structure (thread-safe: spans may arrive from attached threads) --
    def add_child(self, parent: Span, child: Span) -> bool:
        with self._lock:
            if self._n >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return False
            self._n += 1
            parent.children.append(child)
            return True

    def add_anomaly(self, kind: str, attrs: dict | None):
        with self._lock:
            self.anomalies.append((kind, attrs, time.perf_counter()))

    def add_decision(self, site: str, rung: str, reason: str):
        with self._lock:
            key = (site, rung, reason)
            self.decisions[key] = self.decisions.get(key, 0) + 1

    def add_event(self, event: dict):
        """Stage one fleet-ledger lifecycle event (obs/timeline.py) for
        commit at round close."""
        with self._lock:
            self.events.append(event)

    def add_capture(self, record: dict):
        """Attach a replay-capture record (last one wins — the round's
        most recent solve is the one an anomaly usually indicts)."""
        with self._lock:
            self.capsule_pending = record

    # -- derived views (call after the round closed) ----------------------
    def spans(self):
        """Every span, pre-order, root first."""
        out, stack = [], [self.root]
        while stack:
            s = stack.pop()
            out.append(s)
            stack.extend(reversed(s.children))
        return out

    def self_times(self) -> dict:
        """span name -> [total self seconds, count] over the tree."""
        agg: dict = {}
        for s in self.spans():
            e = agg.setdefault(s.name, [0.0, 0])
            e[0] += s.self_seconds()
            e[1] += 1
        return agg

    def summary(self, top: int = 5) -> list:
        """Top-N spans by aggregate self time (the perf-row embed)."""
        agg = self.self_times()
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]
        return [
            {"span": name, "self_ms": round(tot * 1000.0, 3), "count": n}
            for name, (tot, n) in rows
        ]

    def leaf_coverage(self) -> float:
        """Fraction of the round's wall clock attributed to spans BELOW
        the root — the instrumentation-coverage number the acceptance
        criterion pins (≥95% on a 300-node consolidation round)."""
        d = self.root.dur or 0.0
        if d <= 0.0:
            return 1.0
        return 1.0 - self.root.self_seconds() / d


class _NopSpan:
    """Shared do-nothing context manager for disabled/unrooted spans."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


_NOP = _NopSpan()


class _SpanHandle:
    __slots__ = ("_tracer", "_span", "_attached")

    def __init__(self, tracer: "Tracer", sp: Span, attached: bool):
        self._tracer = tracer
        self._span = sp
        self._attached = attached

    def __enter__(self):
        if self._attached:
            self._tracer._tls.stack.append(self._span)
        return self._span

    def __exit__(self, et, ev, tb):
        sp = self._span
        sp.dur = time.perf_counter() - sp.t0
        if et is not None:
            if sp.attrs is None:
                sp.attrs = {}
            sp.attrs["error"] = getattr(et, "__name__", str(et))
        if self._attached:
            stack = self._tracer._tls.stack
            if stack and stack[-1] is sp:
                stack.pop()
        return False


class _RoundHandle:
    __slots__ = ("_tracer", "_trace")

    def __init__(self, tracer: "Tracer", trace: Trace):
        self._tracer = tracer
        self._trace = trace

    def __enter__(self):
        tls = self._tracer._tls
        tls.trace = self._trace
        tls.stack = [self._trace.root]
        return self._trace

    def __exit__(self, et, ev, tb):
        tr = self._trace
        tr.root.dur = time.perf_counter() - tr.root.t0
        if et is not None:
            if tr.root.attrs is None:
                tr.root.attrs = {}
            tr.root.attrs["error"] = getattr(et, "__name__", str(et))
        tls = self._tracer._tls
        tls.trace = None
        tls.stack = []
        self._tracer._finish(tr)
        return False


class _Attach:
    """Joins a worker thread to an existing trace (root-parented spans)."""

    __slots__ = ("_tracer", "_trace")

    def __init__(self, tracer: "Tracer", trace: Trace):
        self._tracer = tracer
        self._trace = trace

    def __enter__(self):
        tls = self._tracer._tls
        tls.trace = self._trace
        tls.stack = [self._trace.root]
        return self._trace

    def __exit__(self, et, ev, tb):
        tls = self._tracer._tls
        tls.trace = None
        tls.stack = []
        return False


class Tracer:
    """The process tracer. One module-level instance (``TRACER``) is the
    production default — components reach it through the module helpers
    ``span``/``round_trace``/``anomaly`` so tests can ``configure()`` it
    without re-wiring every controller."""

    def __init__(self, enabled: bool = True, recorder=None):
        self.enabled = enabled
        self.recorder = recorder
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._seq = 0

    # -- thread-local plumbing -------------------------------------------
    @property
    def _stack(self) -> list:
        tls = self._tls
        if not hasattr(tls, "stack"):
            tls.stack = []
            tls.trace = None
        return tls.stack

    def current_trace(self) -> Trace | None:
        self._stack  # materialize the thread-local slots
        return self._tls.trace

    def current_trace_id(self) -> str | None:
        tr = self.current_trace()
        return tr.trace_id if tr is not None else None

    def _new_id(self) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return f"{os.getpid():x}-{seq:04x}"

    # -- the public surface ----------------------------------------------
    def round_trace(self, name: str, registry=None, **attrs):
        """Open a round: the root of a new trace. Degrades to a plain
        child span when a trace is already active on this thread (a
        simulation re-entering the provisioner must not steal the
        disruption round's recorder slot)."""
        if not self.enabled:
            return _NOP
        if self.current_trace() is not None:
            return self.span(name, **attrs)
        _ensure_log_context()
        tr = Trace(self._new_id(), name, registry=registry,
                   attrs=attrs or None)
        return _RoundHandle(self, tr)

    def span(self, name: str, kind: str = "host", **attrs):
        """A timed region under the thread's innermost open span. No-op
        (shared singleton, no allocation) when the tracer is disabled or
        the thread has no active trace."""
        if not self.enabled:
            return _NOP
        stack = self._stack
        if not stack:
            return _NOP
        tr = self._tls.trace
        sp = Span(name, kind, threading.get_ident(), attrs or None)
        attached = tr.add_child(stack[-1], sp)
        if not attached:
            return _NOP
        return _SpanHandle(self, sp, attached)

    def anomaly(self, kind: str, registry=None, **attrs):
        """Mark the current trace (if any) as anomalous and count the
        trigger. The recorder dumps one Chrome trace file per anomalous
        round when the trace closes."""
        if not self.enabled:
            return
        tr = self.current_trace()
        reg = registry if registry is not None else (
            tr.registry if tr is not None else None
        )
        if reg is not None:
            from karpenter_tpu.operator import metrics as m

            reg.counter(
                m.TRACE_ANOMALIES,
                "anomaly triggers observed by the reconcile flight recorder",
            ).inc(kind=kind)
        if tr is not None:
            tr.add_anomaly(kind, attrs or None)

    def attach(self, trace: Trace):
        """Context manager joining THIS thread to ``trace`` (spans parent
        under the trace root). For worker threads fanned out inside a
        round."""
        if not self.enabled or trace is None:
            return _NOP
        return _Attach(self, trace)

    def discard_round(self):
        """Mark the current round as idle — it skips the ring buffer and
        the histograms (unless an anomaly fired, which always wins). For
        owners whose polling loop ticks with nothing to do: a quiet
        cluster must not churn its one interesting round out of the
        flight recorder."""
        tr = self.current_trace()
        if tr is not None:
            tr.discarded = True

    # -- round completion -------------------------------------------------
    def _finish(self, trace: Trace):
        if trace.discarded and not trace.anomalies:
            return
        self._feed_metrics(trace)
        if trace.decisions:
            # the decision ledger keeps a last-K ring of per-round rung
            # summaries for the /introspect surface (obs/decisions.py)
            from karpenter_tpu.obs import decisions as _decisions

            _decisions.note_round(trace)
        rec = self.recorder
        if rec is not None:
            rec.record(trace)
        if trace.events:
            # the fleet ledger commits the round's staged lifecycle
            # events AFTER the recorder ran, so the round's capsule ref
            # (when one was written) rides on the committed events
            from karpenter_tpu.obs import timeline as _timeline

            _timeline.note_round(trace)

    def _feed_metrics(self, trace: Trace):
        registry = trace.registry
        if registry is None:
            return
        from karpenter_tpu.operator import metrics as m

        registry.histogram(
            m.TRACE_ROUND_SECONDS, "traced reconcile round durations"
        ).observe(trace.root.dur or 0.0, round=trace.name)
        hist = registry.histogram(
            m.TRACE_SPAN_SECONDS,
            "per-span self time (span tree leaves feed the stage "
            "attribution story)",
        )
        for sp in trace.spans():
            if sp is trace.root:
                continue
            hist.observe(sp.self_seconds(), span=sp.name, kind=sp.kind)


# ---------------------------------------------------------------------------
# module singletons + env wiring
# ---------------------------------------------------------------------------

def _env_enabled() -> bool:
    return envknobs.env_bool("KARPENTER_TRACE", True)


def _env_dump_all() -> bool:
    return (envknobs.env_str("KARPENTER_TRACE_DUMP", "") or "").strip().lower() in (
        "1", "all", "true", "yes", "on",
    )


def _env_dir() -> str:
    return envknobs.env_str("KARPENTER_TRACE_DIR") or os.path.join(
        tempfile.gettempdir(), "karpenter-traces"
    )


def _env_capacity() -> int:
    return envknobs.env_int("KARPENTER_TRACE_RING", 32, minimum=1)


def _build_recorder():
    from karpenter_tpu.obs.recorder import FlightRecorder

    return FlightRecorder(
        capacity=_env_capacity(), dump_dir=_env_dir(),
        dump_all=_env_dump_all(),
    )


RECORDER = _build_recorder()
TRACER = Tracer(enabled=_env_enabled(), recorder=RECORDER)


def span(name: str, kind: str = "host", **attrs):
    return TRACER.span(name, kind=kind, **attrs)


def round_trace(name: str, registry=None, **attrs):
    return TRACER.round_trace(name, registry=registry, **attrs)


def anomaly(kind: str, registry=None, **attrs):
    return TRACER.anomaly(kind, registry=registry, **attrs)


def attach(trace: Trace):
    return TRACER.attach(trace)


def discard_round():
    TRACER.discard_round()


def current_trace_id() -> str | None:
    return TRACER.current_trace_id()


def configure(enabled: bool | None = None, dump_dir: str | None = None,
              capacity: int | None = None, dump_all: bool | None = None):
    """Mutate the process tracer/recorder in place (tests, perf harness,
    __main__ flag wiring). Returns (TRACER, RECORDER)."""
    if enabled is not None:
        TRACER.enabled = enabled
    RECORDER.configure(dump_dir=dump_dir, capacity=capacity,
                       dump_all=dump_all)
    return TRACER, RECORDER


def reset():
    """Restore env defaults and clear the ring + this thread's stack
    (test isolation). Also clears the decision ledger — its streak state
    feeds anomalies into rounds this tracer records, so the two must
    reset together or a prior test's held rung leaks a regression."""
    TRACER.enabled = _env_enabled()
    TRACER._tls.trace = None
    TRACER._tls.stack = []
    RECORDER.configure(dump_dir=_env_dir(), capacity=_env_capacity(),
                       dump_all=_env_dump_all())
    RECORDER.clear()
    from karpenter_tpu.obs import capsule as _capsule
    from karpenter_tpu.obs import decisions as _decisions
    from karpenter_tpu.obs import timeline as _timeline

    _decisions.reset()
    _capsule.reset()
    _timeline.reset()
    return TRACER, RECORDER


# trace ids thread into the structured logging plane: every record emitted
# while a round is open carries trace=<id> (operator/logging.py providers).
# Installed lazily at the first round — importing the operator package here
# would close an import cycle (operator.__init__ → environment →
# provisioner → models.solver → obs)
def _log_context() -> dict:
    tid = TRACER.current_trace_id()
    return {"trace": tid} if tid else {}


_LOG_HOOK_INSTALLED: list = []


def _ensure_log_context():
    if _LOG_HOOK_INSTALLED:
        return
    _LOG_HOOK_INSTALLED.append(True)
    from karpenter_tpu.operator import logging as _logging

    _logging.add_context_provider(_log_context)
