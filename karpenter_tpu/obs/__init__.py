"""obs: the reconcile flight recorder (span tracing + anomaly dumps).

Contract (cross-referenced from ops/consolidate.py and ops/tensorize.py):

- ``round_trace(name, registry=...)`` opens one traced reconcile round;
  ``span(name, kind=...)`` nests timed regions under it (``kind`` in
  {host, device, cache}); ``anomaly(kind, ...)`` marks the round so the
  flight recorder dumps its Chrome trace-event JSON. See
  :mod:`karpenter_tpu.obs.trace` for the full model and env knobs, and
  :mod:`karpenter_tpu.obs.recorder` for the dump format.
- Span enter/exit is host-only by construction: graftlint's GL4xx rules
  (``karpenter_tpu/analysis/tracing.py``) fail the tier-1 gate if a span
  or anomaly call becomes reachable from jit/pallas-traced code.
- :mod:`karpenter_tpu.obs.devplane` adds the device-plane telemetry:
  the compile ledger (cold-compile events + the
  ``cold-compile-in-steady-state`` anomaly), pow-2 padding-waste
  accounting, and the SLO trackers behind the metrics server's ``/slo``
  endpoint. Its hooks are host-only under the same gate (GL403).
- :mod:`karpenter_tpu.obs.decisions` is the decision plane: every
  fallback-ladder site records one ``(site, rung, reason)`` verdict per
  invocation (``karpenter_decision_total``, the ``rung-regression`` and
  ``solve-overhead-drift`` anomalies, the ``/introspect`` surface and
  ``python -m karpenter_tpu.obs report``). Its hooks are host-only under
  GL404.
- :mod:`karpenter_tpu.obs.capsule` is the replay plane: every hot-path
  dispatch seam captures the solve's exact tensor inputs/outputs by
  reference; anomalous rounds serialize ONE schema-versioned
  ``.capsule.npz`` next to their Chrome dump, and
  ``python -m karpenter_tpu.obs replay <capsule> [--ab]`` re-executes it
  bit-identically offline (and A/Bs every eligible rung). Its hooks are
  host-only under GL405.
- :mod:`karpenter_tpu.obs.timeline` is the fleet ledger: the causal
  node-lifecycle timeline (bounded event ring with decision/trace/
  capsule cause chains, queried via ``/introspect`` and
  ``python -m karpenter_tpu.obs report --timeline``), realized-cost
  accounting with per-command predicted-vs-realized reconciliation (the
  ``savings-drift`` anomaly), per-tenant device-time billing behind the
  ``/usage`` endpoint, and the observed interruption-rate feed. Its
  hooks are host-only under GL406.
"""

from karpenter_tpu.obs import capsule, decisions, devplane, timeline
from karpenter_tpu.obs.recorder import FlightRecorder, chrome_events
from karpenter_tpu.obs.trace import (
    RECORDER,
    TRACER,
    Span,
    Trace,
    Tracer,
    anomaly,
    attach,
    configure,
    current_trace_id,
    discard_round,
    reset,
    round_trace,
    span,
)

__all__ = [
    "FlightRecorder",
    "chrome_events",
    "capsule",
    "decisions",
    "devplane",
    "timeline",
    "RECORDER",
    "TRACER",
    "Span",
    "Trace",
    "Tracer",
    "anomaly",
    "attach",
    "configure",
    "current_trace_id",
    "discard_round",
    "reset",
    "round_trace",
    "span",
]
