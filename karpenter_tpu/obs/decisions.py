"""Decision ledger: fallback-rung provenance for every hot-path ladder.

The flight recorder (obs/trace.py) made *time* observable and the device
plane (obs/devplane.py) made *compiles and padding* observable; this
module is the third leg — it makes the system's *decisions* observable.
Every hot path is a ladder of silent rungs (partitioned → replicated →
unsharded mesh, delta-advance → full-rebuild snapshots, definitive →
gallop → sequential probes, …), and a steady-state downgrade — the exact
failure mode that made the replicated mesh program a no-op for two PRs —
was invisible until someone read a bench JSON. Here, every ladder site
records exactly ONE ``(site, rung, reason)`` verdict per invocation:

======================  =================================  =========================================
site                    rungs (best first)                 recorded by
======================  =================================  =========================================
``mesh.partition``      partitioned, replicated,           ``parallel/mesh.py sharded_solve``
                        unsharded
``snapshot.advance``    delta, rebuild                     ``ops/consolidate.py SnapshotCache``
``probe.confirm``       definitive, gallop, sequential     ``controllers/disruption/methods.py``
``consolidate.global``  joint, ladder, sequential          ``controllers/disruption/methods.py``
``solver.route``        relax, mesh, native, xla,          ``models/solver.py TPUSolver.solve``
                        service, host
``session.sync``        delta, resync                      ``service/solver_service.py`` (both ends)
``decode.recheck``      skip, full                         ``models/solver.py _compat_entry``
``admission.tier``      cascade, single                    ``admission/plane.py solve_round``
``admission.preempt``   confirmed, declined, skipped       ``admission/preempt.py``
``admission.gang``      atomic, routed                     ``admission/plane.py _solve_gang``
======================  =================================  =========================================

Reasons are drawn from a CLOSED enum per site (``SITES[site]["reasons"]``)
so the ``karpenter_decision_total{site,rung,reason}`` label cardinality is
bounded: an unknown reason clamps to ``"other"`` instead of minting a new
series (``canonical_reason``). Unknown sites/rungs raise — they are code
constants, and a typo must fail tests, not mint a series. The static half
of that contract is graftlint's GL502 (analysis/contracts.py): every
``record_decision`` call site in the package — literal, wrapper-routed,
or riding a carrier like ``LAST_RUN['plan_refusal']`` — is resolved
against ``SITES`` at lint time, so adding a producer reason without
registering it here fails the tier-1 gate before it can clamp at runtime
(rule table: deploy/README.md § Static analysis).

Every record also:

- lands on the open round's flight-recorder trace (``Trace.add_decision``)
  as structured attrs, so the Chrome dump of an anomalous round shows
  which rungs it ran (``otherData.decisions``);
- feeds the **rung-regression anomaly**: a site that held a top rung for
  ``KARPENTER_RUNG_STEADY_AFTER`` (16) consecutive invocations and then
  records a strictly lower rung fires ``rung-regression`` through the
  existing one-dump-per-round machinery — the same stance as
  cold-compile-in-steady-state. A site's first-ever record can never fire
  (first-sight exemption), reasons a site marks ``benign`` (a session's
  initial upload for a new shape family, a calibrated small-batch routing
  flip) neither fire nor break the held streak (expected universe growth,
  mirroring the compile ledger's first-of-family exemption), and after
  firing the downgraded rung becomes the new held rung, so a persistent
  downgrade dumps once, not per round.

The **solve-quality account** (``record_quality``) tracks per-solve nodes
against the pods-cap floor the solver already computes: the ratio lands on
the ``karpenter_solve_overhead_ratio`` gauge and a per-shape-family series,
and a steady-state drift (ratio held within ``KARPENTER_QUALITY_DRIFT_TOL``
of the family's best for ``KARPENTER_QUALITY_STEADY_AFTER`` solves, then
exceeds it) fires the ``solve-overhead-drift`` anomaly once per crossing.
Families below ``KARPENTER_QUALITY_MIN_FLOOR`` (8) feed the gauge/series
but not the drift detector — toy solves must not arm it.

Introspection: ``introspect_snapshot()`` is the ``/introspect`` endpoint's
JSON body (metrics server AND the solver service's --metrics-port): per-
site rung mixes, the last-K rounds' rung summaries (fed by the tracer at
round close), the quality series, per-tenant rung mixes (bounded LRU, the
SloTracker stance), and the recorder's retained anomalous rounds.
``python -m karpenter_tpu.obs report`` renders it for a human.

All hooks are host-side by construction: graftlint's GL404 rule
(analysis/tracing.py) fails the tier-1 gate if ``record_decision``/
``record_quality`` (or a verb on a decisions receiver) becomes reachable
from jit/pallas-traced code. Site/rung/reason semantics are documented in
deploy/README.md ("Decision plane").

The ``fleet.reconcile`` site is produced by the fleet ledger
(:mod:`karpenter_tpu.obs.timeline`): one verdict per reconciled
disruption command, with the savings-drift anomaly owning that site's
steady-streak story — see deploy/README.md ("Fleet ledger").
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

__all__ = [
    "SITES",
    "OTHER_REASON",
    "DecisionLedger",
    "DECISIONS",
    "record_decision",
    "record_quality",
    "canonical_reason",
    "rung_rank",
    "note_round",
    "counts",
    "rung_delta",
    "introspect_snapshot",
    "reset",
    "SOLVER_FALLBACK_REASONS",
]

# the catchall every site's enum carries: unknown reasons clamp here so
# client-supplied or exception-derived strings can never mint new series
OTHER_REASON = "other"

# The closed site registry. ``rungs`` are ordered BEST first — rank order
# is what the rung-regression anomaly and bench.py's sentinel compare —
# and ``reasons`` is the complete label universe for the site (producers'
# literal reason strings are pinned against these sets by
# tests/test_decisions.py, so the scattered fallback-cause strings and the
# ledger can never drift apart).
SITES = {
    "mesh.partition": {
        # parallel/mesh.py sharded_solve: the partitioned formulation, the
        # replicated exact fallback, or the plain unsharded kernel.
        # Replicated reasons are plan_shards' refusal causes verbatim.
        "rungs": ("partitioned", "replicated", "unsharded"),
        "reasons": frozenset({
            "ok", "partition-disabled", "degenerate-mesh", "existing-nodes",
            "min-values", "nodepool-limits", "single-bin-groups",
            "topology-classes", "too-few-groups", "no-need", "single-slice",
            "no-plan", "repair-bound", OTHER_REASON,
        }),
    },
    "snapshot.advance": {
        # ops/consolidate.py SnapshotCache: a stale held bundle either
        # delta-advances or is displaced by a full rebuild. Rebuild
        # reasons are the inexpressible-delta causes.
        "rungs": ("delta", "rebuild"),
        "reasons": frozenset({
            "ok", "journal-gap", "opaque-entry", "plan", "limits",
            "ineligible-pending", "unseen-signature", "unseen-pending",
            "churn", "candidate-widened", OTHER_REASON,
        }),
    },
    "probe.confirm": {
        # controllers/disruption/methods.py: how a consolidation method's
        # probe ladder resolved — definitive (one confirming simulation),
        # gallop (device seed + sequential recovery), or the reference's
        # sequential search outright. joint-seeded = the answer came from
        # the round's joint dispatch (ops/consolidate.py JointSeed)
        # without a second device dispatch — the ISSUE-14 short-circuit's
        # accounted, never-silent skipped-probe path.
        "rungs": ("definitive", "gallop", "sequential"),
        "reasons": frozenset({
            "ok", "non-definitive", "inexpressible", "probe-error",
            "no-device", "joint-seeded", OTHER_REASON,
        }),
    },
    "consolidate.global": {
        # controllers/disruption/methods.py GlobalConsolidation: the joint
        # device-solved retirement shipped (joint), handed the round to
        # the per-candidate ladder with a cause (ladder — confirm
        # disagreement, repair overflow, topology plan, or simply nothing
        # to retire), or never ran a device solve at all (sequential).
        # Workload-driven hand-offs are benign; confirm-mismatch,
        # repair-bound, probe-error, and inexpressible stay armed — a
        # steady 2k fleet quietly descending to the ladder every round is
        # exactly the regression this site exists to catch.
        # joint-noop-fenced = the joint dispatch PROVED round-wide
        # no-retirement on a mid-transition snapshot and the controller
        # closed the round without running the MultiNode/SingleNode
        # probes (ISSUE-14 short-circuit) — workload-driven, benign.
        # relax / relax-rounded = the LP relaxation rung (ops/relax.py)
        # selected the shipped set (exactly at the LP bound / with the
        # rounding window shedding below it); relax-fallback = relax
        # attempted and declined, the FFD ladder shipped the round
        # (RELAX_STATS pins the cause). All three ship a command at the
        # best rung, so like "ok" they stay armed rather than benign.
        # replace = the joint REPLACE program (multi-claim rows,
        # KARPENTER_REPLACE_MAX_CLAIMS>1) shipped a retirement the m->1
        # delete rows would have stranded — armed, same stance as relax.
        "rungs": ("joint", "ladder", "sequential"),
        "reasons": frozenset({
            "ok", "no-retirement", "non-definitive", "confirm-mismatch",
            "repair-bound", "topology-plan", "inexpressible",
            "probe-error", "no-device", "disabled", "too-few-candidates",
            "joint-noop-fenced", "relax", "relax-rounded",
            "relax-fallback", "replace", OTHER_REASON,
        }),
        "benign": frozenset({
            "no-retirement", "non-definitive", "topology-plan", "disabled",
            "too-few-candidates", "no-device", "joint-noop-fenced",
        }),
    },
    "disrupt.interruption": {
        # controllers/disruption/methods.py InterruptionDrain: one verdict
        # per notice-bearing round — the replacement was solved and
        # launched BEFORE the drain (proactive; delete-only when the
        # survivors absorb every displaced pod), the replacement solve
        # could not place the pods so the node drains bare and the
        # provisioner rescues post-drain (reactive), or the deadline left
        # no time for a replacement at all and the round degraded to an
        # immediate drain (degraded). Degradations are cloud-driven (a
        # short-lead notice) but a fleet whose proactive path silently
        # dies — every notice degrading — is exactly what this site's
        # regression tracker exists to catch, so nothing is benign.
        "rungs": ("proactive", "reactive", "degraded"),
        "reasons": frozenset({
            "ok", "delete-only", "reactive-fallback", "deadline-degraded",
            OTHER_REASON,
        }),
    },
    "fleet.reconcile": {
        # obs/timeline.py FleetTimeline._reconcile: one verdict per
        # disruption command whose replacements all launched and whose
        # retired nodes all left the fleet — realized savings (retired
        # rate minus launch rate) within the KARPENTER_SAVINGS_DRIFT_TOL
        # band of the criterion prediction, or drifting. Every reason is
        # benign: the savings-drift anomaly (obs/timeline.py) owns the
        # steady-streak regression story for this site, so the generic
        # rung-regression detector must not double-fire beside it. See
        # deploy/README.md "Fleet ledger".
        "rungs": ("within", "drift"),
        "reasons": frozenset({
            "ok", "consolidation", "interruption", OTHER_REASON,
        }),
        "benign": frozenset({
            "ok", "consolidation", "interruption", OTHER_REASON,
        }),
    },
    "solver.route": {
        # models/solver.py TPUSolver.solve: which engine ran the kernel
        # (or that no kernel ran at all — the host FFD rung). relax =
        # the LP relaxation floor (ops/relax.py lp_bin_floor) tightened
        # the bin estimate that steered the completed solve — the rung
        # outranks the engines because the relaxation certificate, not
        # engine routing, decided the solve's shape.
        "rungs": ("relax", "mesh", "native", "xla", "service", "host"),
        "reasons": frozenset({
            "ok", "small-batch", "work-floor", "cpu-backend", "no-templates",
            "no-eligible", "no-device-groups", "remote-fallback",
            OTHER_REASON,
        }),
        # calibrated routing flips (a small batch after a big-batch streak,
        # the work floor, a bigger batch leaving the native crossover) are
        # the router doing its job, not a regression; the host-rung reasons
        # and remote-fallback stay armed
        "benign": frozenset({"ok", "small-batch", "work-floor",
                             "cpu-backend"}),
    },
    "session.sync": {
        # service/solver_service.py, both ends: a session round ships a
        # delta, or a full snapshot (initial upload, client-detected
        # journal drift, or a server resync demand by exception class).
        "rungs": ("delta", "resync"),
        "reasons": frozenset({
            "ok", "initial", "journal-gap", "opaque-delta",
            "ResyncRequired", "SessionExpired", "UnknownSession",
            "OutOfOrderDelta", OTHER_REASON,
        }),
        # a first upload for a NEW shape family (or one the client's
        # bounded family LRU evicted and re-registered) is expected
        # universe growth, not protocol drift — the same stance as the
        # compile ledger's first-of-family exemption. It neither fires nor
        # breaks the held delta streak.
        "benign": frozenset({"initial"}),
    },
    "decode.recheck": {
        # models/solver.py _compat_entry: the decoder's merged-requirement
        # re-check was provably skippable, or ran in full (and why the
        # exactness argument did not apply).
        "rungs": ("skip", "full"),
        "reasons": frozenset({
            "ok", "no-candidates", "disabled", "offering-keys",
            "group-key-overlap", "non-decomposable", OTHER_REASON,
        }),
    },
    "admission.tier": {
        # admission/plane.py: a live batch with priority markers collapsed
        # its gang-free tiers into ONE device dispatch with on-device tier
        # fencing (fused — deploy/README.md "Fused cluster round"), ran
        # the per-tier cascade (host rung, gang-interleaved, or
        # KARPENTER_FUSED_ROUND=0), or collapsed to the plain single
        # solve. The tier count is workload-driven, so every reason is
        # benign — the site exists for the mix, not the regression
        # detector.
        "rungs": ("fused", "cascade", "single"),
        "reasons": frozenset({
            "ok", "single-tier", "disabled", OTHER_REASON,
        }),
        "benign": frozenset({"ok", "single-tier", "disabled", OTHER_REASON}),
    },
    "admission.preempt": {
        # admission/preempt.py: one verdict per unschedulable high-tier pod
        # the preemption ladder examined — evictions shipped after a
        # confirming simulation, declined (probe/confirm said no), or
        # skipped before any counterfactual ran. Workload-driven declines
        # are benign; confirm-failed (probe-vs-host disagreement) and
        # probe-error stay armed.
        "rungs": ("confirmed", "declined", "skipped"),
        "reasons": frozenset({
            "ok", "no-victims", "policy-never", "no-feasible-node",
            "confirm-failed", "pdb-blocked", "ineligible-spec", "disabled",
            "probe-error", OTHER_REASON,
        }),
        "benign": frozenset({
            "no-victims", "policy-never", "no-feasible-node",
            "ineligible-spec", "disabled",
        }),
    },
    "admission.gang": {
        # admission/gangs.py via plane._solve_gang: one verdict per gang —
        # the whole group landed atomically, or the whole group
        # host-routed with a cause (never a partial bind). Capacity-driven
        # routes are benign; trial-error (the commit diverged from its
        # trial) stays armed.
        "rungs": ("atomic", "routed"),
        "reasons": frozenset({
            "ok", "infeasible", "budget-starved", "oversize", "trial-error",
            OTHER_REASON,
        }),
        "benign": frozenset({"infeasible", "budget-starved", "oversize"}),
    },
}

# RemoteSolver fallback reasons (karpenter_solver_remote_fallbacks_total):
# not a ladder site of their own, but the same bounded-cardinality stance —
# server exception classes outside this set clamp to "server-error" so a
# novel server bug can't mint unbounded label series (satellite of the
# session.sync enum; clamped in service/solver_service.py _fallback).
SOLVER_FALLBACK_REASONS = frozenset({
    "transport", "transport-retryable", "server-error",
    "ResyncRequired", "SessionExpired", "UnknownSession", "OutOfOrderDelta",
    "TenantBudgetExceeded", "CrossTenantBleed",
    "ValueError", "RuntimeError", "KeyError", "AssertionError",
})


# the shared env-knob trio (utils/envknobs.py — the same parser the
# service plane's knobs ride, so clamp/garbage behavior cannot drift)
from karpenter_tpu.utils.envknobs import env_float as _env_float  # noqa: E402
from karpenter_tpu.utils.envknobs import env_int as _env_int  # noqa: E402


def canonical_reason(site: str, reason) -> str:
    """Clamp ``reason`` into the site's closed enum (unknown → "other").
    Empty/None reads as "ok" — a rung taken cleanly needs no cause."""
    spec = SITES.get(site)
    r = str(reason) if reason else "ok"
    if spec is None or r in spec["reasons"]:
        return r
    return OTHER_REASON


def rung_rank(site: str, rung: str) -> int:
    """Position of ``rung`` in the site's best-first order (lower is
    better); unknown rungs rank past the end so comparisons stay total."""
    rungs = SITES.get(site, {}).get("rungs", ())
    try:
        return rungs.index(rung)
    except ValueError:
        return len(rungs)


def _resolve_registry(registry):
    from karpenter_tpu.obs import devplane

    return devplane._resolve_registry(registry)


# bounded per-tenant rung-mix views, mirroring the SloTracker cap: tenant
# ids are client-supplied and must not grow ledger memory without limit
_TENANT_CAP = 256


class DecisionLedger:
    """Process-wide ``(site, rung, reason)`` accounting + the streak state
    behind the rung-regression anomaly. One module instance
    (``DECISIONS``) is the production default; tests construct their own
    or ``reset()`` it."""

    def __init__(self, steady_after: int | None = None):
        self._lock = threading.Lock()
        self._counts: dict = {}  # (site, rung, reason) -> int
        self._last: dict = {}  # site -> (rung, reason)
        # site -> [held rung index, consecutive records at or above it]
        self._held: dict = {}
        self._tenants: "OrderedDict[str, dict]" = OrderedDict()
        self._rounds: deque = deque(
            maxlen=_env_int("KARPENTER_DECISION_RING", 64, minimum=1))
        self.steady_after = (
            steady_after if steady_after is not None
            else _env_int("KARPENTER_RUNG_STEADY_AFTER", 16, minimum=1)
        )
        # solve-quality account: shape family -> drift-detector state
        self._q: dict = {}
        self._q_series: deque = deque(maxlen=256)
        self.q_steady_after = _env_int("KARPENTER_QUALITY_STEADY_AFTER", 16, minimum=1)
        self.q_tol = _env_float("KARPENTER_QUALITY_DRIFT_TOL", 0.25)
        self.q_min_floor = _env_int("KARPENTER_QUALITY_MIN_FLOOR", 8,
                                    minimum=0)

    # -- the one hook every ladder site calls -----------------------------

    def record(self, site: str, rung: str, reason: str = "ok",
               registry=None, tenant: str | None = None) -> str:
        """One ladder verdict. Returns the canonical (possibly clamped)
        reason. Unknown sites/rungs raise — they are code constants."""
        spec = SITES.get(site)
        if spec is None:
            raise ValueError(f"unknown decision site {site!r}")
        if rung not in spec["rungs"]:
            raise ValueError(f"unknown rung {rung!r} for site {site}")
        reason = canonical_reason(site, reason)
        idx = spec["rungs"].index(rung)
        fire = None
        with self._lock:
            key = (site, rung, reason)
            self._counts[key] = self._counts.get(key, 0) + 1
            self._last[site] = (rung, reason)
            held = self._held.get(site)
            if held is None or idx < held[0]:
                # first sight, or an upgrade: the better rung starts a
                # fresh streak (first-sight exemption falls out here — no
                # prior streak exists to regress from)
                self._held[site] = [idx, 1]
            elif idx == held[0]:
                held[1] += 1
            elif reason in spec.get("benign", ()):
                # expected-growth / calibrated-routing downgrade (e.g. a
                # session's initial upload for a new shape family, a
                # small batch routing native mid-xla-streak): neither an
                # anomaly nor a streak break — the held rung survives the
                # interruption, so a REAL downgrade after it still fires
                pass
            else:
                if held[1] >= self.steady_after:
                    fire = (spec["rungs"][held[0]], held[1])
                self._held[site] = [idx, 1]
            if tenant is not None:
                mix = self._tenants.pop(tenant, None)
                if mix is None:
                    if len(self._tenants) >= _TENANT_CAP:
                        self._tenants.pop(next(iter(self._tenants)))
                    mix = {}
                self._tenants[tenant] = mix
                smix = mix.setdefault(site, {})
                smix[rung] = smix.get(rung, 0) + 1
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.counter(
            _m.DECISION_TOTAL,
            "ladder verdicts recorded by the decision ledger "
            "(one per site invocation)",
        ).inc(site=site, rung=rung, reason=reason)
        from karpenter_tpu.obs import trace as _trace

        tr = _trace.TRACER.current_trace()
        if tr is not None:
            tr.add_decision(site, rung, reason)
        if fire is not None:
            # the one bad round the flight recorder exists for: a site
            # that had settled on a top rung just downgraded — dump the
            # round that paid it (once; the new rung is now the held one)
            _trace.anomaly(
                "rung-regression", registry=reg, site=site,
                from_rung=fire[0], to_rung=rung, reason=reason,
                held=fire[1],
            )
        return reason

    # -- solve-quality account --------------------------------------------

    def observe_quality(self, nodes: int, floor: int, family=None,
                        registry=None) -> float:
        """One solve's node count vs. the solver's pods-cap floor (its
        demand lower bound). Returns the overhead ratio."""
        nodes = max(int(nodes), 0)
        floor = max(int(floor), 0)
        ratio = nodes / max(floor, 1)
        fam = str(family) if family is not None else "default"
        fire = None
        with self._lock:
            self._q_series.append({
                "family": fam, "nodes": nodes, "floor": floor,
                "ratio": round(ratio, 4), "at": time.time(),
            })
            if floor >= self.q_min_floor:
                ent = self._q.get(fam)
                if ent is None:
                    ent = self._q[fam] = {
                        "baseline": ratio, "streak": 0, "violating": False,
                    }
                if ratio < ent["baseline"]:
                    ent["baseline"] = ratio
                if ratio <= ent["baseline"] * (1.0 + self.q_tol):
                    ent["streak"] += 1
                    ent["violating"] = False
                else:
                    if (ent["streak"] >= self.q_steady_after
                            and not ent["violating"]):
                        fire = (ent["baseline"], ent["streak"])
                    ent["violating"] = True
                    ent["streak"] = 0
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.gauge(
            _m.SOLVE_OVERHEAD_RATIO,
            "per-solve nodes over the solver's pods-cap floor "
            "(1.0 = packed to the demand lower bound)",
        ).set(ratio, family=fam)
        if fire is not None:
            from karpenter_tpu.obs import trace as _trace

            _trace.anomaly(
                "solve-overhead-drift", registry=reg, family=fam,
                ratio=round(ratio, 4), baseline=round(fire[0], 4),
                held=fire[1],
            )
        return ratio

    # -- round summaries (fed by the tracer at round close) ---------------

    def note_round(self, trace) -> None:
        """Fold a closed round trace's decisions into the last-K ring the
        introspection surface serves."""
        decs = getattr(trace, "decisions", None)
        if not decs:
            return
        summary: dict = {}
        for (site, rung, reason), n in decs.items():
            srow = summary.setdefault(site, {})
            rrow = srow.setdefault(rung, {})
            rrow[reason] = rrow.get(reason, 0) + n
        with self._lock:
            self._rounds.append({
                "round": trace.name,
                "trace_id": trace.trace_id,
                "wall_start": trace.wall_start,
                "decisions": summary,
            })

    # -- reads -------------------------------------------------------------

    def counts(self) -> dict:
        """{(site, rung, reason): n} snapshot — the perf harness deltas
        this per row."""
        with self._lock:
            return dict(self._counts)

    def site_summary(self) -> dict:
        """{site: {last, held, rungs{rung{reason: n}}}} over the process
        lifetime."""
        with self._lock:
            items = list(self._counts.items())
            last = dict(self._last)
            held = {s: list(v) for s, v in self._held.items()}
        out: dict = {}
        for (site, rung, reason), n in items:
            srow = out.setdefault(site, {"rungs": {}})
            rrow = srow["rungs"].setdefault(rung, {})
            rrow[reason] = rrow.get(reason, 0) + n
        for site, srow in out.items():
            if site in last:
                srow["last"] = {"rung": last[site][0],
                                "reason": last[site][1]}
            hv = held.get(site)
            if hv is not None:
                rungs = SITES[site]["rungs"]
                srow["held"] = {"rung": rungs[hv[0]], "streak": hv[1]}
        return out

    def quality_summary(self) -> dict:
        with self._lock:
            series = list(self._q_series)
            fams = {
                f: {"baseline": round(e["baseline"], 4),
                    "streak": e["streak"], "violating": e["violating"]}
                for f, e in self._q.items()
            }
        return {"series": series, "families": fams}

    def tenant_mix(self) -> dict:
        with self._lock:
            return {t: {s: dict(r) for s, r in mix.items()}
                    for t, mix in self._tenants.items()}

    def rounds(self, k: int | None = None) -> list:
        with self._lock:
            rounds = list(self._rounds)
        return rounds[-k:] if k else rounds

    def clear(self):
        with self._lock:
            self._counts.clear()
            self._last.clear()
            self._held.clear()
            self._tenants.clear()
            self._rounds.clear()
            self._q.clear()
            self._q_series.clear()


DECISIONS = DecisionLedger()


def record_decision(site: str, rung: str, reason: str = "ok",
                    registry=None, tenant: str | None = None) -> str:
    return DECISIONS.record(site, rung, reason, registry=registry,
                            tenant=tenant)


def record_quality(nodes: int, floor: int, family=None,
                   registry=None) -> float:
    return DECISIONS.observe_quality(nodes, floor, family=family,
                                     registry=registry)


def note_round(trace) -> None:
    DECISIONS.note_round(trace)


def counts() -> dict:
    return DECISIONS.counts()


def rung_delta(before: dict, after: dict) -> dict:
    """{site: {rung: n}} of the records between two ``counts()`` snapshots
    — the per-row rung summary the perf harness and bench.py embed."""
    out: dict = {}
    for (site, rung, _reason), n in after.items():
        d = n - before.get((site, rung, _reason), 0)
        if d:
            srow = out.setdefault(site, {})
            srow[rung] = srow.get(rung, 0) + d
    return out


def introspect_snapshot(k: int = 16) -> dict:
    """The ``/introspect`` endpoint body: per-site rung mixes, the last-K
    rounds' rung summaries, the quality account, per-tenant rung mixes,
    the flight recorder's retained anomalous rounds, the replay capsules
    written by this process (obs/capsule.py), and the fleet ledger's
    timeline section (obs/timeline.py)."""
    from karpenter_tpu.obs import capsule as _capsule
    from karpenter_tpu.obs import timeline as _timeline
    from karpenter_tpu.obs import trace as _trace

    anomalies = []
    for tr in _trace.RECORDER.traces():
        if not tr.anomalies:
            continue
        anomalies.append({
            "round": tr.name,
            "trace_id": tr.trace_id,
            "kinds": [kind for kind, _, _ in tr.anomalies],
            "dump": tr.dump_path,
            "capsule": tr.capsule_path,
        })
    return {
        "sites": DECISIONS.site_summary(),
        "rounds": DECISIONS.rounds(k),
        "quality": DECISIONS.quality_summary(),
        "tenants": DECISIONS.tenant_mix(),
        "anomalies": anomalies[-k:],
        "capsules": _capsule.index(k),
        "timeline": _timeline.timeline_snapshot(k),
    }


def reset():
    """Test isolation: clear the ledger and re-read the env knobs."""
    DECISIONS.clear()
    DECISIONS.steady_after = _env_int("KARPENTER_RUNG_STEADY_AFTER", 16, minimum=1)
    DECISIONS.q_steady_after = _env_int("KARPENTER_QUALITY_STEADY_AFTER", 16, minimum=1)
    DECISIONS.q_tol = _env_float("KARPENTER_QUALITY_DRIFT_TOL", 0.25)
    DECISIONS.q_min_floor = _env_int("KARPENTER_QUALITY_MIN_FLOOR", 8,
                                     minimum=0)
    return DECISIONS
