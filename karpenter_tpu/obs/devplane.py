"""Device-plane telemetry: compile ledger, padding-waste accounting, SLO.

The flight recorder (obs/trace.py) explains where a round's *host* wall
clock goes; this module covers the three blind spots the device plane
still had:

- **Compile ledger.** Every jit family the product dispatches — the
  packed solve kernel (``solve.kernel``), the batched consolidation probe
  (``probe.kernel``), the mesh-sharded solve (``mesh.shard``) — reports
  each dispatch here with the executable-identifying key (shape bucket +
  static params). A key never seen before is a **cold compile**: the
  dispatch wall time (which includes XLA trace+compile for a cold key)
  lands in ``karpenter_compile_seconds{family}``,
  ``karpenter_compile_events_total{family}`` counts it, and
  ``karpenter_compile_families_resident{family}`` gauges the live key
  cardinality. A cold compile that interrupts a long warm streak (the
  key universe had stopped growing — steady state) fires the
  ``cold-compile-in-steady-state`` anomaly, so the flight recorder dumps
  the round that paid the surprise compile. The streak threshold is
  ``KARPENTER_COMPILE_STEADY_AFTER`` (default 16 warm dispatches).
- **Padding-waste accounting.** Every pow-2-ladder dispatch (solver bin
  axis, probe row chunks, mesh shard axes) records padded vs. actual
  extents; the wasted-work fraction ``1 - actual/padded`` feeds the
  ``karpenter_pad_waste_ratio{site}`` histogram and the ``STATS``
  aggregate the perf harness surfaces per row (``pad_waste_ratio``).
- **SLO trackers.** Named rolling windows (:class:`SloTracker`) over
  request durations/outcomes: ``karpenter_solver_request_seconds
  {outcome}`` histograms, rolling p50/p95/p99 gauges
  (``karpenter_solver_request_quantile_seconds{slo,q}``), and an
  error-budget burn counter (``karpenter_slo_error_budget_burn_total
  {slo}``) that ticks for every objective-violating request (error
  outcome, or latency above the tracker's latency SLO). The
  ``/slo`` endpoint on the metrics server (karpenter_tpu/__main__.py
  ``serve_metrics``) serves ``slo_snapshot()`` + the ledger summary as
  JSON. The gRPC solver service (service/solver_service.py) is the first
  producer: one linked server-side round trace per request, client trace
  ids carried in request meta.

All hooks are host-side by construction: graftlint's GL403 rule
(analysis/tracing.py) fails the tier-1 gate if any of them becomes
reachable from jit/pallas-traced code. Metric families are documented in
deploy/README.md ("Device-plane & SLO telemetry").
"""

from __future__ import annotations

import threading
from collections import deque

from karpenter_tpu.utils.envknobs import env_int

__all__ = [
    "CompileLedger",
    "LEDGER",
    "SloTracker",
    "STATS",
    "record_bin_growth",
    "record_dispatch",
    "record_padding",
    "record_shard_balance",
    "record_shard_fallback",
    "record_shard_overlap",
    "record_shard_repair",
    "slo_tracker",
    "slo_snapshot",
    "reset",
]

# process-wide accounting the perf harness deltas per solve
# (snapshot-and-diff readers). Mutations hold _STATS_LOCK: dict-entry +=
# is a read-modify-write that interleaves across the solver service's
# gRPC worker threads, and a lost increment silently undercounts the
# /slo summary and the perf-row deltas.
STATS = {
    "cold_compiles": 0,
    "compile_ms": 0.0,
    "warm_dispatches": 0,
    "pad_dispatches": 0,
    "pad_cells_actual": 0.0,
    "pad_cells_padded": 0.0,
    # partitioned mesh solve (parallel/mesh.py): host tensorize wall time
    # hidden under in-flight shard programs (the pipeline's overlap),
    # straddling pods re-packed by the bounded repair pass, and fallbacks
    # out of the partitioned rung
    "shard_overlap_ms": 0.0,
    "shard_repair_pods": 0,
    "shard_fallbacks": 0,
    # max/mean hybrid shard weight of the most recent partition plan
    # (1.0 = perfectly balanced; the ROADMAP's next mesh lever)
    "shard_balance_ratio": 0.0,
    # on-device doublings of the solve's merged bin axis (the solver's
    # doubled re-run) — the fused-round lever that keeps axis exhaustion
    # off the host repair path; perf rows surface it as bin_growth_events
    "bin_growths": 0,
    # total device seconds across EVERY dispatch (cold + warm) — the
    # ground-truth total the fleet ledger's per-tenant billing
    # (obs/timeline.py /usage) must sum to within rounding
    "dispatch_seconds": 0.0,
}
_STATS_LOCK = threading.Lock()


def _env_steady_after() -> int:
    return env_int("KARPENTER_COMPILE_STEADY_AFTER", 16, minimum=1)


def _resolve_registry(registry):
    """Explicit registry > the open round's registry > the process
    default — the same resolution order ``anomaly()`` uses, so ledger
    metrics land where the round's other families do."""
    if registry is not None:
        return registry
    from karpenter_tpu.obs import trace as _trace

    tr = _trace.TRACER.current_trace()
    if tr is not None and tr.registry is not None:
        return tr.registry
    from karpenter_tpu.operator import metrics as _m

    return _m.REGISTRY


class CompileLedger:
    """Which jit executables exist, and when a new one appears.

    ``record_dispatch`` is called host-side after every kernel dispatch
    with the family name and the key that identifies the compiled
    executable (shape bucket + static params — the same tuple the
    kernel caches key on). First sight of a key is a cold-compile event;
    every other dispatch extends the warm streak that arms the
    steady-state anomaly."""

    def __init__(self, steady_after: int | None = None):
        self._lock = threading.Lock()
        self._keys: dict = {}  # family -> set of executable keys
        self._warm_streak = 0
        self.steady_after = (
            steady_after if steady_after is not None else _env_steady_after()
        )

    def record_dispatch(self, family: str, key, seconds: float,
                        registry=None, tenant: str | None = None) -> bool:
        """Note one dispatch; returns True when it was a cold compile.
        ``tenant`` attributes the dispatch's device seconds to a tenant on
        the fleet ledger's billing plane (obs/timeline.py); None lets the
        ledger resolve the open round's tenant attr (the solver service's
        per-session rounds) before falling back to "untenanted"."""
        with self._lock:
            seen = self._keys.setdefault(family, set())
            cold = key not in seen
            first_of_family = cold and not seen
            if cold:
                seen.add(key)
                streak, self._warm_streak = self._warm_streak, 0
                resident = len(seen)
            else:
                self._warm_streak += 1
        from karpenter_tpu.obs import timeline as _timeline

        _timeline.record_billing(family, seconds, tenant=tenant,
                                 registry=registry)
        if not cold:
            with _STATS_LOCK:
                STATS["warm_dispatches"] += 1
                STATS["dispatch_seconds"] += seconds
            return False
        with _STATS_LOCK:
            STATS["cold_compiles"] += 1
            STATS["compile_ms"] += seconds * 1000.0
            STATS["dispatch_seconds"] += seconds
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        reg.counter(
            _m.COMPILE_EVENTS,
            "cold-compile events observed by the device-plane compile ledger",
        ).inc(family=family)
        reg.histogram(
            _m.COMPILE_SECONDS,
            "wall time of dispatches that paid an XLA trace+compile",
        ).observe(seconds, family=family)
        reg.gauge(
            _m.COMPILE_FAMILIES,
            "live executable cardinality per jit family",
        ).set(resident, family=family)
        if streak >= self.steady_after and not first_of_family:
            # the key universe had stopped growing and a compile still
            # happened: the one bad round the flight recorder exists for.
            # A family's FIRST key ever is exempt — a subsystem coming
            # online late (the first probe round, the first mesh solve) is
            # expected universe growth, not churn (the same stance as the
            # snapshot-rebuild trigger's first-build exemption)
            from karpenter_tpu.obs import trace as _trace

            _trace.anomaly(
                "cold-compile-in-steady-state", registry=reg, family=family,
                warm_streak=streak, compile_ms=round(seconds * 1000.0, 3),
            )
        return True

    def families(self) -> dict:
        """family -> resident executable count."""
        with self._lock:
            return {fam: len(keys) for fam, keys in self._keys.items()}

    def warm_streak(self) -> int:
        with self._lock:
            return self._warm_streak

    def snapshot(self) -> dict:
        with _STATS_LOCK:
            cold, ms = STATS["cold_compiles"], STATS["compile_ms"]
        return {
            "families": self.families(),
            "warm_streak": self.warm_streak(),
            "steady_after": self.steady_after,
            "cold_compiles": cold,
            "compile_ms": round(ms, 3),
        }

    def clear(self):
        with self._lock:
            self._keys.clear()
            self._warm_streak = 0


LEDGER = CompileLedger()


def record_dispatch(family: str, key, seconds: float, registry=None,
                    tenant: str | None = None) -> bool:
    return LEDGER.record_dispatch(family, key, seconds, registry=registry,
                                  tenant=tenant)


def record_padding(site: str, actual, padded, registry=None) -> float:
    """One pow-2-ladder dispatch's padded vs. actual work extents (cell
    counts, e.g. G*T*B vs Gp*Tp*Bp). Returns the wasted-work fraction."""
    actual = max(float(actual), 0.0)
    padded = max(float(padded), 0.0)
    ratio = 0.0 if padded <= 0.0 else min(max(1.0 - actual / padded, 0.0), 1.0)
    with _STATS_LOCK:
        STATS["pad_dispatches"] += 1
        STATS["pad_cells_actual"] += actual
        STATS["pad_cells_padded"] += padded
    from karpenter_tpu.operator import metrics as _m

    _resolve_registry(registry).histogram(
        _m.PAD_WASTE_RATIO,
        "wasted-work fraction of pow-2-padded device dispatches "
        "(1 - actual/padded extents)",
        buckets=_m.PAD_WASTE_BUCKETS,
    ).observe(ratio, site=site)
    return ratio


def record_bin_growth() -> None:
    """One on-device doubling of a solve's merged bin axis (the doubled
    re-run in models/solver.py ``_run_and_decode``): the estimated axis
    ran dry and growth stayed on the device instead of routing the
    remainder through the host loop."""
    with _STATS_LOCK:
        STATS["bin_growths"] += 1


def record_shard_overlap(seconds: float, registry=None) -> None:
    """Host tensorize wall time of one partitioned mesh solve that ran
    while earlier shards' programs were already in flight — the pipelined
    shard.tensorize-under-shard.block overlap, counted so the MULTICHIP
    rows can show the pipeline engaged rather than inferring it from
    span arithmetic."""
    seconds = max(float(seconds), 0.0)
    with _STATS_LOCK:
        STATS["shard_overlap_ms"] += seconds * 1000.0
    from karpenter_tpu.operator import metrics as _m

    _resolve_registry(registry).counter(
        _m.SHARD_OVERLAP_SECONDS,
        "host shard-tensorize seconds hidden under in-flight shard solves "
        "(partitioned mesh pipeline)",
    ).inc(seconds)


def record_shard_repair(pods: int, registry=None) -> None:
    """Straddling pods the partitioned merge's bounded host repair pass
    re-packed (parallel/mesh.py _repair_merged)."""
    pods = max(int(pods), 0)
    if not pods:
        return
    with _STATS_LOCK:
        STATS["shard_repair_pods"] += pods
    from karpenter_tpu.operator import metrics as _m

    _resolve_registry(registry).counter(
        _m.SHARD_REPAIR_PODS,
        "straddling pods re-packed by the partitioned mesh repair pass",
    ).inc(pods)


def record_shard_balance(ratio: float, registry=None) -> None:
    """Shard-balance quality of one partition plan: max/mean hybrid shard
    weight (parallel/mesh.py plan_shards). 1.0 is a perfectly balanced
    partition; the hybrid weight bounds it at ~2x without minimizing it,
    and this gauge is the surface the ROADMAP's balance lever reads."""
    ratio = max(float(ratio), 0.0)
    with _STATS_LOCK:
        STATS["shard_balance_ratio"] = ratio
    from karpenter_tpu.operator import metrics as _m

    _resolve_registry(registry).gauge(
        _m.SHARD_BALANCE_RATIO,
        "max/mean shard weight of the most recent partitioned mesh plan",
    ).set(ratio)


def record_shard_fallback(reason: str, registry=None) -> None:
    """One abandonment of the partitioned mesh rung (repair bound
    exceeded, etc.) — the solve fell back to an exact slower path."""
    with _STATS_LOCK:
        STATS["shard_fallbacks"] += 1
    from karpenter_tpu.operator import metrics as _m

    _resolve_registry(registry).counter(
        _m.SHARD_FALLBACKS,
        "partitioned mesh solves that fell back to an exact slower path",
    ).inc(reason=reason)


class SloTracker:
    """Rolling request-latency/outcome window with quantiles and an
    error-budget burn counter.

    ``observe`` records one request: the duration lands in the
    ``karpenter_solver_request_seconds{outcome}`` histogram, the rolling
    window's p50/p95/p99 refresh their gauges, and a request that
    violates the objective burns error budget. Violation = the outcome is
    a failure (``error``/``rejected`` — ``resync`` is a protocol
    renegotiation, not a failure), or the duration exceeds ``latency_slo``
    seconds when one is set. ``snapshot()`` is the ``/slo`` endpoint's
    JSON body.

    Multi-tenant surfaces: passing ``tenant=`` additionally maintains a
    per-tenant rolling window (quantile gauges and
    ``karpenter_solver_tenant_requests_total{slo,tenant,outcome}`` carry
    the tenant label) and the snapshot gains a ``tenants`` section — the
    ISSUE-7 per-tenant SLO plane rides the same tracker rather than a new
    one."""

    # outcomes that do NOT burn error budget: a resync demand is the delta
    # protocol renegotiating, not a failed request
    _OK_OUTCOMES = ("ok", "resync")
    # tenant sub-windows are bounded: tenant ids are client-supplied, and
    # a fleet with ephemeral tenant names must not grow tracker memory
    # without limit — the least-recently-observed tenant's window drops at
    # the cap (its already-emitted metric series remain on the registry;
    # operators with unbounded tenant churn should also bound scrape
    # cardinality upstream)
    _TENANT_CAP = 256

    def __init__(self, name: str, objective: float = 0.99,
                 latency_slo: float | None = None, window: int = 512):
        self.name = name
        self.objective = objective
        self.latency_slo = latency_slo
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=max(window, 16))
        self._count = 0
        self._errors = 0
        self._burned = 0
        # tenant -> {window, count, errors, burned} rolling sub-views
        self._tenants: dict = {}

    def observe(self, seconds: float, outcome: str = "ok", registry=None,
                tenant: str | None = None):
        violated = outcome not in self._OK_OUTCOMES or (
            self.latency_slo is not None and seconds > self.latency_slo
        )
        t_samples = None
        evicted = None
        with self._lock:
            self._window.append(float(seconds))
            self._count += 1
            if outcome not in self._OK_OUTCOMES:
                self._errors += 1
            if violated:
                self._burned += 1
            samples = sorted(self._window)
            if tenant is not None:
                tv = self._tenants.pop(tenant, None)
                if tv is None:
                    if len(self._tenants) >= self._TENANT_CAP:
                        # dict order is recency order (pop+reinsert below)
                        evicted = next(iter(self._tenants))
                        self._tenants.pop(evicted)
                    tv = {
                        "window": deque(maxlen=256), "count": 0,
                        "errors": 0, "burned": 0,
                    }
                self._tenants[tenant] = tv
                tv["window"].append(float(seconds))
                tv["count"] += 1
                if outcome not in self._OK_OUTCOMES:
                    tv["errors"] += 1
                if violated:
                    tv["burned"] += 1
                t_samples = sorted(tv["window"])
        from karpenter_tpu.operator import metrics as _m

        reg = _resolve_registry(registry)
        if evicted is not None:
            # the LRU-dropped tenant's billing/quantile series retire with
            # its sub-window (obs/timeline.py drop_tenant) — the bounded-
            # cardinality stance extended to the metric registry
            from karpenter_tpu.obs import timeline as _timeline

            _timeline.drop_tenant(evicted, slo=self.name, registry=reg)
        reg.histogram(
            _m.SOLVER_REQUEST_SECONDS,
            "solver-service request durations by outcome",
        ).observe(seconds, outcome=outcome)
        if violated:
            reg.counter(
                _m.SLO_BUDGET_BURN,
                "requests that violated the SLO objective (errors, or "
                "latency above the tracker's latency SLO)",
            ).inc(slo=self.name)
        q = reg.gauge(
            _m.SOLVER_REQUEST_QUANTILE,
            "rolling request-latency quantiles over the SLO window",
        )
        for label, v in self._quantiles(samples).items():
            q.set(v, slo=self.name, q=label)
        if tenant is not None:
            reg.counter(
                _m.SOLVER_TENANT_REQUESTS,
                "solver-service requests by tenant and outcome",
            ).inc(slo=self.name, tenant=tenant, outcome=outcome)
            for label, v in self._quantiles(t_samples).items():
                q.set(v, slo=self.name, tenant=tenant, q=label)

    def tenant_quantiles(self, tenant: str) -> dict:
        """Rolling {p50,p95,p99} (seconds) of one tenant's sub-window —
        the perf harness's per-tenant latency read."""
        with self._lock:
            tv = self._tenants.get(tenant)
            samples = sorted(tv["window"]) if tv is not None else []
        return self._quantiles(samples)

    @staticmethod
    def _quantiles(samples: list) -> dict:
        if not samples:
            return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
        n = len(samples)
        return {
            label: samples[min(int(frac * n), n - 1)]
            for label, frac in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))
        }

    def snapshot(self) -> dict:
        with self._lock:
            samples = sorted(self._window)
            count, errors, burned = self._count, self._errors, self._burned
            tenants = {
                t: (sorted(tv["window"]), tv["count"], tv["errors"],
                    tv["burned"])
                for t, tv in self._tenants.items()
            }
        qs = self._quantiles(samples)
        tenant_view = {}
        for t, (t_samples, t_count, t_errors, t_burned) in tenants.items():
            tq = self._quantiles(t_samples)
            tenant_view[t] = {
                "count": t_count,
                "errors": t_errors,
                "budget_burned": t_burned,
                "p50_ms": round(tq["p50"] * 1000.0, 3),
                "p95_ms": round(tq["p95"] * 1000.0, 3),
                "p99_ms": round(tq["p99"] * 1000.0, 3),
            }
        error_rate = errors / count if count else 0.0
        # budget burn: fraction of the window's allowed violations spent —
        # >1.0 means the objective is being missed
        allowed = max(count, 1) * max(1.0 - self.objective, 1e-9)
        return {
            "count": count,
            "errors": errors,
            "error_rate": round(error_rate, 6),
            "objective": self.objective,
            "latency_slo_ms": (
                round(self.latency_slo * 1000.0, 3)
                if self.latency_slo is not None else None
            ),
            "budget_burned": burned,
            "budget_burn_ratio": round(burned / allowed, 4),
            "window": len(samples),
            "p50_ms": round(qs["p50"] * 1000.0, 3),
            "p95_ms": round(qs["p95"] * 1000.0, 3),
            "p99_ms": round(qs["p99"] * 1000.0, 3),
            **({"tenants": tenant_view} if tenant_view else {}),
        }


_SLO_LOCK = threading.Lock()
_SLO: dict = {}


def slo_tracker(name: str, **kw) -> SloTracker:
    """Get-or-create the named tracker (constructor kwargs apply only on
    first creation)."""
    with _SLO_LOCK:
        t = _SLO.get(name)
        if t is None:
            t = _SLO[name] = SloTracker(name, **kw)
        return t


def slo_snapshot() -> dict:
    """The /slo endpoint body: every tracker's rolling view plus the
    compile ledger summary."""
    with _SLO_LOCK:
        trackers = list(_SLO.values())
    return {
        "slo": {t.name: t.snapshot() for t in trackers},
        "compile_ledger": LEDGER.snapshot(),
    }


def reset():
    """Test isolation: clear the ledger, the SLO trackers, and STATS."""
    LEDGER.clear()
    LEDGER.steady_after = _env_steady_after()
    with _SLO_LOCK:
        _SLO.clear()
    with _STATS_LOCK:
        STATS.update(
            cold_compiles=0, compile_ms=0.0, warm_dispatches=0,
            pad_dispatches=0, pad_cells_actual=0.0, pad_cells_padded=0.0,
            shard_overlap_ms=0.0, shard_repair_pods=0, shard_fallbacks=0,
            shard_balance_ratio=0.0, bin_growths=0, dispatch_seconds=0.0,
        )
