"""Test-double CloudProvider.

Mirrors pkg/cloudprovider/fake/cloudprovider.go:47-158: records calls,
supports injectable next-errors and a create budget, and synthesizes
NodeClaims from the cheapest compatible offering.
"""

from __future__ import annotations

import copy
import threading

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import new_uid
from karpenter_tpu.cloudprovider.catalog import kwok_catalog
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    cheapest_effective_offering,
)
from karpenter_tpu.scheduling import Requirements, node_selector_requirements


class FakeCloudProvider(CloudProvider):
    def __init__(self, instance_types=None):
        self.instance_types = instance_types if instance_types is not None else kwok_catalog()
        self.created: dict = {}  # provider_id -> NodeClaim
        self.create_calls: list = []
        self.delete_calls: list = []
        self.next_create_err: Exception | None = None
        self.next_delete_err: Exception | None = None
        self.next_get_err: Exception | None = None
        self.allowed_create_calls: int | None = None
        self.drifted: str = ""  # reason returned by is_drifted for all claims
        self._lock = threading.Lock()

    def name(self) -> str:
        return "fake"

    def get_instance_types(self, node_pool) -> list:
        return list(self.instance_types)

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        with self._lock:
            if self.next_create_err is not None:
                err, self.next_create_err = self.next_create_err, None
                raise err
            if self.allowed_create_calls is not None and len(self.create_calls) >= self.allowed_create_calls:
                raise InsufficientCapacityError("create budget exhausted")
            self.create_calls.append(node_claim)
            reqs = node_selector_requirements(node_claim.spec.requirements)
            choice = self._cheapest(reqs, node_claim.spec.resource_requests)
            if choice is None:
                raise InsufficientCapacityError("no compatible instance type")
            it, offering = choice
            claim = copy.deepcopy(node_claim)
            claim.status.provider_id = f"fake://{new_uid('instance')}"
            claim.status.capacity = dict(it.capacity)
            claim.status.allocatable = dict(it.allocatable())
            # offering-derived labels are authoritative: they reflect where
            # the instance actually launched, so they spread last
            offering_labels = {
                wk.INSTANCE_TYPE_LABEL: it.name,
                wk.TOPOLOGY_ZONE_LABEL: offering.zone,
                wk.CAPACITY_TYPE_LABEL: offering.capacity_type,
            }
            claim.metadata.labels = {
                **node_claim.metadata.labels,
                **{k: v for k, v in reqs.labels().items() if k not in offering_labels},
                **offering_labels,
            }
            self.created[claim.status.provider_id] = claim
            return claim

    def _cheapest(self, reqs: Requirements, requests: dict):
        # cheapest EFFECTIVE offering (the shared launch-placement rule)
        return cheapest_effective_offering(self.instance_types, reqs,
                                           requests)

    def delete(self, node_claim: NodeClaim) -> None:
        with self._lock:
            if self.next_delete_err is not None:
                err, self.next_delete_err = self.next_delete_err, None
                raise err
            self.delete_calls.append(node_claim)
            if node_claim.status.provider_id not in self.created:
                raise NodeClaimNotFoundError(node_claim.status.provider_id)
            del self.created[node_claim.status.provider_id]

    def get(self, provider_id: str) -> NodeClaim:
        with self._lock:
            if self.next_get_err is not None:
                err, self.next_get_err = self.next_get_err, None
                raise err
            claim = self.created.get(provider_id)
            if claim is None:
                raise NodeClaimNotFoundError(provider_id)
            return claim

    def list(self) -> list:
        with self._lock:
            return list(self.created.values())

    def is_drifted(self, node_claim) -> str:
        return self.drifted
