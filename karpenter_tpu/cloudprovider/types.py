"""Cloud-provider SPI: the only seam to the outside world.

Semantics from the reference's pkg/cloudprovider/types.go: the CloudProvider
interface :46-69, InstanceType/Offerings catalog model :73-102/:214-297,
SatisfiesMinValues :165-199, Truncate :203-212, and the typed errors
:299-387. The catalog model doubles as the source for the device-side
allocatable/price tensors (ops/tensorize.py).

Interruption-risk contract (spot resilience, deploy/README.md "Spot
resilience"): every :class:`Offering` may carry a per-offering
``interruption_risk`` signal in [0, 1] — the provider's estimate of the
probability the capacity is reclaimed within the planning horizon.
``None`` means UNKNOWN (no signal; consumers must stay conservative —
under λ > 0 an unknown risk prices at the ``KARPENTER_SPOT_RISK_DEFAULT``
prior so unscored capacity is never systematically preferred), ``0.0``
means known-stable (on-demand). The risk never gates feasibility;
it discounts price: :func:`effective_price` is
``price × (1 + λ·risk)`` with ``λ = KARPENTER_SPOT_RISK_LAMBDA``
(default 0 — risk-blind, bit-identical to nominal pricing). The same
formula is tensorized into the device price matrices at snapshot build
(ops/tensorize.py), so provisioning, the consolidation probe ladders,
and the replacement price filters are all risk-aware through ONE number,
with zero new dispatch paths. Interruption NOTICES (the two-minute
warning) arrive through :meth:`CloudProvider.interruption_notices`; the
disruption controller marks the node and the ``InterruptionDrain``
method drains it proactively (controllers/disruption/methods.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling import Requirement, Requirements, IN
from karpenter_tpu.utils import resources as resutil

SPOT_REQUIREMENT = Requirements(Requirement(wk.CAPACITY_TYPE_LABEL, IN, [wk.CAPACITY_TYPE_SPOT]))
ON_DEMAND_REQUIREMENT = Requirements(
    Requirement(wk.CAPACITY_TYPE_LABEL, IN, [wk.CAPACITY_TYPE_ON_DEMAND])
)


@dataclass
class Offering:
    """One (zone, capacity-type) purchase option (types.go:214-225).

    ``interruption_risk`` is the provider's per-offering reclaim-risk
    signal in [0, 1]; ``None`` = unknown (module docstring contract)."""

    requirements: Requirements
    price: float
    available: bool = True
    interruption_risk: float | None = None

    @property
    def zone(self) -> str:
        r = self.requirements.get_req(wk.TOPOLOGY_ZONE_LABEL)
        return next(iter(r.values), "") if not r.complement else ""

    @property
    def capacity_type(self) -> str:
        r = self.requirements.get_req(wk.CAPACITY_TYPE_LABEL)
        return next(iter(r.values), "") if not r.complement else ""


def risk_lambda() -> float:
    """The risk-discount weight λ (``KARPENTER_SPOT_RISK_LAMBDA``, ≥ 0;
    default 0 = risk-blind). Read per call so a perf harness can flip it
    between legs without rebuilding the process."""
    from karpenter_tpu.utils.envknobs import env_float

    return env_float("KARPENTER_SPOT_RISK_LAMBDA", 0.0, minimum=0.0)


def default_risk() -> float:
    """The prior an UNKNOWN risk (``interruption_risk=None``) prices at
    under λ > 0 (``KARPENTER_SPOT_RISK_DEFAULT``, default 0). Without a
    prior, unscored capacity would price as known-stable and every λ > 0
    consumer would systematically anti-select TOWARD the pools the
    provider could not vouch for; operators on partially-instrumented
    providers set a mid-band prior (e.g. 0.3) to keep the conservative
    stance. The default stays 0 so the λ=0 parity and existing λ > 0
    behavior are unchanged unless opted in."""
    from karpenter_tpu.utils.envknobs import env_float

    return env_float("KARPENTER_SPOT_RISK_DEFAULT", 0.0, minimum=0.0)


def effective_price(offering: Offering, lam: float | None = None) -> float:
    """Risk-discounted effective price: ``price × (1 + λ·risk)``.

    λ=0 (the default) — or a zero risk — returns the nominal price
    UNCHANGED (the same float object path, no multiply), which is what
    makes the λ=0 parity pin exact: a risk-bearing catalog under λ=0
    prices bit-identically to a risk-free one. An UNKNOWN risk prices at
    the :func:`default_risk` prior (default 0)."""
    if lam is None:
        lam = risk_lambda()
    if lam <= 0.0:
        return offering.price
    risk = offering.interruption_risk
    if risk is None:
        risk = default_risk()
    if not risk:
        return offering.price
    return offering.price * (1.0 + lam * risk)


class Offerings(list):
    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o
            for o in self
            if reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(
            reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) for o in self
        )

    def cheapest(self) -> Offering:
        return min(self, key=lambda o: o.price)

    def most_expensive(self) -> Offering:
        return max(self, key=lambda o: o.price)

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Spot-aware worst-case launch price (types.go:276-297)."""
        if reqs.get_req(wk.CAPACITY_TYPE_LABEL).has(wk.CAPACITY_TYPE_SPOT):
            spot = self.compatible(reqs).compatible(SPOT_REQUIREMENT)
            if spot:
                return spot.most_expensive().price
        if reqs.get_req(wk.CAPACITY_TYPE_LABEL).has(wk.CAPACITY_TYPE_ON_DEMAND):
            od = self.compatible(reqs).compatible(ON_DEMAND_REQUIREMENT)
            if od:
                return od.most_expensive().price
        return math.inf


@dataclass
class InstanceTypeOverhead:
    kube_reserved: dict = field(default_factory=dict)
    system_reserved: dict = field(default_factory=dict)
    eviction_threshold: dict = field(default_factory=dict)

    def total(self) -> dict:
        return resutil.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


class InstanceType:
    """Properties of a potential node (types.go:73-102)."""

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: Offerings,
        capacity: dict,
        overhead: InstanceTypeOverhead | None = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = offerings
        self.capacity = capacity
        self.overhead = overhead or InstanceTypeOverhead()
        self._allocatable = None

    def allocatable(self) -> dict:
        if self._allocatable is None:
            self._allocatable = resutil.subtract(self.capacity, self.overhead.total())
        return self._allocatable

    def __repr__(self):
        return f"InstanceType({self.name})"


def _cheapest_available_price(it: InstanceType, reqs: Requirements,
                              lam: float | None = None) -> float:
    ofs = it.offerings.available().compatible(reqs)
    if not ofs:
        return math.inf
    # risk-aware: the ordering prefers low-risk capacity once λ > 0 and
    # is bit-identical to the nominal order at λ=0 (effective_price is
    # the identity there)
    if lam is None:
        lam = risk_lambda()
    return min(effective_price(o, lam) for o in ofs)


def order_by_price(its, reqs: Requirements) -> list:
    """Cheapest available+compatible offering first; name tiebreak
    (types.go OrderByPrice:104). Risk-aware through
    :func:`effective_price` (λ=0 keeps the nominal order); λ is read
    once per sort, not once per key evaluation."""
    lam = risk_lambda()
    return sorted(
        its,
        key=lambda it: (_cheapest_available_price(it, reqs, lam), it.name))


def cheapest_effective_offering(its, reqs: Requirements,
                                requests: dict | None = None):
    """``(InstanceType, Offering)`` with the minimal EFFECTIVE price
    among available offerings compatible with ``reqs`` (full per-type
    check incl. resource fit), or None. The ONE launch-placement rule the
    kwok and fake providers share: risk-aware under λ > 0, the nominal
    cheapest bit-identically at λ=0."""
    lam = risk_lambda()
    best = best_eff = None
    for it in its:
        if not instance_type_compatible(it, reqs, requests):
            continue
        for o in it.offerings.available().compatible(reqs):
            eff = effective_price(o, lam)
            if best is None or eff < best_eff:
                best, best_eff = (it, o), eff
    return best


def compatible_instance_types(its, reqs: Requirements) -> list:
    """Instance types with at least one available offering compatible with
    reqs (types.go Compatible:124)."""
    return [it for it in its if it.offerings.available().has_compatible(reqs)]


def instance_type_compatible(it: InstanceType, reqs: Requirements, requests: dict | None = None) -> bool:
    """Full per-type check used by the scheduler's filter
    (scheduling/nodeclaim.go filterInstanceTypesByRequirements:242):
    requirement overlap (two-way Intersects — custom labels the pod demands
    but the type doesn't define become node labels, so they don't filter
    here) ∧ resource fit ∧ an available compatible offering."""
    if it.requirements.intersects(reqs) is not None:
        return False
    if requests is not None and not resutil.fits(requests, it.allocatable()):
        return False
    return it.offerings.available().has_compatible(reqs)


def filter_instance_types(its, reqs: Requirements, requests: dict | None = None) -> list:
    return [it for it in its if instance_type_compatible(it, reqs, requests)]


def satisfies_min_values(its, reqs: Requirements):
    """(min needed instance types, error) per types.go:165-199 — walks the
    (pre-sorted) list accumulating distinct values per minValues key until
    every floor is met."""
    if not reqs.has_min_values():
        return 0, None
    values_for_key: dict = {}
    min_keys = [r.key for r in reqs.values() if r.min_values is not None]
    incompatible = None
    for i, it in enumerate(its):
        for key in min_keys:
            values_for_key.setdefault(key, set()).update(it.requirements.get_req(key).values)
        incompatible = next(
            (
                k
                for k in min_keys
                if len(values_for_key.get(k, ())) < (reqs.get_req(k).min_values or 0)
            ),
            None,
        )
        if incompatible is None:
            return i + 1, None
    return len(list(its)), f'minValues requirement is not met for "{incompatible}"'


def truncate_instance_types(its, reqs: Requirements, max_items: int):
    """(truncated list, error) — price-ordered prefix of max_items, rejected
    if it breaks minValues (types.go Truncate:203)."""
    truncated = order_by_price(its, reqs)[:max_items]
    if reqs.has_min_values():
        _, err = satisfies_min_values(truncated, reqs)
        if err:
            return list(its), f"validating minValues, {err}"
    return truncated, None


# ---------------------------------------------------------------------------
# typed errors (types.go:299-387)


class NodeClaimNotFoundError(Exception):
    pass


class InsufficientCapacityError(Exception):
    pass


class NodeClassNotReadyError(Exception):
    pass


class CatalogView:
    """The ONE node→(instance type, offering) resolution walk: nodepool
    label → pool → per-pool catalog memo → instance-type label →
    (zone, capacity-type) offering match. Shared by the chaos injector's
    risk sampling (cloudprovider/chaos.py), InterruptionDrain's rebuilt
    candidates (controllers/disruption/methods.py), and the perf
    harness's fleet-cost sweep (perf/run.py) so a catalog-shape change
    lands in one place. Memoizes one catalog list per pool per view —
    construct one per pass, not per node."""

    def __init__(self, pools, cloud):
        self.pools = {p.name: p for p in pools}
        self.cloud = cloud
        self._catalogs: dict = {}

    def pool_of(self, labels: dict):
        return self.pools.get(labels.get(wk.NODEPOOL_LABEL, ""))

    def instance_type(self, labels: dict) -> "InstanceType | None":
        pool = self.pool_of(labels)
        if pool is None:
            return None
        cat = self._catalogs.get(pool.name)
        if cat is None:
            cat = self._catalogs[pool.name] = {
                it.name: it
                for it in self.cloud.get_instance_types(pool)
            }
        return cat.get(labels.get(wk.INSTANCE_TYPE_LABEL, ""))

    def offering(self, labels: dict) -> "Offering | None":
        """The offering a node with these labels runs on, or None."""
        it = self.instance_type(labels)
        if it is None:
            return None
        zone = labels.get(wk.TOPOLOGY_ZONE_LABEL, "")
        ct = labels.get(wk.CAPACITY_TYPE_LABEL, wk.CAPACITY_TYPE_ON_DEMAND)
        for o in it.offerings:
            if o.zone == zone and o.capacity_type == ct:
                return o
        return None


@dataclass
class InterruptionNotice:
    """A spot interruption warning: the provider will reclaim the capacity
    behind ``provider_id`` at (about) ``deadline`` (clock seconds). The
    disruption controller marks the node and the ``InterruptionDrain``
    method replaces-then-drains it before the deadline
    (controllers/disruption/methods.py)."""

    provider_id: str
    deadline: float


class CloudProvider:
    """The SPI every provider implements (types.go:46-69)."""

    def create(self, node_claim):  # -> NodeClaim (with status filled)
        raise NotImplementedError

    def delete(self, node_claim) -> None:
        raise NotImplementedError

    def get(self, provider_id: str):  # -> NodeClaim
        raise NotImplementedError

    def list(self) -> list:  # -> [NodeClaim]
        raise NotImplementedError

    def get_instance_types(self, node_pool) -> list:  # -> [InstanceType]
        raise NotImplementedError

    def is_drifted(self, node_claim) -> str:
        """Returns a drift reason or '' (types.go IsDrifted)."""
        return ""

    def interruption_notices(self) -> list:
        """Pending :class:`InterruptionNotice`\\ s, drained on read (the
        SQS-queue analog of AWS's interruption handling). Providers
        without an interruption feed keep the empty default."""
        return []

    def name(self) -> str:
        raise NotImplementedError
