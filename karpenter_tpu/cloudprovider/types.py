"""Cloud-provider SPI: the only seam to the outside world.

Semantics from the reference's pkg/cloudprovider/types.go: the CloudProvider
interface :46-69, InstanceType/Offerings catalog model :73-102/:214-297,
SatisfiesMinValues :165-199, Truncate :203-212, and the typed errors
:299-387. The catalog model doubles as the source for the device-side
allocatable/price tensors (ops/tensorize.py).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling import Requirement, Requirements, IN
from karpenter_tpu.utils import resources as resutil

SPOT_REQUIREMENT = Requirements(Requirement(wk.CAPACITY_TYPE_LABEL, IN, [wk.CAPACITY_TYPE_SPOT]))
ON_DEMAND_REQUIREMENT = Requirements(
    Requirement(wk.CAPACITY_TYPE_LABEL, IN, [wk.CAPACITY_TYPE_ON_DEMAND])
)


@dataclass
class Offering:
    """One (zone, capacity-type) purchase option (types.go:214-225)."""

    requirements: Requirements
    price: float
    available: bool = True

    @property
    def zone(self) -> str:
        r = self.requirements.get_req(wk.TOPOLOGY_ZONE_LABEL)
        return next(iter(r.values), "") if not r.complement else ""

    @property
    def capacity_type(self) -> str:
        r = self.requirements.get_req(wk.CAPACITY_TYPE_LABEL)
        return next(iter(r.values), "") if not r.complement else ""


class Offerings(list):
    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(
            o
            for o in self
            if reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS)
        )

    def has_compatible(self, reqs: Requirements) -> bool:
        return any(
            reqs.is_compatible(o.requirements, allow_undefined=wk.WELL_KNOWN_LABELS) for o in self
        )

    def cheapest(self) -> Offering:
        return min(self, key=lambda o: o.price)

    def most_expensive(self) -> Offering:
        return max(self, key=lambda o: o.price)

    def worst_launch_price(self, reqs: Requirements) -> float:
        """Spot-aware worst-case launch price (types.go:276-297)."""
        if reqs.get_req(wk.CAPACITY_TYPE_LABEL).has(wk.CAPACITY_TYPE_SPOT):
            spot = self.compatible(reqs).compatible(SPOT_REQUIREMENT)
            if spot:
                return spot.most_expensive().price
        if reqs.get_req(wk.CAPACITY_TYPE_LABEL).has(wk.CAPACITY_TYPE_ON_DEMAND):
            od = self.compatible(reqs).compatible(ON_DEMAND_REQUIREMENT)
            if od:
                return od.most_expensive().price
        return math.inf


@dataclass
class InstanceTypeOverhead:
    kube_reserved: dict = field(default_factory=dict)
    system_reserved: dict = field(default_factory=dict)
    eviction_threshold: dict = field(default_factory=dict)

    def total(self) -> dict:
        return resutil.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


class InstanceType:
    """Properties of a potential node (types.go:73-102)."""

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: Offerings,
        capacity: dict,
        overhead: InstanceTypeOverhead | None = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = offerings
        self.capacity = capacity
        self.overhead = overhead or InstanceTypeOverhead()
        self._allocatable = None

    def allocatable(self) -> dict:
        if self._allocatable is None:
            self._allocatable = resutil.subtract(self.capacity, self.overhead.total())
        return self._allocatable

    def __repr__(self):
        return f"InstanceType({self.name})"


def _cheapest_available_price(it: InstanceType, reqs: Requirements) -> float:
    ofs = it.offerings.available().compatible(reqs)
    return ofs.cheapest().price if ofs else math.inf


def order_by_price(its, reqs: Requirements) -> list:
    """Cheapest available+compatible offering first; name tiebreak
    (types.go OrderByPrice:104)."""
    return sorted(its, key=lambda it: (_cheapest_available_price(it, reqs), it.name))


def compatible_instance_types(its, reqs: Requirements) -> list:
    """Instance types with at least one available offering compatible with
    reqs (types.go Compatible:124)."""
    return [it for it in its if it.offerings.available().has_compatible(reqs)]


def instance_type_compatible(it: InstanceType, reqs: Requirements, requests: dict | None = None) -> bool:
    """Full per-type check used by the scheduler's filter
    (scheduling/nodeclaim.go filterInstanceTypesByRequirements:242):
    requirement overlap (two-way Intersects — custom labels the pod demands
    but the type doesn't define become node labels, so they don't filter
    here) ∧ resource fit ∧ an available compatible offering."""
    if it.requirements.intersects(reqs) is not None:
        return False
    if requests is not None and not resutil.fits(requests, it.allocatable()):
        return False
    return it.offerings.available().has_compatible(reqs)


def filter_instance_types(its, reqs: Requirements, requests: dict | None = None) -> list:
    return [it for it in its if instance_type_compatible(it, reqs, requests)]


def satisfies_min_values(its, reqs: Requirements):
    """(min needed instance types, error) per types.go:165-199 — walks the
    (pre-sorted) list accumulating distinct values per minValues key until
    every floor is met."""
    if not reqs.has_min_values():
        return 0, None
    values_for_key: dict = {}
    min_keys = [r.key for r in reqs.values() if r.min_values is not None]
    incompatible = None
    for i, it in enumerate(its):
        for key in min_keys:
            values_for_key.setdefault(key, set()).update(it.requirements.get_req(key).values)
        incompatible = next(
            (
                k
                for k in min_keys
                if len(values_for_key.get(k, ())) < (reqs.get_req(k).min_values or 0)
            ),
            None,
        )
        if incompatible is None:
            return i + 1, None
    return len(list(its)), f'minValues requirement is not met for "{incompatible}"'


def truncate_instance_types(its, reqs: Requirements, max_items: int):
    """(truncated list, error) — price-ordered prefix of max_items, rejected
    if it breaks minValues (types.go Truncate:203)."""
    truncated = order_by_price(its, reqs)[:max_items]
    if reqs.has_min_values():
        _, err = satisfies_min_values(truncated, reqs)
        if err:
            return list(its), f"validating minValues, {err}"
    return truncated, None


# ---------------------------------------------------------------------------
# typed errors (types.go:299-387)


class NodeClaimNotFoundError(Exception):
    pass


class InsufficientCapacityError(Exception):
    pass


class NodeClassNotReadyError(Exception):
    pass


class CloudProvider:
    """The SPI every provider implements (types.go:46-69)."""

    def create(self, node_claim):  # -> NodeClaim (with status filled)
        raise NotImplementedError

    def delete(self, node_claim) -> None:
        raise NotImplementedError

    def get(self, provider_id: str):  # -> NodeClaim
        raise NotImplementedError

    def list(self) -> list:  # -> [NodeClaim]
        raise NotImplementedError

    def get_instance_types(self, node_pool) -> list:  # -> [InstanceType]
        raise NotImplementedError

    def is_drifted(self, node_claim) -> str:
        """Returns a drift reason or '' (types.go IsDrifted)."""
        return ""

    def name(self) -> str:
        raise NotImplementedError
