"""Metrics decorator for any CloudProvider.

Mirror of the reference's pkg/cloudprovider/metrics/cloudprovider.go: wraps
an inner provider, timing every SPI method into a duration histogram and
counting errors by method — the decorator precedent the Solver interface
follows for wrapping device and host implementations behind one seam.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.operator import metrics as m


class MetricsCloudProvider(CloudProvider):
    def __init__(self, inner: CloudProvider, registry=None):
        self.inner = inner
        self.registry = registry or m.REGISTRY

    def _timed(self, method: str, fn, *args, **kw):
        t0 = time.perf_counter()
        try:
            return fn(*args, **kw)
        except Exception as e:
            self.registry.counter(m.CLOUDPROVIDER_ERRORS).inc(
                method=method, provider=self.inner.name(), error=type(e).__name__)
            raise
        finally:
            self.registry.histogram(m.CLOUDPROVIDER_DURATION).observe(
                time.perf_counter() - t0, method=method, provider=self.inner.name())

    def name(self) -> str:
        return self.inner.name()

    def create(self, node_claim):
        return self._timed("Create", self.inner.create, node_claim)

    def delete(self, node_claim):
        return self._timed("Delete", self.inner.delete, node_claim)

    def get(self, provider_id):
        return self._timed("Get", self.inner.get, provider_id)

    def list(self):
        return self._timed("List", self.inner.list)

    def get_instance_types(self, node_pool):
        return self._timed("GetInstanceTypes", self.inner.get_instance_types, node_pool)

    def is_drifted(self, node_claim):
        return self._timed("IsDrifted", self.inner.is_drifted, node_claim)

    def __getattr__(self, item):
        # pass through provider-specific surface (e.g. kwok's .created)
        return getattr(self.inner, item)
