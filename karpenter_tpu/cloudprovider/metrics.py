"""Metrics decorator for any CloudProvider.

Mirror of the reference's pkg/cloudprovider/metrics/cloudprovider.go: wraps
an inner provider, timing every SPI method into a duration histogram and
counting errors by method — the decorator precedent the Solver interface
follows for wrapping device and host implementations behind one seam.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.operator import metrics as m


class MetricsCloudProvider(CloudProvider):
    def __init__(self, inner: CloudProvider, registry=None):
        self.inner = inner
        self.registry = registry or m.REGISTRY
        # offering-risk gauge bookkeeping: instance-type name -> the
        # (type, zone, ct) label keys last exported for it, so a refresh
        # retires exactly the stale series of the types it re-saw
        self._risk_keys: dict = {}

    def _timed(self, method: str, fn, *args, **kw):
        t0 = time.perf_counter()
        try:
            return fn(*args, **kw)
        except Exception as e:
            self.registry.counter(m.CLOUDPROVIDER_ERRORS).inc(
                method=method, provider=self.inner.name(), error=type(e).__name__)
            raise
        finally:
            self.registry.histogram(m.CLOUDPROVIDER_DURATION).observe(
                time.perf_counter() - t0, method=method, provider=self.inner.name())

    def name(self) -> str:
        return self.inner.name()

    def create(self, node_claim):
        return self._timed("Create", self.inner.create, node_claim)

    def delete(self, node_claim):
        return self._timed("Delete", self.inner.delete, node_claim)

    def get(self, provider_id):
        return self._timed("Get", self.inner.get, provider_id)

    def list(self):
        return self._timed("List", self.inner.list)

    def get_instance_types(self, node_pool):
        its = self._timed(
            "GetInstanceTypes", self.inner.get_instance_types, node_pool)
        self._export_offering_risk(its)
        return its

    def _export_offering_risk(self, its):
        """Refresh the ``karpenter_offering_risk`` gauge from the catalog
        snapshot (offerings with a KNOWN nonzero risk only — on-demand's
        0.0 and unknown Nones would just multiply series). Catalog lists
        are memoized by the callers (get_candidates' catalog cache, the
        solver's type cache), so this runs per cache fill, not per poll.
        Reconciled PER TYPE, never a family-wide clear: providers may
        filter catalogs per nodepool, and one pool's refresh must retire
        only the stale series of the types it re-saw — not wipe every
        other pool's. (A type that vanishes from the catalog entirely
        keeps its last series until some call re-lists it; per-pool
        attribution isn't available at this seam.)"""
        g = self.registry.gauge(
            m.OFFERING_RISK,
            "per-offering interruption-risk signal (spot resilience)")
        for it in its:
            new = {}
            for o in it.offerings:
                if o.interruption_risk:
                    new[(it.name, o.zone, o.capacity_type)] = (
                        o.interruption_risk)
            for tn, z, ct in self._risk_keys.get(it.name, set()) - new.keys():
                g.remove(instance_type=tn, zone=z, capacity_type=ct)
            for (tn, z, ct), v in new.items():
                g.set(v, instance_type=tn, zone=z, capacity_type=ct)
            self._risk_keys[it.name] = set(new)

    def is_drifted(self, node_claim):
        return self._timed("IsDrifted", self.inner.is_drifted, node_claim)

    def interruption_notices(self):
        # explicit delegation: the CloudProvider base default ([]) would
        # otherwise shadow __getattr__ and swallow the inner provider's
        # (or an armed ChaosCloud's) notice feed
        return self._timed(
            "InterruptionNotices", self.inner.interruption_notices)

    def __getattr__(self, item):
        # pass through provider-specific surface (e.g. kwok's .created)
        return getattr(self.inner, item)
