"""kwok-style provider: nodes materialize directly in the object store.

Mirror of the reference's kwok provider (kwok/cloudprovider/
cloudprovider.go:54-188): Create picks the cheapest compatible offering and
fabricates the Node object itself (there is no kubelet), Delete/Get/List
operate on those objects, and the catalog is the synthetic generated one.
This is the e2e vehicle for the hermetic cluster (kube/store.py).
"""

from __future__ import annotations

import copy

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodeclaim import NodeClaim
from karpenter_tpu.api.objects import Node, ObjectMeta, Taint
from karpenter_tpu.cloudprovider.catalog import kwok_catalog
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    cheapest_effective_offering,
)
from karpenter_tpu.scheduling import node_selector_requirements

UNREGISTERED_TAINT = Taint(key=wk.UNREGISTERED_TAINT_KEY, effect="NoExecute")


class KwokCloudProvider(CloudProvider):
    def __init__(self, store, instance_types=None, ready_delay: float = 0.0):
        self.store = store
        self.instance_types = instance_types if instance_types is not None else kwok_catalog()
        self.ready_delay = ready_delay
        self.created: dict = {}  # provider_id -> NodeClaim

    def name(self) -> str:
        return "kwok"

    def get_instance_types(self, node_pool) -> list:
        return list(self.instance_types)

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        reqs = node_selector_requirements(node_claim.spec.requirements)
        # launch placement is risk-aware (the shared
        # cheapest_effective_offering rule): a λ > 0 deployment buys
        # low-interruption-risk capacity; λ=0 keeps the nominal cheapest
        best = cheapest_effective_offering(
            self.instance_types, reqs, node_claim.spec.resource_requests)
        if best is None:
            raise InsufficientCapacityError(
                f"no instance type available for claim {node_claim.name}"
            )
        it, offering = best

        claim = copy.deepcopy(node_claim)
        node_name = node_claim.name
        claim.status.provider_id = f"kwok://{node_name}"
        claim.status.node_name = node_name
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())

        labels = {
            **claim.metadata.labels,
            wk.INSTANCE_TYPE_LABEL: it.name,
            wk.TOPOLOGY_ZONE_LABEL: offering.zone,
            wk.CAPACITY_TYPE_LABEL: offering.capacity_type,
            wk.HOSTNAME_LABEL: node_name,
        }
        claim.metadata.labels = labels
        # kwok has no kubelet: fabricate the Node (cloudprovider.go toNode:140)
        node = Node(
            metadata=ObjectMeta(name=node_name, namespace="", labels=dict(labels)),
            provider_id=claim.status.provider_id,
            taints=[UNREGISTERED_TAINT] + list(claim.spec.taints),
            startup_taints=list(claim.spec.startup_taints),
            capacity=dict(it.capacity),
            allocatable=dict(it.allocatable()),
            ready=self.ready_delay <= 0,
        )
        if self.store.try_get("nodes", node_name) is None:
            self.store.create("nodes", node)
        self.created[claim.status.provider_id] = claim
        return claim

    def delete(self, node_claim: NodeClaim) -> None:
        pid = node_claim.status.provider_id
        if pid not in self.created:
            raise NodeClaimNotFoundError(pid)
        del self.created[pid]
        node = self.store.try_get("nodes", node_claim.status.node_name or node_claim.name)
        if node is not None:
            self.store.delete("nodes", node)

    def get(self, provider_id: str) -> NodeClaim:
        claim = self.created.get(provider_id)
        if claim is None:
            raise NodeClaimNotFoundError(provider_id)
        return claim

    def list(self) -> list:
        return list(self.created.values())

    def is_drifted(self, node_claim) -> str:
        return ""
