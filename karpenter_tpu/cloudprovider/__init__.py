from karpenter_tpu.cloudprovider.types import (  # noqa: F401
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    Offerings,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
    order_by_price,
    compatible_instance_types,
    filter_instance_types,
    instance_type_compatible,
    satisfies_min_values,
    truncate_instance_types,
)

__all__ = [
    "CloudProvider", "InstanceType", "InstanceTypeOverhead", "Offering",
    "Offerings", "InsufficientCapacityError", "NodeClaimNotFoundError",
    "NodeClassNotReadyError", "order_by_price", "compatible_instance_types",
    "filter_instance_types", "instance_type_compatible",
    "satisfies_min_values", "truncate_instance_types",
]
