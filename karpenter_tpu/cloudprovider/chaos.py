"""ChaosCloud: the ONE seeded fault injector behind the CloudProvider seam.

Grown out of tests/test_chaos.py's private ICE wrapper when spot
resilience (deploy/README.md "Spot resilience") needed the same storm in
three places — the chaos convergence suite, the spot-resilience tests, and
``python -m perf spot``'s 1000-node acceptance storm. One implementation,
not three drifting copies:

* **ICE injection** — a seeded fraction of ``create`` calls raise
  :class:`~karpenter_tpu.cloudprovider.types.InsufficientCapacityError`
  (the fake-provider fault-injection pattern, fake/cloudprovider.go:54-58);
  ``force_first_ice`` makes every seed exercise the terminal-ICE recovery
  path at least once.
* **Offering flaps** — seeded availability toggles (spot market churn).
* **Price shifts** — risk-correlated spot price drift: the storm multiplies
  high-risk offerings' prices upward, the real-market coupling (capacity
  pressure raises both the reclaim rate and the clearing price) the
  risk-discounted effective price exists to anticipate.
* **Interruption notices** — seeded two-minute-warning injection: live
  spot nodes are sampled ∝ their offering's ``interruption_risk`` and a
  notice with a deadline lands on the provider's
  ``interruption_notices()`` feed (the disruption controller drains it).
* **Reclaim** — at the deadline the capacity VANISHES ungracefully (node,
  claim, and bound pods deleted; no drain): whatever was still bound is
  counted ``pods_lost`` — and ``pods_lost_with_lead`` when the notice had
  arrived with real lead time, the number the spot acceptance pins at
  ZERO (a proactive drain must have emptied the node first).

``arm(env)`` patches the environment's (wrapped) provider in place —
instance-attribute overrides on the live object every controller already
holds — so it composes with MetricsCloudProvider and needs no wiring
changes.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.cloudprovider.types import (
    CatalogView,
    InsufficientCapacityError,
    InterruptionNotice,
)


class ChaosCloud:
    def __init__(self, rng, ice_rate: float = 0.0,
                 force_first_ice: bool = False):
        self.rng = rng
        self.ice_rate = ice_rate
        self.force_first_ice = force_first_ice
        self.active = True
        self.env = None
        self._pending: list = []  # InterruptionNotice not yet pulled
        # provider_id -> (deadline, counts_as_early)
        self._deadlines: dict = {}
        self.stats = {
            "ices": 0,
            "flaps": 0,
            "price_shifts": 0,
            "notices": 0,
            "reclaims": 0,
            "pods_lost": 0,
            "pods_lost_with_lead": 0,
        }

    # test_chaos.py's historical surface
    @property
    def ices(self) -> int:
        return self.stats["ices"]

    # -- wiring -----------------------------------------------------------

    def arm(self, env) -> "ChaosCloud":
        """Attach to an Environment: wrap ``create`` with seeded ICEs and
        feed ``interruption_notices`` from this injector. Patches the
        instance every controller already references, so arming after
        Environment construction is safe."""
        self.env = env
        inner_create = env.cloud.create

        def create(nc):
            if self.active and self.ice_rate > 0 and (
                (self.force_first_ice and self.stats["ices"] == 0)
                or self.rng.random() < self.ice_rate
            ):
                self.stats["ices"] += 1
                raise InsufficientCapacityError(
                    f"chaos ICE #{self.stats['ices']}")
            return inner_create(nc)

        env.cloud.create = create
        env.cloud.interruption_notices = self.take_notices
        return self

    def take_notices(self) -> list:
        out, self._pending = self._pending, []
        return out

    def has_notice(self, provider_id: str) -> bool:
        """Whether a (not-yet-reclaimed) notice already targets this
        node — injectors must check it: a second notice would silently
        OVERWRITE the first one's deadline and early-flag, corrupting the
        zero-late-drain accounting the acceptance gates on."""
        return provider_id in self._deadlines

    # -- storm actions ----------------------------------------------------

    def flap_random_offering(self, offerings):
        """Toggle one offering's availability (ICE or recovery)."""
        o = self.rng.choice(list(offerings))
        o.available = not o.available
        self.stats["flaps"] += 1
        return o

    def shift_prices(self, offerings, factor: float = 1.2,
                     min_risk: float = 0.5) -> int:
        """Risk-correlated spot price drift: every spot offering whose
        risk is at or above ``min_risk`` gets its price multiplied by
        ``factor`` — the capacity-pressure spiral the storm models. The
        type-side tensor cache fingerprints offering prices, so in-place
        drift invalidates cleanly."""
        shifted = 0
        for o in offerings:
            if (o.capacity_type == wk.CAPACITY_TYPE_SPOT
                    and (o.interruption_risk or 0.0) >= min_risk):
                o.price = round(o.price * factor, 6)
                shifted += 1
        self.stats["price_shifts"] += shifted
        return shifted

    def inject_notice(self, provider_id: str, deadline: float,
                      early: bool = True):
        """Queue one interruption notice. ``early`` marks whether the
        notice carries ≥1 round of lead time — pods lost at its reclaim
        then count against the zero-late-drain acceptance."""
        self._pending.append(InterruptionNotice(provider_id, deadline))
        self._deadlines[provider_id] = (deadline, bool(early))
        self.stats["notices"] += 1

    def notice_storm(self, rate: float, lead_s: float,
                     early: bool = True) -> int:
        """Sample live spot nodes ∝ offering risk and notice them with a
        ``lead_s``-second deadline. ``rate`` scales the per-node draw
        (node risk × rate), so a low-risk fleet rides out the same storm
        a high-risk fleet churns through — the spot acceptance's entire
        mechanism."""
        if self.env is None:
            return 0
        now = self.env.clock.now()
        risks = self._node_risks()
        issued = 0
        for node, risk in risks:
            if node.provider_id in self._deadlines:
                continue  # already noticed
            if self.rng.random() < rate * risk:
                self.inject_notice(node.provider_id, now + lead_s,
                                   early=early)
                issued += 1
        return issued

    def _node_risks(self):
        """[(node, risk)] for live spot nodes, risk from the node's
        (instance-type, zone) offering via the shared resolution walk
        (types.CatalogView)."""
        env = self.env
        view = CatalogView(env.store.list("nodepools"), env.cloud)
        out = []
        for node in env.store.list("nodes"):
            if node.metadata.deletion_timestamp is not None:
                continue
            labels = node.labels
            if labels.get(wk.CAPACITY_TYPE_LABEL) != wk.CAPACITY_TYPE_SPOT:
                continue
            o = view.offering(labels)
            if o is None:
                continue
            out.append((node, o.interruption_risk or 0.0))
        return out

    # -- the reclaim ------------------------------------------------------

    def reclaim_expired(self) -> int:
        """Kill every noticed node whose deadline passed and is still
        alive: the capacity vanishes UNGRACEFULLY — bound pods die with
        it (``pods_lost``; ``pods_lost_with_lead`` when the notice had
        real lead — the proactive drain should have emptied the node
        long before this fires). A node already gone (the proactive path
        worked) just clears its bookkeeping."""
        env = self.env
        now = env.clock.now()
        reclaimed = 0
        for pid, (deadline, early) in list(self._deadlines.items()):
            if now < deadline:
                continue
            del self._deadlines[pid]
            node = next(
                (n for n in env.store.list("nodes")
                 if n.provider_id == pid), None)
            if node is None:
                continue  # drained and gone before the deadline
            reclaimed += 1
            self.stats["reclaims"] += 1
            bound = [
                p for p in env.store.list("pods")
                if p.node_name == node.metadata.name
                and p.metadata.deletion_timestamp is None
            ]
            self.stats["pods_lost"] += len(bound)
            if early:
                self.stats["pods_lost_with_lead"] += len(bound)
            for p in bound:
                p.metadata.finalizers = []
                env.store.delete("pods", p)
            # the instance is gone: force-release node and claim (no
            # graceful finalizer path — that is the entire point)
            node.metadata.finalizers = []
            env.store.delete("nodes", node)
            claim = next(
                (c for c in env.store.list("nodeclaims")
                 if c.status.provider_id == pid), None)
            if claim is not None:
                claim.metadata.finalizers = []
                env.store.delete("nodeclaims", claim)
            created = getattr(env.cloud, "created", None)
            if isinstance(created, dict):
                created.pop(pid, None)
        return reclaimed
