"""GL1xx tracing safety + GL4xx observability safety for jit/pallas paths.

The solve path compiles through ``jax.jit`` / ``pl.pallas_call`` wrappers
(models/solver.py, ops/consolidate.py, parallel/mesh.py, ops/pallas_kernels.py).
Inside anything reachable from those entries, the silent failure modes are:

- GL101 host-sync: ``.item()`` / ``float()`` / ``int()`` / ``bool()`` /
  ``np.asarray()`` on a traced value — a device→host pull per call (64 ms
  each through the tunnel) or an outright TracerError.
- GL102 traced-branch: Python ``if``/``while``/``assert`` on a traced
  value — TracerBoolConversionError, or worse, a concrete leak that bakes
  one batch's data into the compiled program.
- GL103 trace-side-effect: ``print``, ``logging`` calls, ``os.environ``
  reads, and ``global`` writes inside traced code — they run once at trace
  time and freeze (the pallas_enabled() cache-keying bug class).
- GL104 jit-in-loop: constructing ``jax.jit(...)`` / ``pl.pallas_call(...)``
  inside a loop body — a fresh wrapper per iteration recompiles every time
  (the recompilation-storm class the module-level kernel caches exist for).

The GL4xx family rides the same inter-procedural reachability pass and
keeps the reconcile flight recorder (``karpenter_tpu/obs``) safe by
construction — a span enter/exit or anomaly mark that becomes reachable
from a jit/pallas entry would execute ONCE at trace time (freezing one
batch's timing into the compiled program and corrupting every later
round's trace) while its perf_counter/thread-local machinery races XLA's
runtime:

- GL401 span-in-trace: ``span(...)`` / ``round_trace(...)`` (bare or as
  the last attribute of any chain — ``obs.span``, ``TRACER.span``) inside
  jit-reachable code.
- GL402 recorder-in-trace: ``anomaly(...)`` / ``record_anomaly(...)``
  anywhere jit-reachable, plus ``record``/``dump`` invoked on an
  obs-plane object (``obs.*``, ``RECORDER``/``recorder``/``TRACER``/
  ``tracer``/``FLIGHT_RECORDER``).
- GL403 devplane-in-trace: a device-plane telemetry hook
  (``record_dispatch``/``record_padding``/``record_compile``, or
  ``observe`` on a devplane receiver — ``devplane.*``/``LEDGER``/
  ``ledger``) inside jit-reachable code. The hooks read perf_counter
  deltas, mutate shared ledgers/windows, and feed metric registries:
  all host-side machinery that would freeze at trace time and race
  XLA's runtime (the same failure mode as GL401/402, one module over).
- GL404 decision-ledger-in-trace: a decision-plane hook
  (``record_decision``/``record_quality``/``note_round``, or
  ``record``/``observe_quality`` on a decisions receiver —
  ``decisions.*``/``DECISIONS``) inside jit-reachable code. The ledger
  takes a process lock, mutates streak/quality state, feeds metric
  registries, and can mark anomalies on the open trace — a trace-time
  execution would freeze ONE batch's verdict into the compiled program
  (every later solve would re-record it) and race the ledger from XLA's
  runtime (the same failure mode as GL403, one plane over).
- GL405 capsule-in-trace: a replay-capsule hook
  (``record_capture``/``write_capsule``/``maybe_write_round``, or
  ``capture`` on a capsule receiver —
  ``capsule.*``) inside jit-reachable code. The capture hook takes the
  module lock, mutates thread-local/trace state, and the serializers do
  disk I/O — executed at trace time they would freeze ONE batch's
  capture into the compiled program (every later solve would re-record
  stale tensors as "its" capsule — corrupting the exact bit-parity
  replay exists to guarantee) and race the capsule index from XLA's
  runtime (the same failure mode as GL401-404, one plane over).
- GL406 timeline-in-trace: a fleet-ledger timeline hook
  (``record_event``/``record_billing``/``note_launch``/``begin_command``/
  ``observe_fleet``, or ``record``/``observe``/``note`` on a timeline
  receiver — ``timeline.*``/``TIMELINE``) inside jit-reachable code. The
  hooks take the ledger lock, read wall-clock time, mutate the bounded
  event ring / command table / billing rows, and feed metric registries —
  executed at trace time they would mint ONE frozen lifecycle event per
  compile (re-committed by every later solve, corrupting the causal
  timeline and the billed device-seconds the ``/usage`` endpoint reports)
  and race the ring from XLA's runtime (the same failure mode as
  GL401-405, one plane over).

Reachability is an inter-procedural taint pass: entry functions are those
handed to jit/pallas_call (as decorator, call argument, or via
``functools.partial`` with its bound kwargs treated as static); calls into
package-local functions propagate which parameters carry tracers, so a
static ``max_bins=...`` threaded through ``solve_step`` never poisons the
branch checks. Shape reads (``x.shape``/``ndim``/``dtype``/``size``,
``len()``) and structure tests (``is None``, ``in``, ``isinstance``) are
host-static by construction and exempt.
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.core import Finding, dotted

RULES = {
    "GL101": "host sync (.item()/float()/int()/bool()/np.asarray) on a traced value in jit-reachable code",
    "GL102": "Python branch (if/while/assert) on a traced value in jit-reachable code",
    "GL103": "host side effect (print/logging/os.environ/global) in jit-reachable code freezes at trace time",
    "GL104": "jax.jit/pl.pallas_call constructed inside a loop recompiles every iteration",
    "GL401": "obs tracer span enter/exit (span/round_trace) in jit-reachable code executes at trace time",
    "GL402": "obs flight-recorder mutation (anomaly/record/dump) in jit-reachable code executes at trace time",
    "GL403": "devplane telemetry hook (compile ledger / pad-waste / SLO observe) in jit-reachable code executes at trace time",
    "GL404": "decision-ledger hook (record_decision / record_quality / decisions receiver) in jit-reachable code executes at trace time",
    "GL405": "replay-capsule hook (record_capture / write_capsule / capsule receiver) in jit-reachable code executes at trace time",
    "GL406": "fleet-ledger timeline hook (record_event / record_billing / timeline receiver) in jit-reachable code executes at trace time",
}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_HOST_RESULT_FUNCS = {"len", "isinstance", "type", "id", "hash"}
_CAST_FUNCS = {"float", "int", "bool", "complex"}
_NUMPY_ALIASES = {"np", "_np", "numpy", "onp"}
_JIT_NAMES = {"jax.jit", "jit"}
_PALLAS_NAMES = {"pl.pallas_call", "pallas.pallas_call", "pallas_call"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
# GL4xx — the obs flight-recorder surface (karpenter_tpu/obs). Span entry
# is matched by final name so `obs.span`, `TRACER.span`, and a bare
# imported `span` all hit; the generic `record`/`dump` verbs only count
# when invoked on an unmistakably obs-plane receiver.
_SPAN_FUNCS = {"span", "round_trace"}
_ANOMALY_FUNCS = {"anomaly", "record_anomaly"}
_RECORDER_VERBS = {"record", "dump"}
_OBS_BASES = {"obs", "TRACER", "tracer", "RECORDER", "recorder",
              "FLIGHT_RECORDER"}
# GL403 — the device-plane telemetry surface (karpenter_tpu/obs/devplane):
# the hook names are matched by final attribute (devplane.record_dispatch,
# LEDGER.record_dispatch, a bare import); the generic `observe` verb only
# counts on an unmistakably devplane receiver.
_DEVPLANE_FUNCS = {"record_dispatch", "record_padding", "record_compile"}
_DEVPLANE_VERBS = {"observe"}
_DEVPLANE_BASES = {"devplane", "LEDGER", "ledger"}
# GL404 — the decision-ledger surface (karpenter_tpu/obs/decisions): the
# hook names match by final attribute (decisions.record_decision,
# DECISIONS.record, a bare import); the generic `record`/`observe_quality`
# verbs only count on an unmistakably decisions receiver.
_DECISION_FUNCS = {"record_decision", "record_quality", "note_round"}
_DECISION_VERBS = {"record", "observe_quality"}
_DECISION_BASES = {"decisions", "DECISIONS"}
# GL405 — the replay-capsule surface (karpenter_tpu/obs/capsule): the
# capture/serialize hooks match by final attribute (capsule.record_capture,
# a bare import); the generic `capture` verb only counts on an
# unmistakably capsule receiver.
_CAPSULE_FUNCS = {"record_capture", "write_capsule", "maybe_write_round"}
_CAPSULE_VERBS = {"capture"}
_CAPSULE_BASES = {"capsule", "CAPSULES"}
# GL406 — the fleet-ledger timeline surface (karpenter_tpu/obs/timeline):
# the event/billing hooks match by final attribute (timeline.record_event,
# TIMELINE.record_billing, a bare import); the generic verbs only count on
# an unmistakably timeline receiver.
_TIMELINE_FUNCS = {"record_event", "record_billing", "note_launch",
                   "begin_command", "observe_fleet"}
_TIMELINE_VERBS = {"record", "observe", "note"}
_TIMELINE_BASES = {"timeline", "TIMELINE"}


def _const_names(node) -> set:
    """static_argnames/argnums value -> set of str names and int indices
    (a bare constant or a tuple/list/set of them). Int indices are resolved
    to parameter names positionally once the target function is known."""
    out = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, (str, int)):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, (str, int)):
                out.add(elt.value)
    return out


def _resolve_static(static: set, params: list) -> set:
    """Mixed str/int static spec -> parameter-name set."""
    out = set()
    for s in static:
        if isinstance(s, int):
            if 0 <= s < len(params):
                out.add(params[s])
        else:
            out.add(s)
    return out


def _param_names(fn) -> list:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


class _FunctionEnv:
    """Name resolution for one function: nested defs of the lexical chain,
    module top-level functions, then imports."""

    def __init__(self, project, module, chain):
        self.project = project
        self.module = module
        self.chain = chain  # enclosing FunctionDefs, outermost first
        self.imports = project.resolve_imports(module)
        self.top = project.top_level_functions(module)

    def local_defs(self) -> dict:
        defs = {}
        for fn in self.chain:
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs.setdefault(node.name, node)
        return defs

    def resolve(self, func_node):
        """A callee expression -> (module, FunctionDef, chain) | None."""
        if isinstance(func_node, ast.Name):
            local = self.local_defs().get(func_node.id)
            if local is not None:
                return self.module, local, self.chain
            top = self.top.get(func_node.id)
            if top is not None:
                return self.module, top, []
            bound = self.imports.get(func_node.id)
            if bound is not None and bound[0] == "symbol":
                mod, sym = bound[1], bound[2]
                fn = self.project.top_level_functions(mod).get(sym)
                if fn is not None:
                    return mod, fn, []
        elif isinstance(func_node, ast.Attribute) and isinstance(func_node.value, ast.Name):
            bound = self.imports.get(func_node.value.id)
            if bound is not None and bound[0] == "module":
                fn = self.project.top_level_functions(bound[1]).get(func_node.attr)
                if fn is not None:
                    return bound[1], fn, []
        return None


def _find_entries(project):
    """Yield (module, FunctionDef, chain, traced_param_names)."""
    for mod in project.modules.values():
        parents: dict = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def chain_of(fn):
            chain, cur = [], parents.get(fn)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    chain.append(cur)
                cur = parents.get(cur)
            return list(reversed(chain))

        # decorator entries
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                static = set()
                name = dotted(dec)
                if isinstance(dec, ast.Call):
                    inner = dotted(dec.func)
                    if inner in _PARTIAL_NAMES and dec.args and dotted(dec.args[0]) in _JIT_NAMES:
                        for kw in dec.keywords:
                            if kw.arg in ("static_argnames", "static_argnums"):
                                static |= _const_names(kw.value)
                        name = "jax.jit"
                    elif inner in _JIT_NAMES:
                        for kw in dec.keywords:
                            if kw.arg in ("static_argnames", "static_argnums"):
                                static |= _const_names(kw.value)
                        name = "jax.jit"
                if name in _JIT_NAMES:
                    params = _param_names(node)
                    resolved_static = _resolve_static(static, params)
                    traced = [p for p in params if p not in resolved_static]
                    yield mod, node, chain_of(node), traced

        # call-site entries: jax.jit(f), jax.jit(partial(f, ...)), pallas_call(f, ...)
        env_cache: dict = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name not in _JIT_NAMES and name not in _PALLAS_NAMES:
                continue
            if not node.args:
                continue
            target = node.args[0]
            static = set()
            for kw in node.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    static |= _const_names(kw.value)
            bound_kwargs = set()
            if isinstance(target, ast.Call) and dotted(target.func) in _PARTIAL_NAMES:
                bound_kwargs = {kw.arg for kw in target.keywords if kw.arg}
                target = target.args[0] if target.args else None
            if target is None:
                continue
            holder = parents.get(node)
            while holder is not None and not isinstance(
                holder, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                holder = parents.get(holder)
            key = id(holder)
            if key not in env_cache:
                chain = (chain_of(holder) + [holder]) if holder is not None else []
                env_cache[key] = _FunctionEnv(project, mod, chain)
            resolved = env_cache[key].resolve(target)
            if resolved is None:
                continue
            tmod, fn, fchain = resolved
            params = _param_names(fn)
            resolved_static = _resolve_static(static, params) | bound_kwargs
            traced = [p for p in params if p not in resolved_static]
            yield tmod, fn, fchain, traced


class _TaintVisitor:
    """One traced function body: propagate taint, emit findings, collect
    call edges into other package-local functions."""

    def __init__(self, project, module, fn, chain, traced, free_tainted):
        self.project = project
        self.module = module
        self.fn = fn
        self.env = _FunctionEnv(project, module, chain + [fn])
        self.tainted = set(traced) | set(free_tainted)
        self.findings: list = []
        self.edges: list = []  # (module, fn, chain, traced_params, free_tainted)
        self._seen_lines: set = set()

    # -- taint ------------------------------------------------------------
    def is_tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            fname = dotted(node.func)
            base = fname.split(".")[0]
            if base in _HOST_RESULT_FUNCS or fname in _CAST_FUNCS:
                return False
            if fname.endswith(".item"):
                return False
            if base in _NUMPY_ALIASES and fname.split(".")[-1] in ("asarray", "array"):
                return False  # host pull: flagged, result is host-side
            args = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(node.func, ast.Attribute) and self.is_tainted(node.func.value):
                return True  # method on a traced value
            return any(self.is_tainted(a) for a in args)
        if isinstance(node, (ast.BinOp,)):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity/membership tests resolve to host bools at trace time
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in node.ops):
                return False
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_tainted(v) for v in node.values if v is not None)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.is_tainted(node.elt) or any(
                self.is_tainted(g.iter) for g in node.generators
            )
        if isinstance(node, ast.DictComp):
            return (
                self.is_tainted(node.key)
                or self.is_tainted(node.value)
                or any(self.is_tainted(g.iter) for g in node.generators)
            )
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    def _bind_targets(self, target, tainted: bool):
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_targets(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._bind_targets(target.value, tainted)
        # attribute/subscript stores don't rebind names

    def propagate(self, emit: bool):
        self._emit = emit
        for stmt in self.fn.body:
            self._stmt(stmt)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs analyzed when referenced/called
        if isinstance(node, ast.Assign):
            t = self.is_tainted(node.value)
            self._expr(node.value)
            for target in node.targets:
                self._bind_targets(target, t)
            # comprehension loop vars over traced iterables (e.g. dict
            # .items() of the traced arg dict) taint their element names
            return
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            t = self.is_tainted(node.value)
            self._expr(node.value)
            self._bind_targets(node.target, t)
            return
        if isinstance(node, ast.AugAssign):
            t = self.is_tainted(node.value) or self.is_tainted(node.target)
            self._expr(node.value)
            self._bind_targets(node.target, t)
            return
        if isinstance(node, ast.If):
            self._branch_check(node.test, "if")
            self._expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.While):
            self._branch_check(node.test, "while")
            self._expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.Assert):
            self._branch_check(node.test, "assert")
            self._expr(node.test)
            return
        if isinstance(node, ast.For):
            self._expr(node.iter)
            self._bind_targets(node.target, self.is_tainted(node.iter))
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.With):
            for item in node.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind_targets(
                        item.optional_vars, self.is_tainted(item.context_expr)
                    )
            for s in node.body:
                self._stmt(s)
            return
        if isinstance(node, ast.Try):
            for s in node.body + node.orelse + node.finalbody:
                self._stmt(s)
            for handler in node.handlers:
                for s in handler.body:
                    self._stmt(s)
            return
        if hasattr(ast, "Match") and isinstance(node, ast.Match):
            self._branch_check(node.subject, "match")
            self._expr(node.subject)
            for case in node.cases:
                for s in case.body:
                    self._stmt(s)
            return
        if isinstance(node, ast.Return) and node.value is not None:
            self._expr(node.value)
            return
        if isinstance(node, ast.Expr):
            self._expr(node.value)
            return
        if isinstance(node, ast.Global):
            self._flag(
                "GL103",
                node.lineno,
                f"`global {', '.join(node.names)}` inside jit-reachable "
                f"`{self.fn.name}` is a trace-time side effect",
            )
            return
        # default: walk any embedded expressions
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    # -- checks -----------------------------------------------------------
    def _branch_flaggable(self, test) -> bool:
        if isinstance(test, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in test.ops):
                return False  # structure checks run host-side at trace time
            return self.is_tainted(test)
        if isinstance(test, ast.Call) and dotted(test.func) in ("isinstance", "hasattr", "callable"):
            return False
        if isinstance(test, ast.BoolOp):
            return any(self._branch_flaggable(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branch_flaggable(test.operand)
        return self.is_tainted(test)

    def _branch_check(self, test, kind: str):
        if self._branch_flaggable(test):
            self._flag(
                "GL102",
                test.lineno,
                f"Python `{kind}` on a traced value inside jit-reachable "
                f"`{self.fn.name}` (TracerBoolConversionError or a "
                "concretization leak)",
            )

    def _flag(self, rule, line, message):
        if not self._emit:
            return
        key = (rule, line)
        if key in self._seen_lines:
            return
        self._seen_lines.add(key)
        self.findings.append(Finding(self.module.path, line, rule, message))

    def _expr(self, node):
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            self._call(call)
        if self._emit:
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Attribute)
                    and sub.attr == "environ"
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "os"
                ):
                    self._flag(
                        "GL103",
                        sub.lineno,
                        f"os.environ read inside jit-reachable `{self.fn.name}` "
                        "freezes at trace time; resolve it host-side and pass "
                        "the value in",
                    )
                if isinstance(sub, ast.IfExp):
                    self._branch_check(sub.test, "conditional expression")

    def _call(self, node):
        fname = dotted(node.func)
        base = fname.split(".")[0]
        args = list(node.args) + [kw.value for kw in node.keywords]

        # GL101 host syncs
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            if self.is_tainted(node.func.value):
                self._flag(
                    "GL101",
                    node.lineno,
                    f"`.item()` on a traced value inside jit-reachable "
                    f"`{self.fn.name}` forces a device->host sync",
                )
        elif fname in _CAST_FUNCS and any(self.is_tainted(a) for a in args):
            self._flag(
                "GL101",
                node.lineno,
                f"`{fname}()` on a traced value inside jit-reachable "
                f"`{self.fn.name}` forces concretization",
            )
        elif (
            base in _NUMPY_ALIASES
            and fname.split(".")[-1] in ("asarray", "array")
            and any(self.is_tainted(a) for a in args)
        ):
            self._flag(
                "GL101",
                node.lineno,
                f"`{fname}()` on a traced value inside jit-reachable "
                f"`{self.fn.name}` pulls the array to host",
            )

        # GL4xx — obs flight recorder reachable from a jit/pallas entry:
        # the span/anomaly machinery (perf_counter, thread-local stacks,
        # ring mutation) would run once at trace time and race XLA's
        # runtime thereafter. Matches the module-level helpers AND any
        # attribute spelling (obs.span / TRACER.span / self._tracer.span).
        last = fname.split(".")[-1] if fname else ""
        if last in _SPAN_FUNCS:
            self._flag(
                "GL401",
                node.lineno,
                f"tracer span `{fname}(...)` inside jit-reachable "
                f"`{self.fn.name}` executes at trace time (hoist the span "
                "to the host-side dispatch site)",
            )
        elif last in _ANOMALY_FUNCS or (
            last in _RECORDER_VERBS and base in _OBS_BASES
        ):
            self._flag(
                "GL402",
                node.lineno,
                f"flight-recorder call `{fname}(...)` inside jit-reachable "
                f"`{self.fn.name}` executes at trace time (mark anomalies "
                "from the host-side caller)",
            )
        elif last in _DEVPLANE_FUNCS or (
            last in _DEVPLANE_VERBS and base in _DEVPLANE_BASES
        ):
            self._flag(
                "GL403",
                node.lineno,
                f"devplane telemetry hook `{fname}(...)` inside "
                f"jit-reachable `{self.fn.name}` executes at trace time "
                "(record from the host-side dispatch site)",
            )
        elif last in _DECISION_FUNCS or (
            last in _DECISION_VERBS and base in _DECISION_BASES
        ):
            self._flag(
                "GL404",
                node.lineno,
                f"decision-ledger hook `{fname}(...)` inside "
                f"jit-reachable `{self.fn.name}` executes at trace time "
                "(record the verdict from the host-side ladder site)",
            )
        elif last in _CAPSULE_FUNCS or (
            last in _CAPSULE_VERBS and base in _CAPSULE_BASES
        ):
            self._flag(
                "GL405",
                node.lineno,
                f"replay-capsule hook `{fname}(...)` inside "
                f"jit-reachable `{self.fn.name}` executes at trace time "
                "(capture from the host-side dispatch site)",
            )
        elif last in _TIMELINE_FUNCS or (
            last in _TIMELINE_VERBS and base in _TIMELINE_BASES
        ):
            self._flag(
                "GL406",
                node.lineno,
                f"fleet-ledger timeline hook `{fname}(...)` inside "
                f"jit-reachable `{self.fn.name}` executes at trace time "
                "(record lifecycle events from the host-side controller)",
            )

        # GL103 side effects
        if fname == "print":
            self._flag(
                "GL103",
                node.lineno,
                f"`print()` inside jit-reachable `{self.fn.name}` runs once "
                "at trace time (use jax.debug.print for runtime values)",
            )
        elif base == "logging" or fname in ("os.getenv",):
            self._flag(
                "GL103",
                node.lineno,
                f"`{fname}()` inside jit-reachable `{self.fn.name}` is a "
                "trace-time side effect",
            )

        # call edges into package-local functions
        resolved = self.env.resolve(node.func)
        if resolved is not None:
            tmod, fn, fchain = resolved
            params = _param_names(fn)
            traced = set()
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    if self.is_tainted(a.value):
                        traced |= set(params[i:])
                    break
                if i < len(params) and self.is_tainted(a):
                    traced.add(params[i])
            for kw in node.keywords:
                if kw.arg is not None and kw.arg in params and self.is_tainted(kw.value):
                    traced.add(kw.arg)
                elif kw.arg is None and self.is_tainted(kw.value):
                    traced |= set(params)  # **kwargs splat of traced dict
            free = set()
            if tmod is self.module and fchain:
                # nested sibling: free variables of the enclosing chain
                free = self.tainted & _free_names(fn)
            self.edges.append((tmod, fn, fchain, frozenset(traced), frozenset(free)))
        # package-local function VALUES handed to combinators
        # (jax.vmap(f), lax.scan(f, ...), pallas_call(f, ...)): fully traced
        for a in node.args:
            if isinstance(a, (ast.Name, ast.Attribute)) and a is not node.func:
                r = self.env.resolve(a)
                if r is not None:
                    tmod, fn, fchain = r
                    free = self.tainted & _free_names(fn) if tmod is self.module else set()
                    self.edges.append(
                        (tmod, fn, fchain, frozenset(_param_names(fn)), frozenset(free))
                    )


def _free_names(fn) -> set:
    bound = set(_param_names(fn))
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    loads = {
        n.id
        for n in ast.walk(fn)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }
    return loads - bound


def _analyze_traced(project, module, fn, chain, traced, free_tainted):
    v = _TaintVisitor(project, module, fn, chain, traced, free_tainted)
    # taint fixpoint (loop-carried rebinds), then one emitting pass
    for _ in range(4):
        before = set(v.tainted)
        v.propagate(emit=False)
        if v.tainted == before:
            break
    v.edges = []
    v._seen_lines = set()
    v.propagate(emit=True)
    return v.findings, v.edges


def check_tracing(project) -> list:
    findings: list = []
    seen: set = set()
    work = [
        (mod, fn, chain, frozenset(traced), frozenset())
        for mod, fn, chain, traced in _find_entries(project)
    ]
    while work:
        mod, fn, chain, traced, free = work.pop()
        key = (mod.name, fn.lineno, fn.name, traced, free)
        if key in seen:
            continue
        seen.add(key)
        f, edges = _analyze_traced(project, mod, fn, chain, list(traced), list(free))
        findings.extend(f)
        for tmod, tfn, tchain, ttraced, tfree in edges:
            work.append((tmod, tfn, tchain, ttraced, tfree))

    # GL104: jit/pallas_call wrappers built inside loops — everywhere,
    # traced or not (the storm is a host-side structure bug)
    for mod in project.modules.values():
        loop_stack: list = []

        def visit(node):
            is_loop = isinstance(node, (ast.For, ast.While))
            if is_loop:
                loop_stack.append(node)
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if (name in _JIT_NAMES or name in _PALLAS_NAMES) and loop_stack:
                    findings.append(
                        Finding(
                            mod.path,
                            node.lineno,
                            "GL104",
                            f"`{name}(...)` constructed inside a loop builds a "
                            "fresh wrapper (and recompiles) every iteration; "
                            "hoist it and cache the compiled callable",
                        )
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_loop:
                loop_stack.pop()

        visit(mod.tree)
    return findings
