"""GL2xx — lock discipline for classes owning a threading.Lock/RLock.

The concurrent surfaces (kube/store.py's apiserver analog, the metrics
registry, the fake cloud provider, the fake clock) all follow the same
convention: ``self._lock`` created in ``__init__``, every mutation of the
shared dicts/lists inside ``with self._lock``. These rules keep that
convention honest:

- GL201 unguarded-mutation: an attribute that is mutated under the lock
  somewhere in the class (so it IS guarded state) is also mutated in a
  method that neither holds the lock nor is provably only called from
  lock-held code paths within the class.
- GL202 lock-order-cycle: class A's methods acquire B's lock while holding
  A's (via a composed attribute typed by construction in ``__init__``) and
  vice versa — the classic ABBA deadlock, detected as a cycle in the
  holds-while-acquiring graph.
- GL203 self-deadlock: while holding a plain (non-reentrant)
  ``threading.Lock``, calling another method of the same class that
  re-acquires it — blocks forever at runtime; only RLock owners may
  re-enter.

``__init__`` is exempt from GL201 (the object is not yet shared while it
is being constructed), and reads are never flagged — the rules target lost
updates, not stale reads.
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.core import Finding, dotted

RULES = {
    "GL201": "mutation of lock-guarded state without holding the class lock",
    "GL202": "lock-acquisition-order cycle across classes (ABBA deadlock)",
    "GL203": "re-acquiring a non-reentrant Lock from a method already holding it",
}

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
}
_LOCK_CTORS = {"Lock", "RLock", "threading.Lock", "threading.RLock"}


def _lock_attrs(cls) -> dict:
    """self.X = threading.Lock()/RLock() (or an alias of another object's
    lock) anywhere in the class -> {attr: "lock"|"rlock"|"alias"}."""
    out: dict = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            name = dotted(node.value)
            if isinstance(node.value, ast.Call) and name in _LOCK_CTORS:
                out[target.attr] = "rlock" if name.endswith("RLock") else "lock"
            elif (
                isinstance(node.value, ast.Attribute)
                and "lock" in node.value.attr.lower()
            ):
                out[target.attr] = "alias"
    return out


def _methods(cls) -> dict:
    return {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _is_self_attr(node, attrs) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in attrs
    ):
        return node.attr
    return None


def _lock_items(node, lock_attrs) -> set:
    """Which class locks does this With statement acquire?"""
    acquired = set()
    for item in node.items:
        attr = _is_self_attr(item.context_expr, lock_attrs)
        if attr:
            acquired.add(attr)
    return acquired


def _walk_with_lock(fn, lock_attrs):
    """Yield (node, held) for every statement/expr node in fn, where held
    is the SET of class lock attrs held at that point — identity matters:
    holding self._a guards nothing that self._b guards, and calling into a
    self._b acquirer while holding self._a deadlocks nobody."""

    def rec(node, held):
        yield node, held
        if isinstance(node, ast.With):
            acquired = _lock_items(node, lock_attrs)
            if acquired:
                held = held | acquired
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield from rec(child, held)

    for stmt in fn.body:
        yield from rec(stmt, frozenset())


def _mutations(fn, lock_attrs):
    """Yield (attr, line, held_locks) for self-attribute mutations."""
    for node, held in _walk_with_lock(fn, lock_attrs):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                for leaf in _assign_leaves(t):
                    attr = _mutated_attr(leaf)
                    if attr:
                        yield attr, node.lineno, held
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _mutated_attr(t)
                if attr:
                    yield attr, node.lineno, held
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATORS:
                attr = _receiver_attr(node.func.value)
                if attr:
                    yield attr, node.lineno, held


def _assign_leaves(target):
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assign_leaves(elt)
    elif isinstance(target, ast.Starred):
        yield from _assign_leaves(target.value)
    else:
        yield target


def _mutated_attr(target) -> str | None:
    """self.X = / self.X[...] = / del self.X[...] -> X."""
    if isinstance(target, ast.Subscript):
        return _mutated_attr(target.value)
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def _receiver_attr(node) -> str | None:
    """self.X.append(...) / self.X[k].append(...) -> X."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _acquired_attrs(fn, lock_attrs) -> set:
    """Every class lock attr the method acquires anywhere in its body."""
    acquired = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            acquired |= _lock_items(node, lock_attrs)
    return acquired


def _acquires_lock(fn, lock_attrs) -> bool:
    return bool(_acquired_attrs(fn, lock_attrs))


def _locked_only_methods(cls, lock_attrs) -> set:
    """Methods every intra-class call site of which sits under the lock
    (directly, or inside another locked-only method) — the private-helper
    pattern (_maybe_finalize called from locked create/update/delete)."""
    methods = _methods(cls)
    # call sites: method -> [(callee, under_lock)]
    sites: dict = {m: [] for m in methods}
    for name, fn in methods.items():
        for node, held in _walk_with_lock(fn, lock_attrs):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in methods
            ):
                sites[name].append((node.func.attr, bool(held)))
    locked_only: set = set()
    changed = True
    while changed:
        changed = False
        for callee in methods:
            if callee in locked_only or callee == "__init__":
                continue
            callers = [
                (caller, under)
                for caller, calls in sites.items()
                for c, under in calls
                if c == callee
            ]
            if not callers:
                continue
            if all(
                under or caller in locked_only or _fully_locked(methods[caller], lock_attrs)
                for caller, under in callers
            ):
                locked_only.add(callee)
                changed = True
    return locked_only


def _fully_locked(fn, lock_attrs) -> bool:
    """The whole method body is one `with self._lock:` statement."""
    body = [s for s in fn.body if not _is_docstring(s)]
    return (
        len(body) == 1
        and isinstance(body[0], ast.With)
        and _lock_items(body[0], lock_attrs)
    )


def _is_docstring(stmt) -> bool:
    return isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant)


def _effective_lock_attrs(cls, class_map, _seen=None) -> dict:
    """Own lock attrs plus those inherited from bases resolvable in the
    project (Counter(_Metric) guards with the _Metric-assigned lock)."""
    _seen = _seen or set()
    if cls.name in _seen:
        return {}
    _seen.add(cls.name)
    attrs = dict(_lock_attrs(cls))
    for base in cls.bases:
        bname = dotted(base).split(".")[-1]
        entry = class_map.get(bname)
        if entry is not None:
            for k, v in _effective_lock_attrs(entry[1], class_map, _seen).items():
                attrs.setdefault(k, v)
    return attrs


def check_locks(project) -> list:
    findings: list = []
    class_map = {cls.name: (mod, cls) for mod, cls in project.classes()}
    # class name -> (module, ClassDef, lock_attrs) for typed composition edges
    lock_classes: dict = {}
    for mod, cls in project.classes():
        attrs = _effective_lock_attrs(cls, class_map)
        if attrs:
            lock_classes[cls.name] = (mod, cls, attrs)

    hold_edges: dict = {}  # class name -> set of class names acquired while held
    for cname, (mod, cls, lock_attrs) in lock_classes.items():
        methods = _methods(cls)

        # guarded attrs: lock IDENTITY matters — state guarded by self._a
        # is not protected by a method that only holds self._b. Each attr's
        # guard is the lock held at MOST of its locked mutation sites
        # (deterministic name tie-break), so a single wrong-lock site
        # cannot vote itself legitimate.
        lock_votes: dict = {}  # attr -> {lock: site count}
        for name, fn in methods.items():
            if name == "__init__":
                continue
            for attr, _line, held in _mutations(fn, lock_attrs):
                if held and attr not in lock_attrs:
                    votes = lock_votes.setdefault(attr, {})
                    for lock in held:
                        votes[lock] = votes.get(lock, 0) + 1
        guard_of = {
            attr: min(votes, key=lambda k: (-votes[k], k))
            for attr, votes in lock_votes.items()
        }

        locked_only = _locked_only_methods(cls, lock_attrs)

        # GL201: mutation of guarded state without holding its guard lock
        # (covers both the unlocked and the wrong-lock case)
        for name, fn in methods.items():
            if name == "__init__" or name in locked_only:
                continue
            for attr, line, held in _mutations(fn, lock_attrs):
                guard = guard_of.get(attr)
                if guard is not None and guard not in held:
                    findings.append(
                        Finding(
                            mod.path,
                            line,
                            "GL201",
                            f"{cname}.{name} mutates self.{attr} without "
                            f"holding self.{guard}, which guards it "
                            f"elsewhere in the class (lost-update race)",
                        )
                    )

        # GL203: re-entering a HELD plain Lock through a same-class method
        # call (direct recursion included — self.m() from inside m's own
        # locked region re-acquires just as fatally)
        plain = {a for a, kind in lock_attrs.items() if kind == "lock"}
        if plain:
            for name, fn in methods.items():
                for node, held in _walk_with_lock(fn, lock_attrs):
                    held_plain = held & plain
                    if not held_plain or not isinstance(node, ast.Call):
                        continue
                    if (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                    ):
                        reentered = held_plain & _acquired_attrs(
                            methods[node.func.attr], plain
                        )
                        if reentered:
                            lock = "/".join(sorted(reentered))
                            findings.append(
                                Finding(
                                    mod.path,
                                    node.lineno,
                                    "GL203",
                                    f"{cname}.{name} holds non-reentrant "
                                    f"self.{lock} and calls "
                                    f"self.{node.func.attr}() which "
                                    f"re-acquires it — deadlock (use RLock "
                                    f"or an unlocked helper)",
                                )
                            )

        # holds-while-acquiring edges for GL202, via attributes typed by
        # construction (self.other = OtherClass(...) in __init__)
        attr_types: dict = {}
        init = methods.get("__init__")
        if init is not None:
            for node in ast.walk(init):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    callee = dotted(node.value.func).split(".")[-1]
                    if callee in lock_classes:
                        for t in node.targets:
                            attr = _mutated_attr(t)
                            if attr:
                                attr_types[attr] = callee
        for name, fn in methods.items():
            for node, held in _walk_with_lock(fn, lock_attrs):
                if not held or not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Attribute) and isinstance(
                    node.func.value, ast.Attribute
                ):
                    recv = node.func.value
                    if (
                        isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and recv.attr in attr_types
                    ):
                        other = attr_types[recv.attr]
                        omod, ocls, oattrs = lock_classes[other]
                        ofn = _methods(ocls).get(node.func.attr)
                        if ofn is not None and _acquires_lock(ofn, oattrs):
                            hold_edges.setdefault(cname, {})[other] = (
                                mod.path,
                                node.lineno,
                            )

    # GL202: cycles in the holds-while-acquiring graph
    reported: set = set()
    for a, targets in hold_edges.items():
        for b, (path, line) in targets.items():
            if a == b:
                continue
            if a in hold_edges.get(b, {}) and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                findings.append(
                    Finding(
                        path,
                        line,
                        "GL202",
                        f"lock-order cycle: {a} acquires {b}'s lock while "
                        f"holding its own, and {b} does the reverse — "
                        f"ABBA deadlock under contention",
                    )
                )
    return findings
