"""``python -m karpenter_tpu.analysis [paths...]`` — the graftlint CLI.

Prints one ``path:line: RULE-ID message`` per unsuppressed finding and
exits per the contract documented in ``analysis/__init__.py``: 0 clean,
1 when findings survive baseline filtering, 2 on usage/I/O errors.
Multiple roots are supported (``karpenter_tpu/ perf/ bench.py``);
``--rules`` restricts reporting to a comma-separated id set, ``--json``
emits the machine-readable preflight report, ``--baseline FILE``
subtracts a committed findings snapshot (``--update-baseline`` rewrites
it). Suppressed counts ride the summary line so justified exceptions
stay visible. This is the tier-1 gate entry point
(tests/test_static_analysis.py asserts a zero-finding tree) and
bench.py's preflight.
"""

from __future__ import annotations

import argparse
import json
import sys

from karpenter_tpu.analysis import (
    RULES,
    analyze_project,
    apply_baseline,
    load_baseline,
    producer_census,
    write_baseline,
)
from karpenter_tpu.analysis.core import Project


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="karpenter_tpu.analysis",
        description="graftlint: tracing-safety, lock-discipline, drift, "
        "and contract checks for the karpenter_tpu tree",
    )
    ap.add_argument("paths", nargs="*", default=["karpenter_tpu"],
                    help="files or directories to analyze (default: karpenter_tpu)")
    ap.add_argument("--list-rules", "--rules-table", action="store_true",
                    dest="list_rules", help="print the rule ids and exit")
    ap.add_argument("--rules", default=None, metavar="GL101,GL502,...",
                    help="restrict reporting to these comma-separated rule ids")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report (findings, "
                    "baseline split, suppressed count, GL502 census)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="subtract this findings snapshot; missing file = "
                    "empty baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite --baseline FILE from the current findings "
                    "and exit 0")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule}  {RULES[rule]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            print(f"graftlint: unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    if args.update_baseline and not args.baseline:
        print("graftlint: --update-baseline requires --baseline FILE",
              file=sys.stderr)
        return 2

    try:
        project = Project.from_paths(args.paths or ["karpenter_tpu"])
    except (FileNotFoundError, OSError) as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    findings, suppressed = analyze_project(project, rules=rules)

    if args.update_baseline:
        try:
            write_baseline(args.baseline, findings)
        except OSError as exc:
            print(f"graftlint: cannot write baseline: {exc}",
                  file=sys.stderr)
            return 2
        print(f"graftlint: baseline updated ({len(findings)} finding(s))",
              file=sys.stderr)
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else set()
    new, baselined = apply_baseline(findings, baseline)

    if args.json:
        print(json.dumps({
            "ok": not new,
            "findings": [f.render() for f in new],
            "baselined": [f.render() for f in baselined],
            "suppressed": len(suppressed),
            "census": producer_census(project),
            "rules": {r: RULES[r] for r in sorted(rules or RULES)},
        }, indent=2, sort_keys=True))
        return 1 if new else 0

    for f in new:
        print(f.render())
    print(
        f"graftlint: {len(new)} finding(s), "
        f"{len(baselined)} baselined, {len(suppressed)} suppressed",
        file=sys.stderr,
    )
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
