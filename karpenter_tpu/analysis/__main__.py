"""``python -m karpenter_tpu.analysis [paths...]`` — the graftlint CLI.

Prints one ``path:line: RULE-ID message`` per unsuppressed finding and
exits 1 when any exist (0 otherwise); suppressed counts ride the summary
line so justified exceptions stay visible. ``--list-rules`` documents the
rule set. This is the tier-1 gate entry point (tests/test_static_analysis.py
asserts a zero-finding tree) and bench.py's preflight.
"""

from __future__ import annotations

import argparse
import sys

from karpenter_tpu.analysis import RULES, analyze_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="karpenter_tpu.analysis",
        description="graftlint: tracing-safety, lock-discipline, and drift "
        "checks for the karpenter_tpu tree",
    )
    ap.add_argument("paths", nargs="*", default=["karpenter_tpu"],
                    help="files or directories to analyze (default: karpenter_tpu)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule ids and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule}  {RULES[rule]}")
        return 0

    findings, suppressed = analyze_paths(args.paths or ["karpenter_tpu"])
    for f in findings:
        print(f.render())
    print(
        f"graftlint: {len(findings)} finding(s), "
        f"{len(suppressed)} suppressed",
        file=sys.stderr,
    )
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
