"""graftlint: repo-native static analysis for the jax_graft codebase.

Rule families over the package AST (stdlib-only, no jax import —
cheap enough to run as a tier-1 gate and as bench.py's preflight):

- GL1xx tracing safety (tracing.py): host syncs, traced-value branching,
  trace-time side effects, and jit-in-loop recompilation storms in code
  reachable from ``jax.jit`` / ``pl.pallas_call`` entries.
- GL4xx observability safety (tracing.py, riding the same GL1xx
  reachability pass): no obs flight-recorder span enter/exit
  (``span``/``round_trace``) or anomaly/recorder mutation may be
  reachable from jit/pallas-traced code — the tracer stays
  safe-by-construction on the solve path.
- GL2xx lock discipline (locks.py): unguarded mutation of lock-guarded
  state, ABBA lock-order cycles, and plain-Lock re-entry deadlocks.
- GL3xx drift (drift.py): stale/dead ``__init__`` export surface and
  swallowed exceptions in controller reconcile paths.
- GL5xx contracts (contracts.py): env-knob discipline + cache-fingerprint
  coverage (GL501), closed decision-ledger enums checked against
  obs/decisions.py SITES with wrapper/carrier resolution (GL502),
  capsule-seam coverage on every shared-dispatch path (GL503), and
  host-sync-inside-dispatch-loop detection (GL504).

CLI: ``python -m karpenter_tpu.analysis [paths...]`` with ``--rules``,
``--json``, ``--baseline FILE`` / ``--update-baseline``. Exit codes:

- **0** — no unsuppressed, non-baselined findings (also ``--rules`` and a
  successful ``--update-baseline``).
- **1** — at least one unsuppressed finding survived baseline filtering.
- **2** — usage or I/O error (unknown rule id in ``--rules``, unreadable
  path, unwritable baseline).

The baseline is a findings snapshot (one rendered ``path:line: RULE msg``
per line; ``#`` comments and blanks ignored) that lets a new rule land
strict-on-new-code while the tree burns down; the committed
``graftlint-baseline.txt`` is empty — the tree is clean and must stay so.
Suppress a justified pattern inline::

    # graftlint: disable=GL101 -- host-side guard; jitted callers pass it

See core.py for the directive grammar (line, def/class scope, and
file-level forms).
"""

from __future__ import annotations

from karpenter_tpu.analysis.contracts import (
    RULES as _CONTRACT_RULES,
    check_contracts,
    producer_census,
)
from karpenter_tpu.analysis.core import Finding, Project
from karpenter_tpu.analysis.drift import RULES as _DRIFT_RULES, check_drift
from karpenter_tpu.analysis.locks import RULES as _LOCK_RULES, check_locks
from karpenter_tpu.analysis.tracing import RULES as _TRACING_RULES, check_tracing

RULES: dict = {**_TRACING_RULES, **_LOCK_RULES, **_DRIFT_RULES,
               **_CONTRACT_RULES}

__all__ = [
    "Finding",
    "Project",
    "RULES",
    "analyze_project",
    "analyze_paths",
    "analyze_sources",
    "load_baseline",
    "write_baseline",
    "apply_baseline",
    "preflight",
    "preflight_report",
    "producer_census",
]


def analyze_project(project: Project, rules=None):
    """Run every rule family; returns (findings, suppressed) sorted by
    position, deduplicated by (path, line, rule). ``rules`` (an iterable
    of ids) restricts the output — the passes still run whole-program so
    inter-procedural context is never truncated."""
    raw = (check_tracing(project) + check_locks(project)
           + check_drift(project) + check_contracts(project))
    by_path = {m.path: m for m in project.modules.values()}
    keep = set(rules) if rules is not None else None
    findings, suppressed, seen = [], [], set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.path, f.line, f.rule)
        if key in seen:
            continue
        seen.add(key)
        if keep is not None and f.rule not in keep:
            continue
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.rule):
            suppressed.append(f)
        else:
            findings.append(f)
    return findings, suppressed


def analyze_paths(paths, rules=None):
    return analyze_project(Project.from_paths(paths), rules=rules)


def analyze_sources(sources: dict, rules=None):
    """Fixture entry point: {dotted_module_name: source} -> (findings,
    suppressed). Used by tests to seed positive/negative rule fixtures."""
    return analyze_project(Project.from_sources(sources), rules=rules)


def load_baseline(path) -> set:
    """A baseline file -> set of rendered finding lines. A missing file is
    an empty baseline (new checkouts start strict)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except FileNotFoundError:
        return set()
    return {ln.strip() for ln in lines
            if ln.strip() and not ln.strip().startswith("#")}


def write_baseline(path, findings) -> None:
    """Snapshot ``findings`` (Finding objects or rendered strings) so a
    new rule can land strict-on-new-code while the listed debt burns
    down."""
    rendered = sorted(
        f if isinstance(f, str) else f.render() for f in findings
    )
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# graftlint baseline — accepted findings, one rendered\n"
                 "# `path:line: RULE message` per line. Burn down, never\n"
                 "# grow: remove lines as the debt is fixed.\n")
        for line in rendered:
            fh.write(line + "\n")


def apply_baseline(findings, baseline: set):
    """-> (new, baselined): findings whose rendered line is in the
    baseline are accepted debt, everything else must be fixed."""
    new, baselined = [], []
    for f in findings:
        (baselined if f.render() in baseline else new).append(f)
    return new, baselined


def preflight(paths) -> list:
    """Rendered unsuppressed findings for embedding callers (bench.py runs
    this before a long benchmark so a lint regression fails in seconds)."""
    findings, _ = analyze_paths(paths)
    return [f.render() for f in findings]


def preflight_report(paths, baseline_path=None) -> dict:
    """Machine-readable full-rule-set report (the ``--json`` payload):
    findings after baseline filtering, suppression/baseline counts, the
    GL502 producer census, and the rule table. ``ok`` is the exit-0
    condition."""
    project = Project.from_paths(paths)
    findings, suppressed = analyze_project(project)
    baseline = load_baseline(baseline_path) if baseline_path else set()
    new, baselined = apply_baseline(findings, baseline)
    return {
        "ok": not new,
        "findings": [f.render() for f in new],
        "baselined": [f.render() for f in baselined],
        "suppressed": len(suppressed),
        "census": producer_census(project),
        "rules": dict(sorted(RULES.items())),
    }
