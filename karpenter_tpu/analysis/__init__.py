"""graftlint: repo-native static analysis for the jax_graft codebase.

Rule families over the package AST (stdlib-only, no jax import —
cheap enough to run as a tier-1 gate and as bench.py's preflight):

- GL1xx tracing safety (tracing.py): host syncs, traced-value branching,
  trace-time side effects, and jit-in-loop recompilation storms in code
  reachable from ``jax.jit`` / ``pl.pallas_call`` entries.
- GL4xx observability safety (tracing.py, riding the same GL1xx
  reachability pass): no obs flight-recorder span enter/exit
  (``span``/``round_trace``) or anomaly/recorder mutation may be
  reachable from jit/pallas-traced code — the tracer stays
  safe-by-construction on the solve path.
- GL2xx lock discipline (locks.py): unguarded mutation of lock-guarded
  state, ABBA lock-order cycles, and plain-Lock re-entry deadlocks.
- GL3xx drift (drift.py): stale/dead ``__init__`` export surface and
  swallowed exceptions in controller reconcile paths.

CLI: ``python -m karpenter_tpu.analysis [paths...]`` — exits nonzero on
any unsuppressed finding. Suppress a justified pattern inline::

    # graftlint: disable=GL101 -- host-side guard; jitted callers pass it

See core.py for the directive grammar (line, def/class scope, and
file-level forms).
"""

from __future__ import annotations

from karpenter_tpu.analysis.core import Finding, Project
from karpenter_tpu.analysis.drift import RULES as _DRIFT_RULES, check_drift
from karpenter_tpu.analysis.locks import RULES as _LOCK_RULES, check_locks
from karpenter_tpu.analysis.tracing import RULES as _TRACING_RULES, check_tracing

RULES: dict = {**_TRACING_RULES, **_LOCK_RULES, **_DRIFT_RULES}

__all__ = [
    "Finding",
    "Project",
    "RULES",
    "analyze_project",
    "analyze_paths",
    "analyze_sources",
    "preflight",
]


def analyze_project(project: Project):
    """Run every rule family; returns (findings, suppressed) sorted by
    position, deduplicated by (path, line, rule)."""
    raw = check_tracing(project) + check_locks(project) + check_drift(project)
    by_path = {m.path: m for m in project.modules.values()}
    findings, suppressed, seen = [], [], set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.path, f.line, f.rule)
        if key in seen:
            continue
        seen.add(key)
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.rule):
            suppressed.append(f)
        else:
            findings.append(f)
    return findings, suppressed


def analyze_paths(paths):
    return analyze_project(Project.from_paths(paths))


def analyze_sources(sources: dict):
    """Fixture entry point: {dotted_module_name: source} -> (findings,
    suppressed). Used by tests to seed positive/negative rule fixtures."""
    return analyze_project(Project.from_sources(sources))


def preflight(paths) -> list:
    """Rendered unsuppressed findings for embedding callers (bench.py runs
    this before a long benchmark so a lint regression fails in seconds)."""
    findings, _ = analyze_paths(paths)
    return [f.render() for f in findings]
