"""graftlint core: project loading, findings, and suppressions.

The analyzer is a repo-native AST pass (stdlib only — it must import
neither jax nor the package it inspects, so bench.py's preflight and the
tier-1 gate stay cheap and hermetic). Three moving parts live here:

- ``Module``: one parsed source file plus its ``# graftlint:`` directives
  (collected via tokenize, since ast drops comments),
- ``Project``: the module set a run analyzes, with enough import
  resolution for the cross-module rules (jit reachability, export drift,
  lock-order edges),
- ``Finding`` + suppression matching: a directive on the finding line, on
  the enclosing ``def``/``class`` header line, or a file-level
  ``disable-file`` mutes a finding; muted findings still count in the
  summary so drift stays visible.

Directive grammar (the ``--`` justification is REQUIRED — a bare
``disable=`` suppresses nothing, so every muted finding carries its why)::

    # graftlint: disable=GL101 -- host-side guard, jitted callers pass it
    # graftlint: disable=GL201,GL203 -- single-threaded test double
    # graftlint: disable-file=GL303 -- reconcile errors surface via events
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

PACKAGE = "karpenter_tpu"

# the `-- justification` clause is MANDATORY: a bare disable does not
# suppress anything, so the ROADMAP policy ("suppress only with an inline
# justification") is machine-enforced, not aspirational
_DIRECTIVE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+?)\s*--\s*\S"
)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class Module:
    path: str
    name: str  # dotted module name, e.g. karpenter_tpu.ops.kernels
    source: str
    tree: ast.Module = field(init=False)
    # line -> rule ids disabled on that line
    line_disables: dict = field(default_factory=dict)
    file_disables: set = field(default_factory=set)
    # (start, end, header_line) for every def/class scope
    scopes: list = field(default_factory=list)

    def __post_init__(self):
        self.tree = ast.parse(self.source, filename=self.path)
        self._collect_directives()
        self._collect_scopes()

    def _collect_directives(self):
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _DIRECTIVE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if m.group(1) == "disable-file":
                    self.file_disables |= rules
                else:
                    self.line_disables.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass  # unterminated source: ast.parse would have raised first

    def _collect_scopes(self):
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                self.scopes.append((node.lineno, node.end_lineno, node.lineno))

    def suppressed(self, line: int, rule: str) -> bool:
        if rule in self.file_disables:
            return True
        if rule in self.line_disables.get(line, ()):
            return True
        # a directive on a comment-only line covers the statement below it
        lines = self.source.splitlines()
        prev = line - 1
        while prev >= 1 and prev <= len(lines) and lines[prev - 1].lstrip().startswith("#"):
            if rule in self.line_disables.get(prev, ()):
                return True
            prev -= 1
        for start, end, header in self.scopes:
            if start <= line <= end and rule in self.line_disables.get(header, ()):
                return True
        return False


def _module_name(path: str) -> str:
    """Dotted module name anchored at the package directory; files outside
    the package (fixtures, scripts) get their stem."""
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if PACKAGE in parts:
        # LAST occurrence: a checkout directory named karpenter_tpu (the
        # natural clone name) must not double the module prefix and break
        # cross-module import resolution
        rel = parts[len(parts) - 1 - parts[::-1].index(PACKAGE):]
    else:
        rel = [parts[-1]]
    if rel[-1].endswith(".py"):
        rel[-1] = rel[-1][:-3]
    if rel[-1] == "__init__":
        rel = rel[:-1]
    return ".".join(rel) or "__main__"


class Project:
    """The analyzed module set with minimal import resolution."""

    def __init__(self, modules: list):
        self.modules: dict = {m.name: m for m in modules}

    @classmethod
    def from_paths(cls, paths) -> "Project":
        files = []
        for p in paths:
            if os.path.isdir(p):
                for root, dirs, names in os.walk(p):
                    dirs[:] = sorted(d for d in dirs if not d.startswith((".", "__pycache__")))
                    files.extend(os.path.join(root, n) for n in sorted(names) if n.endswith(".py"))
            elif os.path.isfile(p) and p.endswith(".py"):
                files.append(p)
            else:
                # a vanished path must fail the gate loudly, not let it
                # pass vacuously with zero modules analyzed
                raise FileNotFoundError(f"graftlint: no such file or directory: {p!r}")
        modules = []
        for f in files:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            modules.append(Module(path=f, name=_module_name(f), source=src))
        return cls(modules)

    @classmethod
    def from_sources(cls, sources: dict) -> "Project":
        """Test fixtures: {dotted_name: source}. Paths are synthesized; a
        name ending in ``.__init__`` becomes a package __init__.py module
        named without the suffix (so GL302's package rules apply)."""
        modules = []
        for name, src in sources.items():
            path = name.replace(".", "/") + ".py"
            if name.endswith(".__init__"):
                name = name[: -len(".__init__")]
            modules.append(Module(path=path, name=name, source=src))
        return cls(modules)

    # -- import resolution -------------------------------------------------
    def resolve_imports(self, module: Module) -> dict:
        """Local name -> ("module", Module) | ("symbol", Module, symbol).
        Covers the absolute-import idioms the package uses, including
        function-local imports."""
        env: dict = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self.modules.get(alias.name)
                    if target is not None:
                        env[alias.asname or alias.name.split(".")[0]] = ("module", target)
            elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    sub = self.modules.get(f"{node.module}.{alias.name}")
                    if sub is not None:
                        env[alias.asname or alias.name] = ("module", sub)
                        continue
                    src = self.modules.get(node.module)
                    if src is not None:
                        env[alias.asname or alias.name] = ("symbol", src, alias.name)
        return env

    def top_level_functions(self, module: Module) -> dict:
        return {
            n.name: n
            for n in module.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def classes(self):
        """Yield (module, ClassDef) over the whole project."""
        for mod in self.modules.values():
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    yield mod, node


def dotted(node) -> str:
    """Best-effort dotted-name rendering of a Name/Attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""
