"""GL5xx contract plane: whole-program enforcement of the repo's
hardest-won invariants — the ones previously pinned by brittle per-producer
tests and post-review hardening passes (CHANGES.md).

- GL501 env-knob discipline: every ``os.environ``/``os.getenv`` touch
  outside ``utils/envknobs.py`` is a finding (the trio owns empty-string/
  garbage/clamp semantics, and ``snapshot()`` is what replay capsules
  record — a stray read bypasses both). Separately, a ``KARPENTER_*``
  knob read reachable from a cache-fingerprint producer (a function that
  both ``CACHE.get(key)``-probes and ``CACHE[key] = ...``-fills a mapping
  on a locally-built key tuple) that does not flow into the key expression
  is a finding — the λ-not-in-fingerprint bug class (PR 15 fixed
  ops/tensorize.py's type-side cache by hand; this rule makes the fix
  structural). Knobs read inside the observability planes (``obs.*``,
  ``operator.logging``) and the analyzer itself are excluded from the
  reachability closure: they steer recording, not solver outputs.
- GL502 closed-ledger enforcement: every ``record_decision(site, rung,
  reason)`` producer is checked against the ``SITES`` registry parsed
  from ``obs/decisions.py`` itself — literal sites/rungs/reasons
  directly; reason/rung *carriers* (a local name, a ``self.attr``
  refusal slot, a ``LAST_RUN["plan_refusal"]`` dict key) through every
  literal assigned to them; thin wrapper methods (``self._verdict``)
  through their call sites. This retires the hand-maintained enum-pin
  greps in tests/test_decisions.py. ``producer_census()`` self-reports
  coverage so the gate can prove every site has a checked producer.
- GL503 seam coverage: a function dispatching through the shared
  chunk/dispatch primitives (``dispatch_counterfactual_rows`` and
  friends, ``sharded_solve``) without a ``record_capture`` reachable
  from it is flagged — a new dispatch path can never silently escape
  replay. Literal seam names are validated against ``capsule.SEAMS``.
  The replay module itself (obs/capsule.py) is exempt: replaying a
  capture must not capture the replay.
- GL504 host-sync-in-dispatch-loop: a ``for``/``while`` loop that both
  dispatches device work (reaches a dispatch primitive) and blocks on it
  per iteration (``.item()``, ``.block_until_ready()``,
  ``jax.device_get``) serializes the device — the static prerequisite
  for the one-device-program-per-round fusion (ROADMAP). Materialization
  *inside* the primitives is their contract and not flagged.

All four rules are pure AST passes over ``core.Project`` (stdlib-only, no
jax import) riding the same resolution machinery as the GL1xx taint pass.
Suppression follows the core grammar: ``# graftlint: disable=GL50x -- why``.
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.core import Finding, dotted

RULES = {
    "GL501": "os.environ/os.getenv touched outside utils/envknobs.py, or a KARPENTER_* knob reachable from a cache-fingerprint producer missing from its key",
    "GL502": "record_decision site/rung/reason outside the closed enums of obs/decisions.py SITES",
    "GL503": "dispatch through a shared chunk/dispatch primitive with no record_capture reachable (or an unknown capsule seam)",
    "GL504": "blocking host sync (.item()/.block_until_ready()/jax.device_get) inside a loop that also dispatches device work",
}

# modules allowed to touch os.environ (the knob parser itself)
_ENV_HOME_SUFFIX = "utils.envknobs"
# the envknob accessor surface — calls with a literal KARPENTER_* first
# arg are "knob reads" for the fingerprint-coverage half of GL501
_KNOB_FUNCS = {"env_int", "env_float", "env_bool", "env_str"}
# knobs read inside these planes steer *recording*, not solver outputs —
# excluded from the fingerprint reachability closure (a trace-ring size
# must not have to appear in a tensor-cache key)
_CLOSURE_EXEMPT_SEGMENTS = {"obs", "analysis"}
_CLOSURE_EXEMPT_SUFFIXES = ("operator.logging",)

# the shared chunk/dispatch primitives every new dispatch path rides;
# callers must keep a record_capture reachable (GL503) and must not
# host-sync around them per loop iteration (GL504)
_DISPATCH_PRIMITIVES = {
    "dispatch_counterfactual_rows",
    "dispatch_counterfactual_rows_native",
    "sharded_solve",
}
_CAPTURE_FUNCS = {"record_capture"}
_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_FUNCS = {"device_get"}
# the replay half: re-executing a capture must not re-capture
_CAPSULE_SUFFIX = "obs.capsule"
_DECISIONS_SUFFIX = "obs.decisions"

_MAX_VALUE_DEPTH = 8
_FIXPOINT_ROUNDS = 24


def _segments(name: str) -> set:
    return set(name.split("."))


def _is_env_home(mod) -> bool:
    return mod.name.endswith(_ENV_HOME_SUFFIX) or mod.name == "envknobs"


def _closure_exempt(mod) -> bool:
    if _segments(mod.name) & _CLOSURE_EXEMPT_SEGMENTS:
        return True
    return any(mod.name.endswith(s) for s in _CLOSURE_EXEMPT_SUFFIXES)


# ---------------------------------------------------------------------------
# project index: functions, enclosing classes, light call resolution
# ---------------------------------------------------------------------------


class _Index:
    """Per-project function table + call resolution shared by the GL5xx
    passes: top-level functions, class methods (``self.m`` resolves within
    the enclosing class), and module-alias attribute calls."""

    def __init__(self, project):
        self.project = project
        self.fns: list = []  # (module, fn, class_name|None)
        self._methods: dict = {}  # (mod.name, class_name) -> {name: fn}
        self._imports: dict = {}  # mod.name -> resolve_imports result
        self._top: dict = {}  # mod.name -> {name: fn}
        self._fn_ctx: dict = {}  # id(fn) -> (module, class_name|None)
        for mod in project.modules.values():
            self._imports[mod.name] = project.resolve_imports(mod)
            self._top[mod.name] = project.top_level_functions(mod)
            encl: dict = {}

            def walk(node, cls_name):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.ClassDef):
                        walk(child, child.name)
                    elif isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                        encl[id(child)] = cls_name
                        if cls_name is not None:
                            self._methods.setdefault(
                                (mod.name, cls_name), {}
                            ).setdefault(child.name, child)
                        walk(child, cls_name)
                    else:
                        walk(child, cls_name)

            walk(mod.tree, None)
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    cls = encl.get(id(node))
                    self.fns.append((mod, node, cls))
                    self._fn_ctx[id(node)] = (mod, cls)

    def context(self, fn):
        return self._fn_ctx.get(id(fn))

    def methods(self, mod, cls_name) -> dict:
        return self._methods.get((mod.name, cls_name), {})

    def resolve(self, mod, cls_name, func_expr):
        """Callee expression -> (module, fn, class_name|None) | None."""
        if isinstance(func_expr, ast.Name):
            fn = self._top[mod.name].get(func_expr.id)
            if fn is not None:
                return mod, fn, None
            bound = self._imports[mod.name].get(func_expr.id)
            if bound is not None and bound[0] == "symbol":
                tmod, sym = bound[1], bound[2]
                fn = self._top[tmod.name].get(sym)
                if fn is not None:
                    return tmod, fn, None
        elif isinstance(func_expr, ast.Attribute):
            recv = func_expr.value
            if isinstance(recv, ast.Name):
                if recv.id in ("self", "cls") and cls_name is not None:
                    fn = self.methods(mod, cls_name).get(func_expr.attr)
                    if fn is not None:
                        return mod, fn, cls_name
                bound = self._imports[mod.name].get(recv.id)
                if bound is not None and bound[0] == "module":
                    tmod = bound[1]
                    fn = self._top[tmod.name].get(func_expr.attr)
                    if fn is not None:
                        return tmod, fn, None
        return None

    # -- per-function facts + transitive closures -------------------------

    def direct_calls(self, mod, fn, cls_name):
        """Yield (call_node, resolved|None, final_name) for every call in
        ``fn`` (nested defs included — over-approximate reachability)."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                resolved = self.resolve(mod, cls_name, node.func)
                final = dotted(node.func).split(".")[-1]
                yield node, resolved, final

    def transitive_flags(self, direct_of):
        """Generic bottom-up closure: ``direct_of(mod, fn, cls)`` returns
        this function's own contribution (a set); the result maps
        ``id(fn)`` to the union over everything reachable through
        resolved project-local calls."""
        facts = {}
        edges = {}
        for mod, fn, cls in self.fns:
            facts[id(fn)] = set(direct_of(mod, fn, cls))
            edges[id(fn)] = {
                id(r[1]) for _, r, _ in self.direct_calls(mod, fn, cls)
                if r is not None
            }
        for _ in range(_FIXPOINT_ROUNDS):
            changed = False
            for fid, callees in edges.items():
                for cid in callees:
                    extra = facts.get(cid, ())
                    if not set(extra) <= facts[fid]:
                        facts[fid] |= set(extra)
                        changed = True
            if not changed:
                break
        return facts


# ---------------------------------------------------------------------------
# knob reads
# ---------------------------------------------------------------------------


def _knob_of_call(node: ast.Call):
    """A call that reads one literal KARPENTER_* knob -> its name."""
    name = dotted(node.func)
    final = name.split(".")[-1]
    if final in _KNOB_FUNCS or name in ("os.getenv",) or name.endswith(
        "environ.get"
    ):
        if node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str) and \
                node.args[0].value.startswith("KARPENTER_"):
            return node.args[0].value
    return None


def _direct_knobs(mod, fn) -> set:
    if _closure_exempt(mod) or _is_env_home(mod):
        return set()
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            k = _knob_of_call(node)
            if k is not None:
                out.add(k)
    return out


# ---------------------------------------------------------------------------
# GL501 — env-knob discipline
# ---------------------------------------------------------------------------


def _check_env_reads(project) -> list:
    findings = []
    for mod in project.modules.values():
        if _is_env_home(mod):
            continue
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and node.value.id == "os"
            ):
                findings.append(Finding(
                    mod.path, node.lineno, "GL501",
                    "os.environ touched outside utils/envknobs.py — route "
                    "the knob through env_int/env_float/env_bool/env_str "
                    "(applied_env for writes) so parse/clamp semantics and "
                    "the replay env snapshot stay unified",
                ))
            elif isinstance(node, ast.Call) and dotted(node.func) in (
                "os.getenv", "getenv"
            ):
                findings.append(Finding(
                    mod.path, node.lineno, "GL501",
                    "os.getenv outside utils/envknobs.py — use the envknobs "
                    "accessors so knob semantics cannot drift",
                ))
    return findings


def _is_tuple_expr(expr) -> bool:
    return isinstance(expr, ast.Tuple) or (
        isinstance(expr, ast.Call) and dotted(expr.func) == "tuple"
    )


def _fingerprint_producers(mod, fn):
    """Yield (key_name, key_assigns, probe_line) for every cache pattern
    in ``fn``: a name K probed via ``D.get(K)`` and filled via
    ``D[K] = ...`` on the same receiver, with K built locally as a tuple
    (fingerprints are key tuples — string-keyed counters and pass-through
    keys are not fingerprints). A receiver rebuilt as a fresh ``{}`` dict
    literal inside the function is a per-call memo, not a persistent
    cache: the environment is constant within one call, so it is exempt."""
    probes: dict = {}  # (recv, key) -> line
    fills: set = set()
    assigns: dict = {}  # name -> [value exprs]
    memo_recvs: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and node.args \
                and isinstance(node.args[0], ast.Name):
            recv = dotted(node.func.value)
            if recv:
                probes.setdefault((recv, node.args[0].id), node.lineno)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if node.value is None:
                continue
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                    t.slice, ast.Name
                ):
                    recv = dotted(t.value)
                    if recv:
                        fills.add((recv, t.slice.id))
                elif isinstance(t, ast.Name):
                    assigns.setdefault(t.id, []).append(node.value)
                    if isinstance(node.value, ast.Dict):
                        memo_recvs.add(t.id)
    for (recv, key), line in probes.items():
        if recv in memo_recvs or (recv, key) not in fills:
            continue
        key_exprs = assigns.get(key, [])
        if any(_is_tuple_expr(e) for e in key_exprs):
            yield key, key_exprs, line


def _expr_knobs(index, mod, cls_name, expr, knob_closure) -> set:
    """Knobs covered by an expression: direct literal knob reads plus the
    transitive knob set of every resolved callee inside it."""
    out = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            k = _knob_of_call(node)
            if k is not None:
                out.add(k)
            resolved = index.resolve(mod, cls_name, node.func)
            if resolved is not None:
                out |= knob_closure.get(id(resolved[1]), set())
    return out


def _check_fingerprints(project, index, knob_closure) -> list:
    findings = []
    for mod, fn, cls in index.fns:
        if _closure_exempt(mod) or _is_env_home(mod):
            continue
        # local single-name assignments, for resolving key-tuple elements
        local_assigns: dict = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                local_assigns.setdefault(
                    node.targets[0].id, []
                ).append(node.value)
        for key, key_exprs, line in _fingerprint_producers(mod, fn):
            reachable = set(knob_closure.get(id(fn), set()))
            if not reachable:
                continue
            covered = set()
            for kexpr in key_exprs:
                covered |= _expr_knobs(index, mod, cls, kexpr, knob_closure)
                for node in ast.walk(kexpr):
                    if isinstance(node, ast.Name):
                        for rhs in local_assigns.get(node.id, ()):
                            covered |= _expr_knobs(
                                index, mod, cls, rhs, knob_closure
                            )
            missing = sorted(reachable - covered)
            if missing:
                findings.append(Finding(
                    mod.path, line, "GL501",
                    f"cache fingerprint `{key}` in `{fn.name}` omits "
                    f"knob(s) {', '.join(missing)} read on its compute "
                    "path — a knob flip would serve stale entries; fold "
                    "the knob value into the key tuple",
                ))
    return findings


# ---------------------------------------------------------------------------
# GL502 — closed-ledger enforcement
# ---------------------------------------------------------------------------


def _parse_sites(project):
    """The SITES registry parsed from obs/decisions.py's own AST (no
    import): {site: {"rungs": tuple, "reasons": set}}. None when the
    registry module is not part of the analyzed set (fixture runs that
    exercise other rules)."""
    for mod in project.modules.values():
        if not (mod.name.endswith(_DECISIONS_SUFFIX)
                or mod.name == "decisions"):
            continue
        consts: dict = {}
        sites_node = None
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                tname = node.targets[0].id
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    consts[tname] = node.value.value
                if tname == "SITES":
                    sites_node = node.value
        if sites_node is None or not isinstance(sites_node, ast.Dict):
            continue

        def strs(node) -> set:
            out = set()
            if isinstance(node, ast.Call):  # frozenset({...})
                for a in node.args:
                    out |= strs(a)
            elif isinstance(node, (ast.Set, ast.Tuple, ast.List)):
                for e in node.elts:
                    out |= strs(e)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                out.add(node.value)
            elif isinstance(node, ast.Name) and node.id in consts:
                out.add(consts[node.id])
            return out

        sites = {}
        for k, v in zip(sites_node.keys, sites_node.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            if not isinstance(v, ast.Dict):
                continue
            spec = {"rungs": (), "reasons": set()}
            for fk, fv in zip(v.keys, v.values):
                if isinstance(fk, ast.Constant) and fk.value == "rungs":
                    spec["rungs"] = tuple(
                        e.value for e in getattr(fv, "elts", [])
                        if isinstance(e, ast.Constant)
                    )
                elif isinstance(fk, ast.Constant) and fk.value == "reasons":
                    spec["reasons"] = strs(fv)
            sites[k.value] = spec
        return sites
    return None


class _ValueScope:
    """Literal-string resolution for one expression site: function-local
    name assignments, module-wide attribute/dict-key writes, and
    module-level constants. Wrapper parameters surface as ("param", name)
    markers the caller substitutes."""

    def __init__(self, index, mod, fn, cls_name):
        self.index = index
        self.mod = mod
        self.fn = fn
        self.cls = cls_name
        self.params = set()
        if fn is not None:
            a = fn.args
            self.params = {p.arg for p in
                           (*a.posonlyargs, *a.args, *a.kwonlyargs)} - {
                               "self", "cls"}

    def _fn_assigns(self, name):
        if self.fn is None:
            return []
        out = []
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        out.append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                out.append(node.value)
        return out

    def _module_consts(self, name):
        out = []
        for node in self.mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == name:
                out.append(node.value)
        return out

    def _attr_writes(self, attr):
        """Every ``<recv>.attr = rhs`` (and annotated/class-level form)
        anywhere in the module."""
        out = []
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == attr:
                        out.append(node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Attribute) \
                    and node.target.attr == attr:
                out.append(node.value)
        return out

    def _key_writes(self, key):
        """Every ``D["key"] = rhs`` and ``D.update(key=rhs)`` in the
        module."""
        out = []
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and isinstance(
                        t.slice, ast.Constant
                    ) and t.slice.value == key:
                        out.append(node.value)
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "update":
                for kw in node.keywords:
                    if kw.arg == key:
                        out.append(kw.value)
        return out

    def values(self, expr, depth=0, seen=None):
        """-> set of ("lit", value, line) | ("param", name, line)."""
        if seen is None:
            seen = set()
        if depth > _MAX_VALUE_DEPTH or expr is None:
            return set()
        line = getattr(expr, "lineno", 0)
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, str):
                return {("lit", expr.value, line)}
            return set()  # None / numbers: not label literals
        if isinstance(expr, ast.IfExp):
            return self.values(expr.body, depth + 1, seen) | \
                self.values(expr.orelse, depth + 1, seen)
        if isinstance(expr, ast.BoolOp):
            out = set()
            for v in expr.values:
                out |= self.values(v, depth + 1, seen)
            return out
        if isinstance(expr, ast.Name):
            if expr.id in self.params:
                return {("param", expr.id, line)}
            key = ("name", expr.id)
            if key in seen:
                return set()
            seen = seen | {key}
            out = set()
            for rhs in self._fn_assigns(expr.id) or \
                    self._module_consts(expr.id):
                out |= self.values(rhs, depth + 1, seen)
            return out
        if isinstance(expr, ast.Attribute):
            key = ("attr", expr.attr)
            if key in seen:
                return set()
            seen = seen | {key}
            out = set()
            for rhs in self._attr_writes(expr.attr):
                out |= self.values(rhs, depth + 1, seen)
            return out
        if isinstance(expr, ast.Subscript) and isinstance(
            expr.slice, ast.Constant
        ) and isinstance(expr.slice.value, str):
            return self._from_key(expr.slice.value, depth, seen)
        if isinstance(expr, ast.Call) and isinstance(
            expr.func, ast.Attribute
        ) and expr.func.attr == "get" and expr.args and isinstance(
            expr.args[0], ast.Constant
        ) and isinstance(expr.args[0].value, str):
            out = self._from_key(expr.args[0].value, depth, seen)
            if len(expr.args) > 1:
                out |= self.values(expr.args[1], depth + 1, seen)
            return out
        return set()

    def _from_key(self, key, depth, seen):
        mark = ("key", key)
        if mark in seen:
            return set()
        seen = seen | {mark}
        out = set()
        for rhs in self._key_writes(key):
            out |= self.values(rhs, depth + 1, seen)
        return out

    def tuple_values(self, expr, depth=0):
        """Resolve a *-splatted rung/reason carrier: every Tuple assigned
        to the name/attribute -> list of (rung_values, reason_values)."""
        sources = []
        if isinstance(expr, ast.Name):
            sources = self._fn_assigns(expr.id) or \
                self._module_consts(expr.id)
        elif isinstance(expr, ast.Attribute):
            sources = self._attr_writes(expr.attr)
        out = []
        for rhs in sources:
            if isinstance(rhs, ast.Tuple) and rhs.elts:
                rung = self.values(rhs.elts[0], depth + 1)
                reason = (self.values(rhs.elts[1], depth + 1)
                          if len(rhs.elts) > 1
                          else {("lit", "ok", rhs.lineno)})
                out.append((rung, reason))
        return out


def _ledger_calls(mod):
    """Yield every record_decision-style call in the module (final name
    ``record_decision``, or ``.record`` on a DECISIONS receiver)."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        final = name.split(".")[-1]
        if final == "record_decision":
            yield node
        elif final == "record" and name.split(".")[0] in ("DECISIONS",):
            yield node


def _call_args(node):
    """-> (site_expr, rung_expr, reason_expr, star_expr) with keyword
    forms folded in; missing reason means the "ok" default."""
    site = rung = reason = star = None
    pos = []
    for a in node.args:
        if isinstance(a, ast.Starred):
            star = a.value
            break
        pos.append(a)
    if len(pos) > 0:
        site = pos[0]
    if len(pos) > 1:
        rung = pos[1]
    if len(pos) > 2:
        reason = pos[2]
    for kw in node.keywords:
        if kw.arg == "site":
            site = kw.value
        elif kw.arg == "rung":
            rung = kw.value
        elif kw.arg == "reason":
            reason = kw.value
    return site, rung, reason, star


def _wrapper_callsites(index, mod, fn, cls_name):
    """Call sites of a producer wrapper: ``self.<name>``/``cls.<name>``
    within the enclosing class, bare-name calls module-wide."""
    out = []
    for wmod, wfn, wcls in index.fns:
        if wmod is not mod:
            continue
        for node in ast.walk(wfn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == fn.name and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("self", "cls") and wcls == cls_name:
                out.append((wmod, wfn, wcls, node))
            elif isinstance(f, ast.Name) and f.id == fn.name and \
                    cls_name is None:
                out.append((wmod, wfn, wcls, node))
    return out


def _substitute(index, wrapper_fn, call, param):
    """The argument expression a wrapper call site passes for ``param``
    (positional, keyword, or the wrapper's own default)."""
    a = wrapper_fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args)]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            return None
        if i < len(names) and names[i] == param:
            return arg
    # defaults: align right over positional params
    defaults = a.defaults
    if defaults:
        defaulted = names[-len(defaults):]
        if param in defaulted:
            return defaults[defaulted.index(param)]
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == param and d is not None:
            return d
    return None


def check_ledger(project, census=None) -> list:
    """GL502. When ``census`` (a dict) is passed, fills in the producer
    self-report: checked call sites, distinct sites covered, registry
    size."""
    sites = _parse_sites(project)
    findings: list = []
    producers = 0
    covered_sites: set = set()
    if sites is None:
        if census is not None:
            census.update(producers=0, sites_covered=[], site_count=0)
        return findings
    index = _Index(project)

    def validate(mod, line, site_name, rung_vals, reason_vals):
        spec = sites.get(site_name)
        if spec is None:
            findings.append(Finding(
                mod.path, line, "GL502",
                f"unknown decision site {site_name!r} — sites are a closed "
                "registry (obs/decisions.py SITES); add the site there "
                "first",
            ))
            return
        for kind, v, vline in sorted(rung_vals):
            if kind == "lit" and v not in spec["rungs"]:
                findings.append(Finding(
                    mod.path, vline or line, "GL502",
                    f"rung {v!r} is not in site {site_name!r}'s ladder "
                    f"{spec['rungs']} — rungs are code constants "
                    "(obs/decisions.py)",
                ))
        for kind, v, vline in sorted(reason_vals):
            if kind == "lit" and v not in spec["reasons"]:
                findings.append(Finding(
                    mod.path, vline or line, "GL502",
                    f"reason {v!r} is outside site {site_name!r}'s closed "
                    "enum — unknown reasons clamp to \"other\" at runtime; "
                    "add the cause to SITES[...]['reasons'] or use an "
                    "existing one",
                ))

    for mod in project.modules.values():
        if mod.name.endswith(_DECISIONS_SUFFIX) or mod.name == "decisions":
            continue  # the ledger's own forwarding shims
        for call in _ledger_calls(mod):
            ctx = None
            for fmod, ffn, fcls in index.fns:
                if fmod is mod and ffn.lineno <= call.lineno <= \
                        (ffn.end_lineno or ffn.lineno):
                    if ctx is None or ffn.lineno > ctx[1].lineno:
                        ctx = (fmod, ffn, fcls)
            fn = ctx[1] if ctx else None
            cls = ctx[2] if ctx else None
            scope = _ValueScope(index, mod, fn, cls)
            site_e, rung_e, reason_e, star_e = _call_args(call)
            site_vals = scope.values(site_e) if site_e is not None else set()
            site_lits = {v for k, v, _ in site_vals if k == "lit"}
            site_params = {v for k, v, _ in site_vals if k == "param"}

            def rr_vals():
                if star_e is not None:
                    pairs = scope.tuple_values(star_e)
                    rung_v = set().union(*[p[0] for p in pairs]) \
                        if pairs else set()
                    reason_v = set().union(*[p[1] for p in pairs]) \
                        if pairs else set()
                    return rung_v, reason_v
                rung_v = scope.values(rung_e) if rung_e is not None else set()
                reason_v = (scope.values(reason_e)
                            if reason_e is not None
                            else {("lit", "ok", call.lineno)})
                return rung_v, reason_v

            rung_vals, reason_vals = rr_vals()
            if site_lits and not site_params:
                producers += 1
                covered_sites |= site_lits
                for s in sorted(site_lits):
                    validate(mod, call.lineno, s, rung_vals, reason_vals)
                # wrapper half: rung/reason params resolve per call site
                wrapper_params = {v for k, v, _ in rung_vals | reason_vals
                                  if k == "param"}
                if wrapper_params and fn is not None:
                    for wmod, wfn, wcls, wcall in _wrapper_callsites(
                        index, mod, fn, cls
                    ):
                        wscope = _ValueScope(index, wmod, wfn, wcls)
                        producers += 1

                        def resolved(vals):
                            out = set()
                            for k, v, vline in vals:
                                if k == "lit":
                                    out.add((k, v, vline))
                                else:
                                    sub = _substitute(index, fn, wcall, v)
                                    if sub is not None:
                                        out |= wscope.values(sub)
                            return out

                        for s in sorted(site_lits):
                            validate(wmod, wcall.lineno, s,
                                     resolved(rung_vals),
                                     resolved(reason_vals))
            elif site_params and fn is not None:
                # site itself is a wrapper parameter: validate per caller
                for wmod, wfn, wcls, wcall in _wrapper_callsites(
                    index, mod, fn, cls
                ):
                    wscope = _ValueScope(index, wmod, wfn, wcls)
                    for p in site_params:
                        sub = _substitute(index, fn, wcall, p)
                        if sub is None:
                            continue
                        for k, v, _ in wscope.values(sub):
                            if k == "lit":
                                producers += 1
                                covered_sites.add(v)
                                validate(wmod, wcall.lineno, v,
                                         rung_vals, reason_vals)
    if census is not None:
        census.update(producers=producers,
                      sites_covered=sorted(covered_sites),
                      site_count=len(sites))
    return findings


# ---------------------------------------------------------------------------
# GL503 — seam coverage
# ---------------------------------------------------------------------------


def _parse_seams(project):
    for mod in project.modules.values():
        if mod.name.endswith(_CAPSULE_SUFFIX) or mod.name == "capsule":
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == "SEAMS":
                    elts = getattr(node.value, "elts", [])
                    return tuple(e.value for e in elts
                                 if isinstance(e, ast.Constant))
    return None


def check_seams(project) -> list:
    findings: list = []
    index = _Index(project)
    seams = _parse_seams(project)

    captures = index.transitive_flags(
        lambda mod, fn, cls: {"capture"} if any(
            final in _CAPTURE_FUNCS
            for _, _, final in index.direct_calls(mod, fn, cls)
        ) else set()
    )

    for mod, fn, cls in index.fns:
        if mod.name.endswith(_CAPSULE_SUFFIX) or mod.name == "capsule":
            continue  # the replay half re-executes captures by design
        if fn.name in _DISPATCH_PRIMITIVES:
            continue  # the shared body itself; its CALLERS own the seam
        dispatch_call = None
        for node, _, final in index.direct_calls(mod, fn, cls):
            if final in _DISPATCH_PRIMITIVES:
                dispatch_call = node
                break
        if dispatch_call is None:
            continue
        if "capture" not in captures.get(id(fn), set()):
            findings.append(Finding(
                mod.path, dispatch_call.lineno, "GL503",
                f"`{fn.name}` dispatches through a shared chunk/dispatch "
                "primitive with no record_capture reachable — every "
                "dispatch path must register a capsule.SEAMS seam so an "
                "anomalous round stays replayable",
            ))

    if seams is not None:
        for mod in project.modules.values():
            if mod.name.endswith(_CAPSULE_SUFFIX) or mod.name == "capsule":
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and dotted(
                    node.func
                ).split(".")[-1] in _CAPTURE_FUNCS:
                    seam_e = node.args[0] if node.args else None
                    for kw in node.keywords:
                        if kw.arg == "seam":
                            seam_e = kw.value
                    if isinstance(seam_e, ast.Constant) and isinstance(
                        seam_e.value, str
                    ) and seam_e.value not in seams:
                        findings.append(Finding(
                            mod.path, node.lineno, "GL503",
                            f"capture seam {seam_e.value!r} is not in "
                            "capsule.SEAMS — seams are a closed registry "
                            "(obs/capsule.py); register the seam there "
                            "first",
                        ))
    return findings


# ---------------------------------------------------------------------------
# GL504 — host sync inside a dispatch loop
# ---------------------------------------------------------------------------


def _sync_verb(node):
    """A blocking host-sync call -> short description, else None."""
    if not isinstance(node, ast.Call):
        return None
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in _SYNC_ATTRS:
        return f".{node.func.attr}()"
    name = dotted(node.func)
    if name.split(".")[-1] in _SYNC_FUNCS:
        return f"{name}()"
    return None


def check_dispatch_loops(project) -> list:
    findings: list = []
    index = _Index(project)
    dispatches = index.transitive_flags(
        lambda mod, fn, cls: {"dispatch"} if any(
            final in _DISPATCH_PRIMITIVES
            for _, _, final in index.direct_calls(mod, fn, cls)
        ) else set()
    )

    for mod, fn, cls in index.fns:
        if mod.name.endswith(_CAPSULE_SUFFIX) or mod.name == "capsule":
            continue  # offline replay re-executes dispatches host-side
        if fn.name in _DISPATCH_PRIMITIVES:
            continue  # chunk-internal materialization is the contract
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            dispatching = False
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                final = dotted(node.func).split(".")[-1]
                if final in _DISPATCH_PRIMITIVES:
                    dispatching = True
                    break
                resolved = index.resolve(mod, cls, node.func)
                if resolved is not None and "dispatch" in dispatches.get(
                    id(resolved[1]), set()
                ):
                    dispatching = True
                    break
            if not dispatching:
                continue
            for node in ast.walk(loop):
                verb = _sync_verb(node)
                if verb is not None:
                    findings.append(Finding(
                        mod.path, node.lineno, "GL504",
                        f"`{verb}` inside a loop that also dispatches "
                        f"device work (`{fn.name}`) serializes the device "
                        "per iteration — batch the rows into one dispatch "
                        "or hoist the sync past the loop",
                    ))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def producer_census(project) -> dict:
    """GL502's self-report: how many record_decision producers the pass
    actually checked, and which sites they cover. The tier-1 gate asserts
    ``producers >= site_count`` so registry growth without a checked
    producer (or a producer pattern the pass stopped seeing) fails
    loudly."""
    census: dict = {}
    check_ledger(project, census=census)
    return census


def check_contracts(project) -> list:
    index = _Index(project)
    knob_closure = index.transitive_flags(
        lambda mod, fn, cls: _direct_knobs(mod, fn)
    )
    findings = _check_env_reads(project)
    findings += _check_fingerprints(project, index, knob_closure)
    findings += check_ledger(project)
    findings += check_seams(project)
    findings += check_dispatch_loops(project)
    return findings
