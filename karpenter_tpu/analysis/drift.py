"""GL3xx — drift checks: export surface and swallowed controller errors.

- GL301 stale-export: a name listed in a module's ``__all__`` that the
  module neither defines nor imports — a rename or deletion that left the
  public surface pointing at nothing (``from pkg import *`` and
  introspection-driven tools break at a distance).
- GL302 dead-export: an ``__init__.py`` re-export (``from .mod import X``)
  that is not in ``__all__`` and that nothing in the analyzed tree imports
  through the package path — surface that silently stopped being API.
  Listing a name in ``__all__`` documents intent and exempts it.
- GL303 swallowed-exception: in ``controllers/``, an ``except Exception``
  (or bare ``except``) whose handler neither re-raises, logs, counts, nor
  records the error — a reconcile loop that eats its failures is invisible
  exactly when it matters (the round-5 chaos flakes were this class).
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.core import Finding, dotted

RULES = {
    "GL301": "__all__ lists a name the module neither defines nor imports",
    "GL302": "__init__.py re-export not in __all__ and never imported via the package",
    "GL303": "except Exception in a controller path neither re-raises, logs, nor counts",
}

_LOGGISH = {
    "debug", "info", "warn", "warning", "error", "exception", "critical",
    "log", "inc", "observe", "record", "emit", "publish",
}


def _module_names(mod) -> tuple:
    """(defined, imported, all_entries_with_line)."""
    defined: set = set()
    imported: set = set()
    all_entries: list = []
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            defined.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    defined.add(t.id)
                    if t.id == "__all__" and isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                                all_entries.append((elt.value, elt.lineno))
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            defined.add(node.target.id)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                imported.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "*":
                    continue
                imported.add(alias.asname or alias.name)
    return defined, imported, all_entries


def _package_imports(project) -> set:
    """(package_name, symbol) pairs consumed anywhere in the tree via
    ``from package import symbol`` or ``package.symbol`` attribute access
    on an imported package alias."""
    used: set = set()
    for mod in project.modules.values():
        aliases: dict = {}  # local alias -> dotted module path
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
                for alias in node.names:
                    used.add((node.module, alias.name))
                    aliases[alias.asname or alias.name] = f"{node.module}.{alias.name}"
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    aliases[alias.asname or alias.name.split(".")[0]] = alias.name
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
                target = aliases.get(node.value.id)
                if target:
                    used.add((target, node.attr))
    return used


def check_exports(project) -> list:
    findings: list = []
    used = _package_imports(project)
    for mod in project.modules.values():
        defined, imported, all_entries = _module_names(mod)
        for name, line in all_entries:
            if name not in defined and name not in imported:
                findings.append(
                    Finding(
                        mod.path,
                        line,
                        "GL301",
                        f"__all__ exports `{name}` but {mod.name} neither "
                        "defines nor imports it (stale export)",
                    )
                )
        if not mod.path.endswith("__init__.py"):
            continue
        all_names = {n for n, _ in all_entries}
        # re-exported symbols: from .sub import X at module top level
        for node in mod.tree.body:
            if not (isinstance(node, ast.ImportFrom) and node.module and node.level == 0):
                continue
            if node.module == "__future__":
                continue
            for alias in node.names:
                name = alias.asname or alias.name
                # module re-exports (from pkg import submodule) are reachable
                # without the __init__ and aren't surface drift
                if f"{node.module}.{alias.name}" in project.modules:
                    continue
                if name in all_names:
                    continue
                if (mod.name, name) in used:
                    continue
                # consumed inside the __init__ body itself (not a pure re-export)
                body_uses = any(
                    isinstance(n, ast.Name) and n.id == name and isinstance(n.ctx, ast.Load)
                    for top in mod.tree.body
                    if not isinstance(top, (ast.Import, ast.ImportFrom))
                    for n in ast.walk(top)
                )
                if body_uses:
                    continue
                findings.append(
                    Finding(
                        mod.path,
                        node.lineno,
                        "GL302",
                        f"`{name}` is re-exported by {mod.name} but is not in "
                        "__all__ and nothing imports it through the package — "
                        "dead surface (add it to __all__ or drop the re-export)",
                    )
                )
    return findings


def _handler_surfaces_error(handler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            leaf = name.split(".")[-1]
            if leaf in _LOGGISH:
                return True
            if any(kw.arg == "exc_info" for kw in node.keywords):
                return True
    return False


def check_swallows(project) -> list:
    findings = []
    for mod in project.modules.values():
        if ".controllers." not in f".{mod.name}.":
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            etype = node.type
            broad = etype is None or (
                isinstance(etype, ast.Name) and etype.id in ("Exception", "BaseException")
            ) or (
                isinstance(etype, ast.Tuple)
                and any(
                    isinstance(e, ast.Name) and e.id in ("Exception", "BaseException")
                    for e in etype.elts
                )
            )
            if broad and not _handler_surfaces_error(node):
                findings.append(
                    Finding(
                        mod.path,
                        node.lineno,
                        "GL303",
                        "broad `except Exception` in a controller path "
                        "swallows the error — log it, count it, or re-raise "
                        "(silent reconcile failures are undiagnosable)",
                    )
                )
    return findings


def check_drift(project) -> list:
    return check_exports(project) + check_swallows(project)
