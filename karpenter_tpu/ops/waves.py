"""Topology compiler: constraint groups → device group plan.

TPU-native reformulation of the reference's TopologyGroup machinery
(topologygroup.go:167-265). The host engine resolves topology domain-by-
domain while pods stream through the FFD loop; the device path instead
compiles each constraint into static group structure the pack kernel
understands, so the whole batch stays one device call:

- zone topology spread (topologygroup.go nextDomainTopologySpread:167):
  placing identical pods one-at-a-time into the least-loaded allowed domain
  is exactly water-filling, so the per-zone pod counts are computed in
  closed form here and the group splits into zone-pinned SUBGROUPS. The
  zone pin rides the ordinary requirement mask — bins narrow to one zone
  exactly like host claims do. Counts from OTHER matching groups are only
  visible to the host engine when a matched pod lands on an
  already-pinned claim (Record commits singleton domains only,
  topology.py:290); the static plan ignores that narrow window.
- hostname topology spread (maxSkew s): every bin is its own hostname
  domain and an empty node is always mintable, so the domain-min is 0 and
  each bin may hold at most s pods of the group -> per-group BIN CAP.
- hostname pod anti-affinity (nextDomainAntiAffinity:252) as CONFLICT
  CLASSES: each distinct required hostname anti-affinity term is a class;
  a group DECLARING class c cannot share a bin with pods MATCHED by c
  (the direct TopologyGroup), and a group matched by c cannot share a bin
  with declarers (the inverse group, topology.go:225). Bins carry
  declared/matched class bitmasks in kernel state. Cluster-pod domain
  counts only name EXISTING nodes, which the device never packs onto, so
  they don't gate the new-bin path.
- zone pod affinity (nextDomainAffinity:219): pods need a domain with
  matches. With existing matches the allowed set is the non-empty domains;
  bootstrap pins the sorted-first allowed domain (the host engine uses the
  same deterministic tie-break).
- hostname pod affinity: all matching pods co-locate on one claim ->
  SINGLE-BIN group flag for the kernel.

Anything else — zone anti-affinity (the Schrödinger case records every
candidate domain, topology_test semantics), cross-group zone affinity,
preferred terms, minDomains, same-selector spreads with different
parameters — routes to the host engine, which remains the semantic oracle.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.api import labels as wk
from karpenter_tpu.models.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
)
from karpenter_tpu.ops.tensorize import UNCAPPED
from karpenter_tpu.scheduling import IN, Requirement, pod_requirements
from karpenter_tpu.utils import resources as resutil

WORD = 32


@dataclass
class DeviceGroup:
    """One kernel scan row: identical pods + compiled topology structure."""

    pods: list
    extra_reqs: list = field(default_factory=list)  # e.g. zone pin
    bin_cap: int = UNCAPPED  # max pods of this group per bin
    single_bin: bool = False  # hostname affinity: whole group in one bin
    decl_classes: frozenset = frozenset()  # hostname-anti classes declared
    match_classes: frozenset = frozenset()  # hostname-anti classes matched
    spread_caps: dict = field(default_factory=dict)  # owned spread class -> maxSkew
    spread_matches: frozenset = frozenset()  # spread classes counting this group
    zone_tail: bool = False  # scans after zone-spread owners


@dataclass
class WavesPlan:
    device_groups: list
    host_pods: list
    n_classes: int = 0
    n_spread_classes: int = 0
    # per-class TopologyGroup refs so the existing-node tensorizer can seed
    # per-node counts from the groups' domain maps (hostname-keyed)
    anti_tgs_by_class: list = field(default_factory=list)  # (direct, inverse|None)
    spread_tgs_by_class: list = field(default_factory=list)

    @property
    def device_pod_count(self):
        return sum(len(g.pods) for g in self.device_groups)

    def class_masks(self):
        """(g_decl [G,CW] u32, g_match [G,CW] u32) for the kernel."""
        G = len(self.device_groups)
        CW = max(1, (self.n_classes + WORD - 1) // WORD)
        decl = np.zeros((G, CW), dtype=np.uint32)
        match = np.zeros((G, CW), dtype=np.uint32)
        for g, dg in enumerate(self.device_groups):
            for c in dg.decl_classes:
                decl[g, c // WORD] |= np.uint32(1 << (c % WORD))
            for c in dg.match_classes:
                match[g, c // WORD] |= np.uint32(1 << (c % WORD))
        return decl, match

    def spread_tensors(self):
        """(g_sown [G,C] i32 cap where owned else UNCAPPED,
        g_smatch [G,C] bool) for the kernel's per-bin spread-class counts."""
        G = len(self.device_groups)
        C = max(1, self.n_spread_classes)
        sown = np.full((G, C), UNCAPPED, dtype=np.int32)
        smatch = np.zeros((G, C), dtype=bool)
        for g, dg in enumerate(self.device_groups):
            for c, cap in dg.spread_caps.items():
                sown[g, c] = cap
            for c in dg.spread_matches:
                smatch[g, c] = True
        return sown, smatch


def _group_key(g0):
    return (
        -g0.effective_requests().get(resutil.CPU, 0.0),
        -g0.effective_requests().get(resutil.MEMORY, 0.0),
    )


def _water_fill(counts: dict, n: int) -> dict:
    """Distribute n additions over domains, always raising the lowest —
    the closed form of the host's least-loaded-domain placement loop.
    Returns domain -> additions. Deterministic (sorted domain tie-break)."""
    out = {d: 0 for d in counts}
    cur = dict(counts)
    remaining = n
    while remaining > 0:
        lo = min(cur.values())
        lows = sorted(d for d in cur if cur[d] == lo)
        higher = [v for v in cur.values() if v > lo]
        gap = (min(higher) - lo) if higher else None
        if gap is not None and gap * len(lows) <= remaining:
            for d in lows:
                cur[d] += gap
                out[d] += gap
            remaining -= gap * len(lows)
        else:
            per, extra = divmod(remaining, len(lows))
            for j, d in enumerate(lows):
                add = per + (1 if j < extra else 0)
                cur[d] += add
                out[d] += add
            remaining = 0
    return out


def _spread_conflicts(topology) -> set:
    """Hash keys of spread groups sharing (key, selector, namespaces) with
    another spread group but different parameters — their counts interact
    in ways the static plan cannot express."""
    seen: dict = {}
    conflicted: set = set()
    for hk, tg in topology.topologies.items():
        if tg.type != TYPE_SPREAD:
            continue
        sel = hk[3]  # selector component of hash_key
        ident = (tg.key, sel, tg.namespaces)
        other = seen.get(ident)
        if other is not None and other != hk:
            conflicted.add(hk)
            conflicted.add(other)
        seen[ident] = hk
    return conflicted


def compile_topology(groups: list, topology) -> WavesPlan:
    """groups: list[list[Pod]] (identical pods per list, any order).
    Returns the device plan; pods whose constraints the device cannot
    express are returned in host_pods."""
    groups = sorted(groups, key=lambda g: _group_key(g[0]))  # FFD order

    if topology is None or not getattr(topology, "has_groups", False):
        return WavesPlan([DeviceGroup(list(g)) for g in groups], [])

    reps = [g[0] for g in groups]
    own_by_gid = [
        [tg for tg in topology.topologies.values() if rep.uid in tg.owners]
        for rep in reps
    ]
    spread_conflicted = _spread_conflicts(topology)

    # ---- hostname anti-affinity conflict classes ----
    # one class per distinct required hostname anti term owned in the batch
    anti_classes: dict = {}  # tg hash_key -> class index
    for gid, own in enumerate(own_by_gid):
        for tg in own:
            if tg.type == TYPE_ANTI_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                anti_classes.setdefault(tg.hash_key(), len(anti_classes))
    anti_tgs = {
        hk: tg for hk, tg in topology.topologies.items() if hk in anti_classes
    }

    # inverse groups whose declarers are NOT in this batch and whose key is
    # not hostname constrain allowed domains in ways the plan can't see
    zone_inverse = [
        tg for tg in topology.inverse_topologies.values()
        if tg.key != wk.HOSTNAME_LABEL
    ]

    # spread groups count by SELECTOR MATCH, not ownership
    # (topologygroup.go:167-217). Hostname spreads become SPREAD CLASSES:
    # bins carry a per-class pod COUNT contributed by every matched group
    # (owner or not), and a group OWNING class c may only land on a bin
    # while count + take <= maxSkew — the exact per-domain accounting of
    # the host engine, shared across co-owner groups and unconstrained
    # same-label groups alike. Zone spreads keep the compile-time
    # water-fill; matched non-owner groups are scanned AFTER the owners
    # (zone_tail) so every owner placement is legal with the counts it saw.
    spread_classes: dict = {}  # hostname-spread tg hash_key -> class index
    for own in own_by_gid:
        for tg in own:
            if tg.type == TYPE_SPREAD and tg.key == wk.HOSTNAME_LABEL:
                spread_classes.setdefault(tg.hash_key(), len(spread_classes))
    spread_tgs = {
        hk: tg for hk, tg in topology.topologies.items() if hk in spread_classes
    }
    zone_spread_tgs = [
        tg
        for tg in topology.topologies.values()
        if tg.type == TYPE_SPREAD and tg.key == wk.TOPOLOGY_ZONE_LABEL
        and any(tg in own for own in own_by_gid)
    ]

    device_groups: list = []
    host_pods: list = []
    overlay: dict = {}  # id(tg) -> compile-local domain counts

    for gid, pods in enumerate(groups):
        rep = reps[gid]
        own = own_by_gid[gid]

        if any(tg.selects(rep) for tg in zone_inverse):
            host_pods.extend(pods)
            continue
        own_ids = {id(tg) for tg in own}
        # matched by an in-batch zone spread it doesn't own: its landings
        # shift the owner's domain counts, so it scans after the owners
        # (its own zone choice is unconstrained, so the deferral is legal)
        zone_tail = any(
            id(tg) not in own_ids and tg.selects(rep) for tg in zone_spread_tgs
        )
        if zone_tail and any(
            tg.type == TYPE_SPREAD and tg.key == wk.TOPOLOGY_ZONE_LABEL
            for tg in own
        ):
            # owns one zone spread while matched by another: the compile-time
            # water-fills would need each other's answers — host engine
            host_pods.extend(pods)
            continue

        extra_reqs: list = []
        bin_cap = UNCAPPED
        single_bin = False
        zone_split = None  # domain -> count
        decl: set = set()
        spread_caps: dict = {}
        ok = True

        for tg in own:
            # compile-time domain counts live in an overlay so later
            # co-owner groups see this group's planned placements without
            # mutating the Topology object — ACTUAL placements are recorded
            # by the decoder, so a capacity spill cannot inflate the counts
            # the host fallback pass reads
            counts = overlay.setdefault(id(tg), dict(tg.domains))
            if tg.type == TYPE_SPREAD and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                if (
                    tg.min_domains is not None
                    or zone_split is not None
                    or tg.hash_key() in spread_conflicted
                ):
                    ok = False
                    break
                pod_zone = pod_requirements(rep).get_req(wk.TOPOLOGY_ZONE_LABEL)
                allowed = {d: c for d, c in counts.items() if pod_zone.has(d)}
                if not allowed:
                    ok = False
                    break
                zone_split = _water_fill(allowed, len(pods))
                for d, add in zone_split.items():
                    counts[d] = counts.get(d, 0) + add
                zone_split = {d: c for d, c in zone_split.items() if c > 0}
            elif tg.type == TYPE_SPREAD and tg.key == wk.HOSTNAME_LABEL:
                cls = spread_classes[tg.hash_key()]
                cap = max(int(tg.max_skew), 1)
                spread_caps[cls] = min(spread_caps.get(cls, cap), cap)
            elif tg.type == TYPE_ANTI_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                decl.add(anti_classes[tg.hash_key()])
            elif tg.type == TYPE_AFFINITY and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                # cross-group zone affinity (followers of an unpinned
                # in-batch target) stays on the host engine
                if any(tg.selects(r) for i, r in enumerate(reps) if i != gid):
                    ok = False
                    break
                nonzero = sorted(d for d, c in counts.items() if c > 0)
                pod_zone = pod_requirements(rep).get_req(wk.TOPOLOGY_ZONE_LABEL)
                if nonzero:
                    allowed_d = [d for d in nonzero if pod_zone.has(d)]
                    if not allowed_d:
                        ok = False
                        break
                    extra_reqs.append(Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, allowed_d))
                else:
                    # bootstrap is SELF-affinity only: a pod whose required
                    # affinity selector matches nobody (not even itself)
                    # cannot schedule (topology_test.go:2126) — the host
                    # engine produces the error
                    if not tg.selects(rep):
                        ok = False
                        break
                    # deterministic sorted-first allowed domain (the host
                    # engine's tie-break, topology.py:207)
                    first = next(
                        (d for d in sorted(counts) if pod_zone.has(d)), None
                    )
                    if first is None:
                        ok = False
                        break
                    extra_reqs.append(Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, [first]))
                    counts[first] = counts.get(first, 0) + len(pods)
            elif tg.type == TYPE_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                if any(tg.selects(r) for i, r in enumerate(reps) if i != gid) or any(
                    counts.values()
                ):
                    ok = False  # cross-group or existing matches: host
                    break
                if not tg.selects(rep):
                    ok = False  # matches nobody, not even itself: host fails it
                    break
                single_bin = True
            else:
                ok = False
                break

        if not ok:
            host_pods.extend(pods)
            continue

        # classes whose selector matches this group (the inverse direction)
        match = {
            c for hk, c in anti_classes.items() if anti_tgs[hk].selects(rep)
        }
        if decl & match:
            # self-matching anti-affinity: at most one pod of the group per
            # bin, the classic one-replica-per-node shape
            bin_cap = 1
        # spread classes counting this group's pods (selector match,
        # topologygroup.go:167 — ownership not required; an owner whose own
        # labels don't match its selector contributes nothing, exactly like
        # the host count)
        smatch = {
            c for hk, c in spread_classes.items() if spread_tgs[hk].selects(rep)
        }

        if zone_split:
            # zone-pinned subgroups; pods partitioned in order
            cursor = 0
            for d in sorted(zone_split):
                cnt = zone_split[d]
                sub = pods[cursor : cursor + cnt]
                cursor += cnt
                device_groups.append(
                    DeviceGroup(
                        sub,
                        extra_reqs + [Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, [d])],
                        bin_cap,
                        single_bin,
                        frozenset(decl),
                        frozenset(match),
                        dict(spread_caps),
                        frozenset(smatch),
                        zone_tail,
                    )
                )
        else:
            device_groups.append(
                DeviceGroup(
                    list(pods), extra_reqs, bin_cap, single_bin,
                    frozenset(decl), frozenset(match),
                    dict(spread_caps), frozenset(smatch), zone_tail,
                )
            )

    # zone-spread matched non-owners scan after the owners so each owner
    # placement is legal with the counts it saw at compile time (the tail's
    # own zone choice is unconstrained); FFD order preserved within parts
    device_groups.sort(key=lambda dg: dg.zone_tail)
    anti_by_class = [None] * len(anti_classes)
    for hk, c in anti_classes.items():
        anti_by_class[c] = (anti_tgs[hk], topology.inverse_topologies.get(hk))
    spread_by_class = [None] * len(spread_classes)
    for hk, c in spread_classes.items():
        spread_by_class[c] = spread_tgs[hk]
    return WavesPlan(
        device_groups,
        host_pods,
        n_classes=len(anti_classes),
        n_spread_classes=len(spread_classes),
        anti_tgs_by_class=anti_by_class,
        spread_tgs_by_class=spread_by_class,
    )
