"""Topology compiler: constraint groups → device group plan.

TPU-native reformulation of the reference's TopologyGroup machinery
(topologygroup.go:167-274). The host engine resolves topology domain-by-
domain while pods stream through the FFD loop; the device path instead
compiles each constraint into static group structure the pack kernel
understands, so the whole batch stays one device call:

- zone topology spread (topologygroup.go nextDomainTopologySpread:167):
  a SELF-SELECTING owner placing identical pods one-at-a-time into the
  least-loaded allowed domain is exactly water-filling, so per-zone pod
  counts are computed in closed form and the group splits into zone-pinned
  SUBGROUPS. A NON-self-selecting owner never moves the counts it is
  checked against, so every pod lands in the same (sorted-first) min-count
  domain — one pinned subgroup.
- hostname topology spread (maxSkew s): every bin is its own hostname
  domain and an empty node is always mintable, so the domain-min is 0 and
  the kernel carries per-bin SPREAD-CLASS counts capped at s.
- hostname pod anti-affinity (nextDomainAntiAffinity:252) as CONFLICT
  CLASSES: a group DECLARING class c cannot share a bin with pods MATCHED
  by c and vice versa (the direct/inverse TopologyGroup pair,
  topology.go:225); bins carry declared/matched class bitmasks.
- hostname pod affinity (nextDomainAffinity:219) as AFFINITY CLASSES with
  per-bin MATCH COUNTS: a group owning class c may only land on bins whose
  matched count is already positive; when no matches exist anywhere a
  self-matching group bootstraps exactly ONE fresh bin (the host's
  bootstrap, topology.py:211). Cross-group chains (A follows B's labels)
  resolve inside the scan because counts evolve per step — the compiler
  orders followers after their targets, mirroring the host queue's
  requeue-to-back of pods that fail a round (queue.go:76).
- zone pod affinity: resolved at COMPILE time against the same sequential
  overlay the zone spreads use — allowed zones are the overlay's non-empty
  domains of the class selector; a unique zone pins the group, multiple
  matches become a zone IN-set (uncounted, exactly like the host's
  non-singleton Record), and a selector with no matches yet DEFERS the
  group to a later compile round (the host requeue).

The compiler runs a sequential OVERLAY simulation in FFD order: every
group's zone-pinned landings bump the compile-local domain counts of every
zone-keyed group whose selector matches it (ownership not required —
topologygroup.go:167 counts by selector), so later groups see earlier
groups' placements exactly as the host loop would. Groups whose affinity
targets haven't landed yet retry in later rounds until a fixed point; the
remainder routes to the host engine, which stays the semantic oracle.

Anything else — zone anti-affinity (the Schrödinger case records every
candidate domain), preferred terms, minDomains, same-selector spreads with
different parameters, hostname affinity onto pre-existing cluster matches —
routes to the host engine.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.api import labels as wk
from karpenter_tpu.models.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
)
from karpenter_tpu.ops.tensorize import UNCAPPED
from karpenter_tpu.scheduling import IN, Requirement, pod_requirements
from karpenter_tpu.utils import resources as resutil

WORD = 32


@dataclass
class DeviceGroup:
    """One kernel scan row: identical pods + compiled topology structure."""

    pods: list
    extra_reqs: list = field(default_factory=list)  # e.g. zone pin
    bin_cap: int = UNCAPPED  # max pods of this group per bin
    single_bin: bool = False  # retained for direct kernel callers
    decl_classes: frozenset = frozenset()  # hostname-anti classes declared
    match_classes: frozenset = frozenset()  # hostname-anti classes matched
    spread_caps: dict = field(default_factory=dict)  # owned spread class -> maxSkew
    spread_matches: frozenset = frozenset()  # spread classes counting this group
    aff_need: frozenset = frozenset()  # hostname-affinity classes owned
    aff_match: frozenset = frozenset()  # hostname-affinity classes matching it


@dataclass
class WavesPlan:
    device_groups: list
    host_pods: list
    n_classes: int = 0
    n_spread_classes: int = 0
    n_aff_classes: int = 0
    # per-class TopologyGroup refs so the existing-node tensorizer can seed
    # per-node counts from the groups' domain maps (hostname-keyed)
    anti_tgs_by_class: list = field(default_factory=list)  # (direct, inverse|None)
    spread_tgs_by_class: list = field(default_factory=list)
    aff_tgs_by_class: list = field(default_factory=list)

    @property
    def device_pod_count(self):
        return sum(len(g.pods) for g in self.device_groups)

    def class_masks(self):
        """(g_decl [G,CW] u32, g_match [G,CW] u32) for the kernel."""
        G = len(self.device_groups)
        CW = max(1, (self.n_classes + WORD - 1) // WORD)
        decl = np.zeros((G, CW), dtype=np.uint32)
        match = np.zeros((G, CW), dtype=np.uint32)
        for g, dg in enumerate(self.device_groups):
            for c in dg.decl_classes:
                decl[g, c // WORD] |= np.uint32(1 << (c % WORD))
            for c in dg.match_classes:
                match[g, c // WORD] |= np.uint32(1 << (c % WORD))
        return decl, match

    def spread_tensors(self):
        """(g_sown [G,C] i32 cap where owned else UNCAPPED,
        g_smatch [G,C] bool) for the kernel's per-bin spread-class counts."""
        G = len(self.device_groups)
        C = max(1, self.n_spread_classes)
        sown = np.full((G, C), UNCAPPED, dtype=np.int32)
        smatch = np.zeros((G, C), dtype=bool)
        for g, dg in enumerate(self.device_groups):
            for c, cap in dg.spread_caps.items():
                sown[g, c] = cap
            for c in dg.spread_matches:
                smatch[g, c] = True
        return sown, smatch

    def aff_tensors(self):
        """(g_aneed [G,A] bool, g_amatch [G,A] bool) for the kernel's
        per-bin affinity-class match counts; bootstrap eligibility is
        derived in-kernel from amatch ∧ global-count==0."""
        G = len(self.device_groups)
        A = max(1, self.n_aff_classes)
        aneed = np.zeros((G, A), dtype=bool)
        amatch = np.zeros((G, A), dtype=bool)
        for g, dg in enumerate(self.device_groups):
            for c in dg.aff_need:
                aneed[g, c] = True
            for c in dg.aff_match:
                amatch[g, c] = True
        return aneed, amatch


def _group_key(g0):
    # FFD order (queue.go:37) with a most-constrained-first tie-break:
    # groups that will carry per-bin caps (required anti-affinity, hostname
    # spread) scan before unconstrained equals, so the bins their caps force
    # open are still fillable by the flexible groups behind them. Measured
    # on the anti+spread 5k config: 84 → 82 bins vs the host oracle's 81
    # (the host interleaves pod-at-a-time, which achieves the same effect).
    a = g0.affinity
    capped = bool(
        (a and a.pod_anti_affinity and a.pod_anti_affinity.required)
        or any(
            c.topology_key == wk.HOSTNAME_LABEL
            for c in g0.topology_spread_constraints
        )
    )
    return (
        -g0.effective_requests().get(resutil.CPU, 0.0),
        -g0.effective_requests().get(resutil.MEMORY, 0.0),
        0 if capped else 1,
    )


def _water_fill(counts: dict, n: int) -> dict:
    """Distribute n additions over domains, always raising the lowest —
    the closed form of the host's least-loaded-domain placement loop.
    Returns domain -> additions. Deterministic (sorted domain tie-break)."""
    out = {d: 0 for d in counts}
    cur = dict(counts)
    remaining = n
    while remaining > 0:
        lo = min(cur.values())
        lows = sorted(d for d in cur if cur[d] == lo)
        higher = [v for v in cur.values() if v > lo]
        gap = (min(higher) - lo) if higher else None
        if gap is not None and gap * len(lows) <= remaining:
            for d in lows:
                cur[d] += gap
                out[d] += gap
            remaining -= gap * len(lows)
        else:
            per, extra = divmod(remaining, len(lows))
            for j, d in enumerate(lows):
                add = per + (1 if j < extra else 0)
                cur[d] += add
                out[d] += add
            remaining = 0
    return out


def _spread_conflicts(topology) -> set:
    """Hash keys of spread groups sharing (key, selector, namespaces) with
    another spread group but different parameters — their counts interact
    in ways the static plan cannot express."""
    seen: dict = {}
    conflicted: set = set()
    for hk, tg in topology.topologies.items():
        if tg.type != TYPE_SPREAD:
            continue
        sel = hk[3]  # selector component of hash_key
        ident = (tg.key, sel, tg.namespaces)
        other = seen.get(ident)
        if other is not None and other != hk:
            conflicted.add(hk)
            conflicted.add(other)
        seen[ident] = hk
    return conflicted


_HOST = "host"
_DEFER = "defer"


class _Compiler:
    """Sequential overlay compile of one batch (see module docstring)."""

    def __init__(self, groups, topology):
        self.groups = groups
        self.topology = topology
        self.reps = [g[0] for g in groups]
        self.own_by_gid = [
            [tg for tg in topology.topologies.values() if rep.uid in tg.owners]
            for rep in self.reps
        ]
        self.spread_conflicted = _spread_conflicts(topology)
        # inverse anti groups whose declarers are NOT in this batch and whose
        # key is not hostname constrain allowed domains invisibly → host
        self.zone_inverse = [
            tg for tg in topology.inverse_topologies.values()
            if tg.key != wk.HOSTNAME_LABEL
        ]
        # one class per distinct required hostname term owned in the batch
        self.anti_classes: dict = {}
        self.aff_classes: dict = {}
        self.spread_classes: dict = {}
        for own in self.own_by_gid:
            for tg in own:
                if tg.key != wk.HOSTNAME_LABEL:
                    continue
                if tg.type == TYPE_ANTI_AFFINITY:
                    self.anti_classes.setdefault(tg.hash_key(), len(self.anti_classes))
                elif tg.type == TYPE_SPREAD:
                    self.spread_classes.setdefault(
                        tg.hash_key(), len(self.spread_classes))
                elif tg.type == TYPE_AFFINITY:
                    self.aff_classes.setdefault(tg.hash_key(), len(self.aff_classes))
        T = topology.topologies
        self.anti_tgs = {hk: T[hk] for hk in self.anti_classes}
        self.spread_tgs = {hk: T[hk] for hk in self.spread_classes}
        self.aff_tgs = {hk: T[hk] for hk in self.aff_classes}
        # compile-local domain counts for every ZONE-keyed spread/affinity
        # group; later groups see earlier groups' pinned landings exactly as
        # the host loop would
        self.overlay: dict = {}
        # in-batch matched-pod counts per hostname-affinity class (scan-order
        # viability; the kernel re-checks per bin at run time)
        self.aff_cnt = [0] * len(self.aff_classes)
        self.device_groups: list = []
        self.host_pods: list = []

    def _counts(self, tg) -> dict:
        c = self.overlay.get(id(tg))
        if c is None:
            c = self.overlay[id(tg)] = dict(tg.domains)
        return c

    def run(self) -> WavesPlan:
        pending = list(range(len(self.groups)))
        progress = True
        while progress and pending:
            progress = False
            still = []
            for gid in pending:
                outcome = self._compile_one(gid)
                if outcome is _DEFER:
                    still.append(gid)
                    continue
                progress = True
            pending = still
        for gid in pending:
            # affinity targets never materialized: the host queue fails these
            # the same way after its own retry cycle (queue.go:76 staleness)
            self.host_pods.extend(self.groups[gid])
        anti_by_class = [None] * len(self.anti_classes)
        for hk, c in self.anti_classes.items():
            anti_by_class[c] = (
                self.anti_tgs[hk], self.topology.inverse_topologies.get(hk))
        spread_by_class = [None] * len(self.spread_classes)
        for hk, c in self.spread_classes.items():
            spread_by_class[c] = self.spread_tgs[hk]
        aff_by_class = [None] * len(self.aff_classes)
        for hk, c in self.aff_classes.items():
            aff_by_class[c] = self.aff_tgs[hk]
        return WavesPlan(
            self.device_groups,
            self.host_pods,
            n_classes=len(self.anti_classes),
            n_spread_classes=len(self.spread_classes),
            n_aff_classes=len(self.aff_classes),
            anti_tgs_by_class=anti_by_class,
            spread_tgs_by_class=spread_by_class,
            aff_tgs_by_class=aff_by_class,
        )

    def _compile_one(self, gid):
        pods = self.groups[gid]
        rep = self.reps[gid]
        own = self.own_by_gid[gid]

        if any(tg.selects(rep) for tg in self.zone_inverse):
            self.host_pods.extend(pods)
            return _HOST

        extra_reqs: list = []
        bin_cap = UNCAPPED
        zone_split = None  # domain -> count (pinned landings)
        # set by ANY zone spread/affinity, pinned or not: composing two
        # zone constraints needs each other's answers → host engine
        zone_constrained = False
        decl: set = set()
        spread_caps: dict = {}
        aff_need: set = set()

        for tg in own:
            if tg.type == TYPE_SPREAD and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                split = self._zone_spread(tg, rep, len(pods), zone_constrained)
                if split is None:
                    self.host_pods.extend(pods)
                    return _HOST
                zone_split, zone_constrained = split, True
            elif tg.type == TYPE_SPREAD and tg.key == wk.HOSTNAME_LABEL:
                cls = self.spread_classes[tg.hash_key()]
                cap = max(int(tg.max_skew), 1)
                spread_caps[cls] = min(spread_caps.get(cls, cap), cap)
            elif tg.type == TYPE_ANTI_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                decl.add(self.anti_classes[tg.hash_key()])
            elif tg.type == TYPE_AFFINITY and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                res = self._zone_affinity(tg, rep, len(pods), zone_constrained)
                if res is _HOST:
                    self.host_pods.extend(pods)
                    return _HOST
                if res is _DEFER:
                    return _DEFER
                req, pinned = res
                extra_reqs.append(req)
                zone_constrained = True
                if pinned is not None:
                    zone_split = {pinned: len(pods)}
            elif tg.type == TYPE_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                if any(tg.domains.values()):
                    # pre-existing cluster matches: the host engine's
                    # exact-domain bootstrap onto registered hostnames is
                    # not expressible as class counts
                    self.host_pods.extend(pods)
                    return _HOST
                cls = self.aff_classes[tg.hash_key()]
                aff_need.add(cls)
                if not tg.selects(rep) and self.aff_cnt[cls] == 0:
                    # target labels haven't landed yet: retry after the
                    # rest of the batch (the host requeue-to-back)
                    return _DEFER
            else:
                self.host_pods.extend(pods)
                return _HOST

        # classes whose selector matches this group (the inverse direction)
        match = {
            c for hk, c in self.anti_classes.items()
            if self.anti_tgs[hk].selects(rep)
        }
        if decl & match:
            # self-matching anti-affinity: at most one pod of the group per
            # bin, the classic one-replica-per-node shape
            bin_cap = 1
        # spread classes counting this group's pods (selector match,
        # topologygroup.go:167 — ownership not required; an owner whose own
        # labels don't match its selector contributes nothing, exactly like
        # the host count)
        smatch = {
            c for hk, c in self.spread_classes.items()
            if self.spread_tgs[hk].selects(rep)
        }
        amatch = {
            c for hk, c in self.aff_classes.items()
            if self.aff_tgs[hk].selects(rep)
        }

        self._emit(
            pods, extra_reqs, bin_cap, zone_split,
            frozenset(decl), frozenset(match), dict(spread_caps),
            frozenset(smatch), frozenset(aff_need), frozenset(amatch),
        )
        self._bump_landings(rep, pods, zone_split)
        return "emit"

    # ---- per-constraint compile steps ----------------------------------
    def _zone_spread(self, tg, rep, n, zone_constrained):
        """domain -> count, or None for host."""
        if (
            tg.min_domains is not None
            or zone_constrained
            or tg.hash_key() in self.spread_conflicted
        ):
            return None
        counts = self._counts(tg)
        pod_zone = pod_requirements(rep).get_req(wk.TOPOLOGY_ZONE_LABEL)
        allowed = {d: c for d, c in counts.items() if pod_zone.has(d)}
        if not allowed:
            return None
        if tg.selects(rep):
            split = _water_fill(allowed, n)
            return {d: c for d, c in split.items() if c > 0}
        # non-self-selecting owner: counts never move, so every pod takes
        # the same min-count domain (sorted tie-break, topology.py:196);
        # maxSkew holds trivially at the minimum
        lo = min(allowed.values())
        d_star = sorted(d for d in allowed if allowed[d] == lo)[0]
        return {d_star: n}

    def _zone_affinity(self, tg, rep, n, zone_constrained):
        """(Requirement, pinned_zone|None) | _DEFER | _HOST."""
        if zone_constrained:
            return _HOST  # composed zone constraints: host engine
        counts = self._counts(tg)
        pod_zone = pod_requirements(rep).get_req(wk.TOPOLOGY_ZONE_LABEL)
        nonzero = sorted(d for d, c in counts.items() if c > 0 and pod_zone.has(d))
        if nonzero:
            if len(nonzero) == 1:
                return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, nonzero), nonzero[0])
            # several match domains: the pod may land in any (host records
            # nothing for non-singleton domains, topology.py:309)
            return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, nonzero), None)
        if not tg.selects(rep):
            return _DEFER
        # self-affinity bootstrap: deterministic sorted-first allowed domain
        # (the host engine's tie-break, topology.py:211-221)
        first = next((d for d in sorted(counts) if pod_zone.has(d)), None)
        if first is None:
            return _HOST  # no domain universe: host produces the error
        return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, [first]), first)

    # ---- landings ------------------------------------------------------
    def _emit(self, pods, extra_reqs, bin_cap, zone_split, decl, match,
              spread_caps, smatch, aff_need, amatch):
        if zone_split:
            # zone-pinned subgroups; pods partitioned in order
            cursor = 0
            for d in sorted(zone_split):
                cnt = zone_split[d]
                sub = pods[cursor: cursor + cnt]
                cursor += cnt
                self.device_groups.append(DeviceGroup(
                    sub,
                    extra_reqs + [Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, [d])],
                    bin_cap, False, decl, match, dict(spread_caps), smatch,
                    aff_need, amatch,
                ))
        else:
            self.device_groups.append(DeviceGroup(
                list(pods), extra_reqs, bin_cap, False, decl, match,
                dict(spread_caps), smatch, aff_need, amatch,
            ))

    def _bump_landings(self, rep, pods, zone_split):
        """Commit this group's pinned landings into the overlay so later
        groups (and later compile rounds) see them — the compile-time
        mirror of Topology.Record's singleton-domain commit."""
        pinned = zone_split
        if pinned is None:
            # a plain node-selector zone pin also counts (the claim's zone
            # set is a singleton, so the host records it)
            pz = pod_requirements(rep).get_req(wk.TOPOLOGY_ZONE_LABEL)
            if not pz.complement and len(pz.values) == 1:
                pinned = {next(iter(pz.values)): len(pods)}
        if pinned:
            for tg in self.topology.topologies.values():
                if tg.key != wk.TOPOLOGY_ZONE_LABEL:
                    continue
                if tg.type not in (TYPE_SPREAD, TYPE_AFFINITY):
                    continue
                if not tg.selects(rep):
                    continue
                counts = self._counts(tg)
                for d, c in pinned.items():
                    counts[d] = counts.get(d, 0) + c
        for hk, cls in self.aff_classes.items():
            if self.aff_tgs[hk].selects(rep):
                self.aff_cnt[cls] += len(pods)


def compile_topology(groups: list, topology) -> WavesPlan:
    """groups: list[list[Pod]] (identical pods per list, any order).
    Returns the device plan; pods whose constraints the device cannot
    express are returned in host_pods."""
    groups = sorted(groups, key=lambda g: _group_key(g[0]))  # FFD order

    if topology is None or not getattr(topology, "has_groups", False):
        return WavesPlan([DeviceGroup(list(g)) for g in groups], [])

    return _Compiler(groups, topology).run()
