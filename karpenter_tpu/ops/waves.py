"""Topology compiler: constraint groups → device group plan.

TPU-native reformulation of the reference's TopologyGroup machinery
(topologygroup.go:167-274). The host engine resolves topology domain-by-
domain while pods stream through the FFD loop; the device path instead
compiles each constraint into static group structure the pack kernel
understands, so the whole batch stays one device call:

- zone topology spread (topologygroup.go nextDomainTopologySpread:167):
  a SELF-SELECTING owner placing identical pods one-at-a-time into the
  least-loaded allowed domain is exactly water-filling, so per-zone pod
  counts are computed in closed form and the group splits into zone-pinned
  SUBGROUPS. A NON-self-selecting owner never moves the counts it is
  checked against, so every pod lands in the same (sorted-first) min-count
  domain — one pinned subgroup.
- hostname topology spread (maxSkew s): every bin is its own hostname
  domain and an empty node is always mintable, so the domain-min is 0 and
  the kernel carries per-bin SPREAD-CLASS counts capped at s.
- hostname pod anti-affinity (nextDomainAntiAffinity:252) as CONFLICT
  CLASSES: a group DECLARING class c cannot share a bin with pods MATCHED
  by c and vice versa (the direct/inverse TopologyGroup pair,
  topology.go:225); bins carry declared/matched class bitmasks.
- hostname pod affinity (nextDomainAffinity:219) as AFFINITY CLASSES with
  per-bin MATCH COUNTS: a group owning class c may only land on bins whose
  matched count is already positive; when no matches exist anywhere a
  self-matching group bootstraps exactly ONE fresh bin (the host's
  bootstrap, topology.py:211). Cross-group chains (A follows B's labels)
  resolve inside the scan because counts evolve per step — the compiler
  orders followers after their targets, mirroring the host queue's
  requeue-to-back of pods that fail a round (queue.go:76).
- zone pod affinity: resolved at COMPILE time against the same sequential
  overlay the zone spreads use — allowed zones are the overlay's non-empty
  domains of the class selector; a unique zone pins the group, multiple
  matches become a zone IN-set (uncounted, exactly like the host's
  non-singleton Record), and a selector with no matches yet DEFERS the
  group to a later compile round (the host requeue).

The compiler runs a sequential OVERLAY simulation in FFD order: every
group's zone-pinned landings bump the compile-local domain counts of every
zone-keyed group whose selector matches it (ownership not required —
topologygroup.go:167 counts by selector), so later groups see earlier
groups' placements exactly as the host loop would. Groups whose affinity
targets haven't landed yet retry in later rounds until a fixed point; the
remainder routes to the host engine, which stays the semantic oracle.

Anything else — zone anti-affinity (the Schrödinger case records every
candidate domain), preferred terms, minDomains, same-selector spreads with
different parameters, hostname affinity onto pre-existing cluster matches —
routes to the host engine. Every host routing carries a REASON
(WavesPlan.host_reasons), exported as
karpenter_provisioning_host_routed_pods_total and surfaced per grid row by
the perf harness.

Vectorized-overlay contract
---------------------------

The default compiler (:class:`_VecCompiler`) and the sequential oracle
(:class:`_Compiler`) share ONE copy of the overlay scan: the scan consults
constraints only through predicate hooks (``_tg_selects`` /
``_zone_inverse_any`` / ``_cls_match`` / ``_cls_smatch`` / ``_cls_amatch``
/ ``_rec_tgs`` / ``_water``), and the vectorized compiler overrides those
hooks with batched numpy tables — groups dedup to distinct (namespace,
labels) signatures, match_labels-only selectors evaluate as one bitwise
subset test over an interned label-pair matrix, expression selectors fall
back to the exact Python matcher once per signature, ownership inverts the
registry's owner sets in one pass, and zone water-filling runs in closed
form over the [domains] axis (:func:`_water_fill_np`). Plans are therefore
bit-identical BY CONSTRUCTION, and tests/test_waves_parity.py enforces it
over 120+ seeded random mixes. KARPENTER_WAVES_SEQUENTIAL=1 (or
``compile_topology(..., vectorized=False)``) selects the oracle for A/B
debugging.

Downstream cache invalidation
-----------------------------

The tensorizer caches packed group rows keyed on (pod signature, this
plan's per-group extra requirements) inside the type-side cache entry
(ops/tensorize.py). Waves therefore participates in that contract through
the extra-req fingerprint alone: a group that lands in a different zone
subgroup (different pin/IN-set) keys a different row, while the OVERLAY
state itself (domain counts) never leaks into the cache — it only shapes
which extra reqs each subgroup carries.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.api import labels as wk
from karpenter_tpu.models.topology import (
    TYPE_AFFINITY,
    TYPE_ANTI_AFFINITY,
    TYPE_SPREAD,
    Topology,
)
from karpenter_tpu.ops.tensorize import UNCAPPED
from karpenter_tpu.scheduling import IN, Requirement, pod_requirements
from karpenter_tpu.utils import resources as resutil

WORD = 32


@dataclass
class DeviceGroup:
    """One kernel scan row: identical pods + compiled topology structure."""

    pods: list
    extra_reqs: list = field(default_factory=list)  # e.g. zone pin
    bin_cap: int = UNCAPPED  # max pods of this group per bin
    single_bin: bool = False  # retained for direct kernel callers
    decl_classes: frozenset = frozenset()  # hostname-anti classes declared
    match_classes: frozenset = frozenset()  # hostname-anti classes matched
    spread_caps: dict = field(default_factory=dict)  # owned spread class -> maxSkew
    spread_matches: frozenset = frozenset()  # spread classes counting this group
    aff_need: frozenset = frozenset()  # hostname-affinity classes owned
    aff_match: frozenset = frozenset()  # hostname-affinity classes matching it


@dataclass
class WavesPlan:
    device_groups: list
    host_pods: list
    n_classes: int = 0
    n_spread_classes: int = 0
    n_aff_classes: int = 0
    # per-class TopologyGroup refs so the existing-node tensorizer can seed
    # per-node counts from the groups' domain maps (hostname-keyed)
    anti_tgs_by_class: list = field(default_factory=list)  # (direct, inverse|None)
    spread_tgs_by_class: list = field(default_factory=list)
    aff_tgs_by_class: list = field(default_factory=list)
    # why pods routed to the host engine: reason -> pod count, feeding the
    # karpenter_provisioning_host_routed_pods_total metric family
    host_reasons: dict = field(default_factory=dict)

    @property
    def device_pod_count(self):
        return sum(len(g.pods) for g in self.device_groups)

    def class_masks(self):
        """(g_decl [G,CW] u32, g_match [G,CW] u32) for the kernel."""
        G = len(self.device_groups)
        CW = max(1, (self.n_classes + WORD - 1) // WORD)
        decl = np.zeros((G, CW), dtype=np.uint32)
        match = np.zeros((G, CW), dtype=np.uint32)
        for g, dg in enumerate(self.device_groups):
            for c in dg.decl_classes:
                decl[g, c // WORD] |= np.uint32(1 << (c % WORD))
            for c in dg.match_classes:
                match[g, c // WORD] |= np.uint32(1 << (c % WORD))
        return decl, match

    def spread_tensors(self):
        """(g_sown [G,C] i32 cap where owned else UNCAPPED,
        g_smatch [G,C] bool) for the kernel's per-bin spread-class counts."""
        G = len(self.device_groups)
        C = max(1, self.n_spread_classes)
        sown = np.full((G, C), UNCAPPED, dtype=np.int32)
        smatch = np.zeros((G, C), dtype=bool)
        for g, dg in enumerate(self.device_groups):
            for c, cap in dg.spread_caps.items():
                sown[g, c] = cap
            for c in dg.spread_matches:
                smatch[g, c] = True
        return sown, smatch

    def aff_tensors(self):
        """(g_aneed [G,A] bool, g_amatch [G,A] bool) for the kernel's
        per-bin affinity-class match counts; bootstrap eligibility is
        derived in-kernel from amatch ∧ global-count==0."""
        G = len(self.device_groups)
        A = max(1, self.n_aff_classes)
        aneed = np.zeros((G, A), dtype=bool)
        amatch = np.zeros((G, A), dtype=bool)
        for g, dg in enumerate(self.device_groups):
            for c in dg.aff_need:
                aneed[g, c] = True
            for c in dg.aff_match:
                amatch[g, c] = True
        return aneed, amatch


def _group_key(g0):
    # FFD order (queue.go:37) with a most-constrained-first tie-break:
    # groups that will carry per-bin caps (required anti-affinity, hostname
    # spread) scan before unconstrained equals, so the bins their caps force
    # open are still fillable by the flexible groups behind them. Measured
    # on the anti+spread 5k config: 84 → 82 bins vs the host oracle's 81
    # (the host interleaves pod-at-a-time, which achieves the same effect).
    a = g0.affinity
    capped = bool(
        (a and a.pod_anti_affinity and a.pod_anti_affinity.required)
        or any(
            c.topology_key == wk.HOSTNAME_LABEL
            for c in g0.topology_spread_constraints
        )
    )
    req = g0.effective_requests()
    return (
        -req.get(resutil.CPU, 0.0),
        -req.get(resutil.MEMORY, 0.0),
        0 if capped else 1,
    )


def _water_fill(counts: dict, n: int) -> dict:
    """Distribute n additions over domains, always raising the lowest —
    the closed form of the host's least-loaded-domain placement loop.
    Returns domain -> additions. Deterministic (sorted domain tie-break)."""
    out = {d: 0 for d in counts}
    cur = dict(counts)
    remaining = n
    while remaining > 0:
        lo = min(cur.values())
        lows = sorted(d for d in cur if cur[d] == lo)
        higher = [v for v in cur.values() if v > lo]
        gap = (min(higher) - lo) if higher else None
        if gap is not None and gap * len(lows) <= remaining:
            for d in lows:
                cur[d] += gap
                out[d] += gap
            remaining -= gap * len(lows)
        else:
            per, extra = divmod(remaining, len(lows))
            for j, d in enumerate(lows):
                add = per + (1 if j < extra else 0)
                cur[d] += add
                out[d] += add
            remaining = 0
    return out


def _spread_conflicts(topology) -> set:
    """Hash keys of spread groups sharing (key, selector, namespaces) with
    another spread group but different parameters — their counts interact
    in ways the static plan cannot express."""
    seen: dict = {}
    conflicted: set = set()
    for hk, tg in topology.topologies.items():
        if tg.type != TYPE_SPREAD:
            continue
        sel = hk[3]  # selector component of hash_key
        ident = (tg.key, sel, tg.namespaces)
        other = seen.get(ident)
        if other is not None and other != hk:
            conflicted.add(hk)
            conflicted.add(other)
        seen[ident] = hk
    return conflicted


_HOST = "host"
_DEFER = "defer"


class _Compiler:
    """Sequential overlay compile of one batch (see module docstring)."""

    def __init__(self, groups, topology):
        self.groups = groups
        self.topology = topology
        self.reps = [g[0] for g in groups]
        self.own_by_gid = self._compute_owns()
        self.spread_conflicted = _spread_conflicts(topology)
        # inverse anti groups whose declarers are NOT in this batch and whose
        # key is not hostname constrain allowed domains invisibly → host
        self.zone_inverse = [
            tg for tg in topology.inverse_topologies.values()
            if tg.key != wk.HOSTNAME_LABEL
        ]
        # one class per distinct required hostname term owned in the batch
        self.anti_classes: dict = {}
        self.aff_classes: dict = {}
        self.spread_classes: dict = {}
        for own in self.own_by_gid:
            for tg in own:
                if tg.key != wk.HOSTNAME_LABEL:
                    continue
                if tg.type == TYPE_ANTI_AFFINITY:
                    self.anti_classes.setdefault(tg.hash_key(), len(self.anti_classes))
                elif tg.type == TYPE_SPREAD:
                    self.spread_classes.setdefault(
                        tg.hash_key(), len(self.spread_classes))
                elif tg.type == TYPE_AFFINITY:
                    self.aff_classes.setdefault(tg.hash_key(), len(self.aff_classes))
        T = topology.topologies
        self.anti_tgs = {hk: T[hk] for hk in self.anti_classes}
        self.spread_tgs = {hk: T[hk] for hk in self.spread_classes}
        self.aff_tgs = {hk: T[hk] for hk in self.aff_classes}
        # zone-keyed spread/affinity groups in registry order: the bump
        # targets (Topology.Record's singleton-domain commit mirror)
        self.zone_rec_tgs = [
            tg for tg in topology.topologies.values()
            if tg.key == wk.TOPOLOGY_ZONE_LABEL
            and tg.type in (TYPE_SPREAD, TYPE_AFFINITY)
        ]
        # compile-local domain counts for every ZONE-keyed spread/affinity
        # group; later groups see earlier groups' pinned landings exactly as
        # the host loop would
        self.overlay: dict = {}
        # in-batch matched-pod counts per hostname-affinity class (scan-order
        # viability; the kernel re-checks per bin at run time)
        self.aff_cnt = [0] * len(self.aff_classes)
        self.device_groups: list = []
        self.host_pods: list = []
        self.host_reasons: dict = {}
        self._pz_memo: dict = {}

    def _counts(self, tg) -> dict:
        c = self.overlay.get(id(tg))
        if c is None:
            c = self.overlay[id(tg)] = dict(tg.domains)
        return c

    def _route_host(self, pods, reason: str):
        self.host_pods.extend(pods)
        self.host_reasons[reason] = self.host_reasons.get(reason, 0) + len(pods)
        return _HOST

    def _compute_owns(self) -> list:
        """own_by_gid: every registry group owning gid's rep, in registry
        order (the scan handles constraints in registration order)."""
        return [
            [tg for tg in self.topology.topologies.values()
             if rep.uid in tg.owners]
            for rep in self.reps
        ]

    # ---- per-group predicates -------------------------------------------
    # The scan consults constraint predicates ONLY through these hooks, so
    # the sequential oracle and the vectorized compiler share one copy of
    # the overlay logic and can only differ in how predicates are evaluated.

    def _tg_selects(self, tg, gid) -> bool:
        return tg.selects(self.reps[gid])

    def _zone_inverse_any(self, gid) -> bool:
        rep = self.reps[gid]
        return any(tg.selects(rep) for tg in self.zone_inverse)

    def _cls_match(self, gid) -> frozenset:
        rep = self.reps[gid]
        return frozenset(
            c for hk, c in self.anti_classes.items()
            if self.anti_tgs[hk].selects(rep)
        )

    def _cls_smatch(self, gid) -> frozenset:
        rep = self.reps[gid]
        return frozenset(
            c for hk, c in self.spread_classes.items()
            if self.spread_tgs[hk].selects(rep)
        )

    def _cls_amatch(self, gid) -> frozenset:
        rep = self.reps[gid]
        return frozenset(
            c for hk, c in self.aff_classes.items()
            if self.aff_tgs[hk].selects(rep)
        )

    def _rec_tgs(self, gid) -> list:
        rep = self.reps[gid]
        return [tg for tg in self.zone_rec_tgs if tg.selects(rep)]

    def _pod_zone(self, gid):
        """pod's allowed-zone requirement, memoized per group (pure
        function of the rep's spec — semantically free in both modes)."""
        pz = self._pz_memo.get(gid)
        if pz is None:
            pz = self._pz_memo[gid] = pod_requirements(
                self.reps[gid]).get_req(wk.TOPOLOGY_ZONE_LABEL)
        return pz

    def _water(self, counts: dict, n: int) -> dict:
        return _water_fill(counts, n)

    def run(self) -> WavesPlan:
        pending = list(range(len(self.groups)))
        progress = True
        while progress and pending:
            progress = False
            still = []
            for gid in pending:
                outcome = self._compile_one(gid)
                if outcome is _DEFER:
                    still.append(gid)
                    continue
                progress = True
            pending = still
        for gid in pending:
            # affinity targets never materialized: the host queue fails these
            # the same way after its own retry cycle (queue.go:76 staleness)
            self._route_host(self.groups[gid], "affinity-unresolved")
        anti_by_class = [None] * len(self.anti_classes)
        for hk, c in self.anti_classes.items():
            anti_by_class[c] = (
                self.anti_tgs[hk], self.topology.inverse_topologies.get(hk))
        spread_by_class = [None] * len(self.spread_classes)
        for hk, c in self.spread_classes.items():
            spread_by_class[c] = self.spread_tgs[hk]
        aff_by_class = [None] * len(self.aff_classes)
        for hk, c in self.aff_classes.items():
            aff_by_class[c] = self.aff_tgs[hk]
        return WavesPlan(
            self.device_groups,
            self.host_pods,
            n_classes=len(self.anti_classes),
            n_spread_classes=len(self.spread_classes),
            n_aff_classes=len(self.aff_classes),
            anti_tgs_by_class=anti_by_class,
            spread_tgs_by_class=spread_by_class,
            aff_tgs_by_class=aff_by_class,
            host_reasons=dict(self.host_reasons),
        )

    def _compile_one(self, gid):
        pods = self.groups[gid]
        rep = self.reps[gid]
        own = self.own_by_gid[gid]

        if self._zone_inverse_any(gid):
            return self._route_host(pods, "zone-inverse-anti")

        extra_reqs: list = []
        bin_cap = UNCAPPED
        zone_split = None  # domain -> count (pinned landings)
        # set by ANY zone spread/affinity, pinned or not: composing two
        # zone constraints needs each other's answers → host engine
        zone_constrained = False
        decl: set = set()
        spread_caps: dict = {}
        aff_need: set = set()

        for tg in own:
            if tg.type == TYPE_SPREAD and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                split = self._zone_spread(tg, gid, len(pods), zone_constrained)
                if split is None:
                    return self._route_host(pods, "zone-spread")
                zone_split, zone_constrained = split, True
            elif tg.type == TYPE_SPREAD and tg.key == wk.HOSTNAME_LABEL:
                cls = self.spread_classes[tg.hash_key()]
                cap = max(int(tg.max_skew), 1)
                spread_caps[cls] = min(spread_caps.get(cls, cap), cap)
            elif tg.type == TYPE_ANTI_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                decl.add(self.anti_classes[tg.hash_key()])
            elif tg.type == TYPE_AFFINITY and tg.key == wk.TOPOLOGY_ZONE_LABEL:
                res = self._zone_affinity(tg, gid, len(pods), zone_constrained)
                if res is _HOST:
                    return self._route_host(pods, "zone-affinity")
                if res is _DEFER:
                    return _DEFER
                req, pinned = res
                extra_reqs.append(req)
                zone_constrained = True
                if pinned is not None:
                    zone_split = {pinned: len(pods)}
            elif tg.type == TYPE_AFFINITY and tg.key == wk.HOSTNAME_LABEL:
                if any(tg.domains.values()):
                    # pre-existing cluster matches: the host engine's
                    # exact-domain bootstrap onto registered hostnames is
                    # not expressible as class counts
                    return self._route_host(pods, "hostname-affinity-existing")
                cls = self.aff_classes[tg.hash_key()]
                aff_need.add(cls)
                if not self._tg_selects(tg, gid) and self.aff_cnt[cls] == 0:
                    # target labels haven't landed yet: retry after the
                    # rest of the batch (the host requeue-to-back)
                    return _DEFER
            else:
                return self._route_host(pods, "unsupported-constraint")

        # classes whose selector matches this group (the inverse direction)
        match = self._cls_match(gid)
        if decl & match:
            # self-matching anti-affinity: at most one pod of the group per
            # bin, the classic one-replica-per-node shape
            bin_cap = 1
        # spread classes counting this group's pods (selector match,
        # topologygroup.go:167 — ownership not required; an owner whose own
        # labels don't match its selector contributes nothing, exactly like
        # the host count)
        smatch = self._cls_smatch(gid)
        amatch = self._cls_amatch(gid)

        self._emit(
            pods, extra_reqs, bin_cap, zone_split,
            frozenset(decl), match, dict(spread_caps),
            smatch, frozenset(aff_need), amatch,
        )
        self._bump_landings(gid, pods, zone_split)
        return "emit"

    # ---- per-constraint compile steps ----------------------------------
    def _zone_spread(self, tg, gid, n, zone_constrained):
        """domain -> count, or None for host."""
        if (
            tg.min_domains is not None
            or zone_constrained
            or tg.hash_key() in self.spread_conflicted
        ):
            return None
        counts = self._counts(tg)
        pod_zone = self._pod_zone(gid)
        allowed = {d: c for d, c in counts.items() if pod_zone.has(d)}
        if not allowed:
            return None
        if self._tg_selects(tg, gid):
            split = self._water(allowed, n)
            return {d: c for d, c in split.items() if c > 0}
        # non-self-selecting owner: counts never move, so every pod takes
        # the same min-count domain (sorted tie-break, topology.py:196);
        # maxSkew holds trivially at the minimum
        lo = min(allowed.values())
        d_star = sorted(d for d in allowed if allowed[d] == lo)[0]
        return {d_star: n}

    def _zone_affinity(self, tg, gid, n, zone_constrained):
        """(Requirement, pinned_zone|None) | _DEFER | _HOST."""
        if zone_constrained:
            return _HOST  # composed zone constraints: host engine
        counts = self._counts(tg)
        pod_zone = self._pod_zone(gid)
        nonzero = sorted(d for d, c in counts.items() if c > 0 and pod_zone.has(d))
        if nonzero:
            if len(nonzero) == 1:
                return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, nonzero), nonzero[0])
            # several match domains: the pod may land in any (host records
            # nothing for non-singleton domains, topology.py:309)
            return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, nonzero), None)
        if not self._tg_selects(tg, gid):
            return _DEFER
        # self-affinity bootstrap: deterministic sorted-first allowed domain
        # (the host engine's tie-break, topology.py:211-221)
        first = next((d for d in sorted(counts) if pod_zone.has(d)), None)
        if first is None:
            return _HOST  # no domain universe: host produces the error
        return (Requirement(wk.TOPOLOGY_ZONE_LABEL, IN, [first]), first)

    # ---- landings ------------------------------------------------------
    def _emit(self, pods, extra_reqs, bin_cap, zone_split, decl, match,
              spread_caps, smatch, aff_need, amatch):
        # batched subgroup construction: every field except the pod slice
        # and the zone pin is IDENTICAL across a wave's subgroups, so the
        # per-wave structure is built ONCE and shared — including
        # `spread_caps`, whose per-subgroup dict(…) copy used to dominate
        # this loop at fleet scale (ROADMAP named _emit as a residual host
        # stage that would dominate at 500k pods; a 100-zone wave now pays
        # one copy, not 100). Sharing is safe: DeviceGroup fields are
        # read-only after compile (tensorize/spread_tensors/class_masks
        # only read), and each call site already hands _emit a fresh dict.
        emit = self.device_groups.append
        if zone_split:
            # zone-pinned subgroups; pods partitioned in order
            cursor = 0
            zone = wk.TOPOLOGY_ZONE_LABEL
            for d in sorted(zone_split):
                cnt = zone_split[d]
                sub = pods[cursor: cursor + cnt]
                cursor += cnt
                emit(DeviceGroup(
                    sub, extra_reqs + [Requirement(zone, IN, [d])],
                    bin_cap, False, decl, match, spread_caps, smatch,
                    aff_need, amatch,
                ))
        else:
            emit(DeviceGroup(
                list(pods), extra_reqs, bin_cap, False, decl, match,
                spread_caps, smatch, aff_need, amatch,
            ))

    def _bump_landings(self, gid, pods, zone_split):
        """Commit this group's pinned landings into the overlay so later
        groups (and later compile rounds) see them — the compile-time
        mirror of Topology.Record's singleton-domain commit."""
        pinned = zone_split
        if pinned is None:
            # a plain node-selector zone pin also counts (the claim's zone
            # set is a singleton, so the host records it)
            pz = self._pod_zone(gid)
            if not pz.complement and len(pz.values) == 1:
                pinned = {next(iter(pz.values)): len(pods)}
        if pinned:
            for tg in self._rec_tgs(gid):
                counts = self._counts(tg)
                for d, c in pinned.items():
                    counts[d] = counts.get(d, 0) + c
        for cls in self._cls_amatch(gid):
            self.aff_cnt[cls] += len(pods)


def _col_sets(m: np.ndarray) -> list:
    """Per-column frozensets of the true rows of a [C, G] bool table —
    one nonzero pass instead of G flatnonzero calls."""
    C, G = m.shape
    out = [frozenset()] * G
    if m.size:
        gs, cs = np.nonzero(m.T)
        starts = np.searchsorted(gs, np.arange(G + 1))
        for g in range(G):
            lo, hi = int(starts[g]), int(starts[g + 1])
            if hi > lo:
                out[g] = frozenset(cs[lo:hi].tolist())
    return out


def _water_fill_np(counts: dict, n: int) -> dict:
    """Closed-form water fill over the [domains] axis — bit-identical to
    :func:`_water_fill` (the sequential oracle; the parity suite pins it):
    the final state raises every participating domain to a common level L*
    (the largest level affordable within n), then hands the remainder out
    one pod each to the first sorted-name domains at that level."""
    names = sorted(counts)
    c = np.array([counts[d] for d in names], dtype=np.int64)
    order = np.argsort(c, kind="stable")  # ascending counts, name tie-break
    cs = c[order]
    pre = np.concatenate([[0], np.cumsum(cs)])
    D = len(cs)
    # cost(k) = lift the k lowest to the (k+1)-th count; the last bracket
    # is unbounded. Find the bracket n lands in, then the level within it.
    ks = np.arange(1, D + 1)
    # the last bracket is unbounded: a level past every count + budget can
    # never be reached, so it serves as the +inf sentinel without overflow
    nxt = np.concatenate([cs[1:], [cs[-1] + n + 1]])
    cost_to_next = ks * nxt - pre[1:]  # cost to reach the NEXT count level
    k = int(np.searchsorted(cost_to_next, n, side="right"))
    k = min(k + 1, D)  # number of participating (lowest) domains
    level = (pre[k] + n) // k
    spent = level * k - pre[k]
    rem = int(n - spent)
    out = {d: 0 for d in names}
    lows = sorted(names[i] for i in order[:k])
    for i, d in enumerate(lows):
        add = int(level) - counts[d] + (1 if i < rem else 0)
        if add > 0:
            out[d] = add
    return out


class _VecCompiler(_Compiler):
    """The default compiler: the SAME sequential overlay scan as
    :class:`_Compiler` (one copy of the logic — the scan consults
    constraints only through the predicate hooks), with every predicate
    precomputed as batched numpy tables instead of per-group Python loops:

    - selector matching: groups dedup to distinct (namespace, labels)
      signatures; match_labels-only selectors evaluate as one bitwise
      subset test over an interned label-pair matrix [signatures × pairs],
      expression selectors fall back to the exact Python matcher once per
      signature; rows broadcast back to [classes × groups] by fancy index.
    - ownership: one inversion pass over the topology registry's owner
      sets replaces the per-group registry scan.
    - zone water-filling: the closed-form [domains]-axis fill
      (:func:`_water_fill_np`).

    Bit-identical plans to the sequential oracle by construction; the
    seeded parity suite (tests/test_waves_parity.py) enforces it."""

    def __init__(self, groups, topology):
        super().__init__(groups, topology)
        reps = self.reps
        G = len(reps)
        sig_of: dict = {}
        lab_ids = np.zeros(G, dtype=np.intp)
        distinct: list = []
        for g, rep in enumerate(reps):
            key = (rep.namespace, tuple(sorted(rep.metadata.labels.items())))
            i = sig_of.get(key)
            if i is None:
                i = sig_of[key] = len(distinct)
                distinct.append(rep)
            lab_ids[g] = i
        D = len(distinct)

        # the tgs whose per-group selection the scan consults, one row each
        anti_list = [None] * len(self.anti_classes)
        for hk, c in self.anti_classes.items():
            anti_list[c] = self.anti_tgs[hk]
        spread_list = [None] * len(self.spread_classes)
        for hk, c in self.spread_classes.items():
            spread_list[c] = self.spread_tgs[hk]
        aff_list = [None] * len(self.aff_classes)
        for hk, c in self.aff_classes.items():
            aff_list[c] = self.aff_tgs[hk]
        all_tgs: list = []
        row_of: dict = {}
        for tg in (*anti_list, *spread_list, *aff_list, *self.zone_inverse,
                   *self.zone_rec_tgs):
            if id(tg) not in row_of:
                row_of[id(tg)] = len(all_tgs)
                all_tgs.append(tg)

        # interned (key, value) pairs of every match_labels-only selector
        pair_idx: dict = {}
        for tg in all_tgs:
            sel = tg.selector
            if sel is not None and not sel.match_expressions:
                for kv in sel.match_labels.items():
                    pair_idx.setdefault(kv, len(pair_idx))
        enc = np.zeros((D, max(len(pair_idx), 1)), dtype=bool)
        for d, rep in enumerate(distinct):
            for kv in rep.metadata.labels.items():
                p = pair_idx.get(kv)
                if p is not None:
                    enc[d, p] = True

        # distinct namespaces intern too: the namespace gate evaluates per
        # (tg, namespace), not per (tg, signature)
        ns_names = []
        ns_pos: dict = {}
        ns_ids = np.zeros(D, dtype=np.intp)
        for d, rep in enumerate(distinct):
            i = ns_pos.get(rep.namespace)
            if i is None:
                i = ns_pos[rep.namespace] = len(ns_names)
                ns_names.append(rep.namespace)
            ns_ids[d] = i

        S = np.zeros((max(len(all_tgs), 1), D), dtype=bool)
        for i, tg in enumerate(all_tgs):
            sel = tg.selector
            if sel is None:
                continue  # selects() is False without a selector
            ns_row = np.array(
                [ns in tg.namespaces for ns in ns_names], dtype=bool
            )[ns_ids]
            if sel.match_expressions:
                # exact Python matcher, once per distinct signature
                row = np.array(
                    [sel.matches(rep.metadata.labels) for rep in distinct],
                    dtype=bool,
                )
            elif sel.match_labels:
                need = np.zeros(enc.shape[1], dtype=bool)
                for kv in sel.match_labels.items():
                    need[pair_idx[kv]] = True
                row = ~((need[None, :] & ~enc).any(axis=1))
            else:
                row = np.ones(D, dtype=bool)  # empty selector matches all
            S[i] = row & ns_row

        SG = S[:, lab_ids]
        self._row_of = row_of
        self._SG = SG

        def cls_rows(tg_list):
            if not tg_list:
                return np.zeros((0, G), dtype=bool)
            return SG[[row_of[id(tg)] for tg in tg_list]]

        anti_m = cls_rows(anti_list)
        spread_m = cls_rows(spread_list)
        aff_m = cls_rows(aff_list)
        zi = cls_rows(self.zone_inverse)
        self._zi_any = zi.any(axis=0) if zi.size else np.zeros(G, dtype=bool)
        # per-gid class sets / bump-target lists, one nonzero pass per table
        self._match_sets = _col_sets(anti_m)
        self._smatch_sets = _col_sets(spread_m)
        self._amatch_sets = _col_sets(aff_m)
        rec_m = cls_rows(self.zone_rec_tgs)
        self._rec_lists = [
            [self.zone_rec_tgs[i] for i in sorted(s)] for s in _col_sets(rec_m)
        ]

    def _compute_owns(self) -> list:
        """Registry-owner inversion: one pass over each group's owner set
        replaces the per-gid registry scan — same per-gid lists, in the
        same registry order (each tg appends once per owning gid)."""
        uid2gid = {rep.uid: g for g, rep in enumerate(self.reps)}
        own: list = [[] for _ in self.reps]
        for tg in self.topology.topologies.values():
            gids = {uid2gid[u] for u in tg.owners if u in uid2gid}
            for g in gids:
                own[g].append(tg)
        return own

    # -- predicate hooks over the precomputed tables ----------------------
    def _tg_selects(self, tg, gid) -> bool:
        row = self._row_of.get(id(tg))
        if row is None:  # not a scan-relevant tg; exact fallback
            return tg.selects(self.reps[gid])
        return bool(self._SG[row, gid])

    def _zone_inverse_any(self, gid) -> bool:
        return bool(self._zi_any[gid])

    def _cls_match(self, gid) -> frozenset:
        return self._match_sets[gid]

    def _cls_smatch(self, gid) -> frozenset:
        return self._smatch_sets[gid]

    def _cls_amatch(self, gid) -> frozenset:
        return self._amatch_sets[gid]

    def _rec_tgs(self, gid) -> list:
        return self._rec_lists[gid]

    def _water(self, counts: dict, n: int) -> dict:
        return _water_fill_np(counts, n)


def compile_topology(groups: list, topology, vectorized: bool | None = None) -> WavesPlan:
    """groups: list[list[Pod]] (identical pods per list, any order).
    Returns the device plan; pods whose constraints the device cannot
    express are returned in host_pods (with per-reason counts in
    host_reasons). ``vectorized=False`` (or KARPENTER_WAVES_SEQUENTIAL=1)
    compiles through the sequential oracle — same plan, per-group Python
    predicate evaluation; the parity suite diffs the two."""
    groups = sorted(groups, key=lambda g: _group_key(g[0]))  # FFD order

    if topology is None or not getattr(topology, "has_groups", False):
        return WavesPlan([DeviceGroup(list(g)) for g in groups], [])

    if vectorized is None:
        from karpenter_tpu.utils.envknobs import env_str

        # inverse opt-in: setting the knob selects the SEQUENTIAL oracle
        vectorized = (env_str("KARPENTER_WAVES_SEQUENTIAL", "") or "") \
            .strip().lower() not in ("1", "true", "yes", "on")
    cls = _VecCompiler if vectorized else _Compiler
    # the sequential-oracle path is one of the slow edges the flight
    # recorder exists to attribute: the span's `vectorized` attr says
    # which compiler carried this round (karpenter_tpu/obs)
    from karpenter_tpu import obs

    with obs.span("waves.compile", groups=len(groups),
                  vectorized=bool(vectorized)):
        return cls(groups, topology).run()
