"""LP-relaxed global assignment rung — the device-resident convex solver
(deploy/README.md "LP relaxation rung").

Both combinatorial hot loops — provisioning bin-packing and the joint
consolidation retirement search — are relaxations of ONE assignment
program: pods of group ``g`` land on capacity columns (surviving nodes,
fresh bins) subject to per-resource capacity and compatibility, and the
integral machinery (the FFD kernel ladder, the prefix criterion) answers
a question the LP answers fractionally in a handful of matrix iterations.
This module solves that LP on device with a diagonally-preconditioned
primal-dual (PDHG / Chambolle-Pock) iteration compiled as ONE executable
per shape family: a ``jax.lax.while_loop`` whose body runs a fixed block
of ``lax.fori_loop`` steps and a residual check — zero host syncs inside
the iteration (the GL504 stance holds structurally: there is no Python
loop around the dispatch at all), termination decided on device from the
primal feasibility residual.

Two entry points ride the one iteration scheme:

* :func:`joint_relax_plan` — the global-consolidation fast path
  (``ops/consolidate.py joint_retirement_plan``): retirement fractions
  ``y[N]`` over the disruption-cost-ordered candidates (a monotone
  prefix chain, so the LP optimum IS a fractional prefix), assignment
  ``x[G, E+1]`` of displaced+pending pods onto survivor columns plus ONE
  claim-envelope column, objective = maximize retirements with an
  earlier-candidate tie-break. The converged objective upper-bounds any
  integral prefix, so ``k_ub = round(sum(y))`` seeds a bounded
  device-side rounding window (one vmapped dispatch scores W candidate
  prefixes) that replaces the host repair loop; the FFD machinery is
  demoted to ROUNDING ORACLE — exactly one exact-arithmetic
  ``_greedy_displace`` materializes the chosen prefix's displacement
  plan (bit-identical to the ladder's rounding, the parity pin), and
  the shared price criterion gates any claim-bearing prefix. Every
  non-ship outcome hands the round to the FFD ladder with its cause
  pinned in ``RELAX_STATS["last_fallback"]`` (the fallback matrix:
  ``inexpressible`` / ``iteration-cap`` / ``non-convergence`` /
  ``price-gate`` / ``lp-no-retirement``).

* :func:`lp_bin_floor` — the provisioning rung (``models/solver.py``):
  the same program without retirement variables (min total bins s.t.
  demand/capacity/compat), whose DUALS are projected to a feasible
  point of the dual cone after the iteration budget — weak duality then
  certifies ``ceil(dual objective)`` as a valid bin floor REGARDLESS of
  convergence, tightening the solver's per-resource estimate (bin-axis
  sizing and the solve-quality account's floor).

Knobs (all through ``utils/envknobs.py``; folded into the kernel cache
fingerprints below — GL501 enforces):

``KARPENTER_RELAX``           enable/kill-switch. Unset = auto (on only
                              when the jax backend is a real accelerator
                              — on CPU the LP iteration is an emulation
                              that loses to the native FFD engine);
                              ``1`` forces on, ``0`` kills.
``KARPENTER_RELAX_MAX_ITERS`` iteration cap (default 384).
``KARPENTER_RELAX_TOL``       relative feasibility tolerance (5e-3).
``KARPENTER_RELAX_RHO``       primal/dual step balance (default 1.0).

Replay: every joint relax decision records the ``relax.dispatch``
capsule seam (obs/capsule.py) carrying the LP tensors AND the standard
counterfactual-row sidecars, so ``obs replay`` re-runs the relax rung
bit-identically and ``obs replay --ab`` races relax vs the FFD ladder
vs host-FFD on the same capture.
"""

from __future__ import annotations

import time

import numpy as np

from karpenter_tpu import obs
from karpenter_tpu.obs import devplane
from karpenter_tpu.utils.envknobs import env_float, env_int, env_str

__all__ = [
    "relax_enabled",
    "joint_relax_plan",
    "lp_bin_floor",
    "RELAX_STATS",
    "replay_joint",
    "replay_host_round",
]

# rounding window width: how many candidate prefixes below the LP bound
# the ONE vmapped rounding dispatch scores (the bounded device-side pass
# that replaces the KARPENTER_GLOBAL_REPAIR_MAX host loop)
ROUND_WINDOW = 8
# exact-materialization attempts: at most this many window prefixes get
# the host oracle pass before the round falls back to the ladder
ROUND_ATTEMPTS = 4
# PDHG steps between on-device residual checks (inner fori_loop length)
CHECK_EVERY = 16
# claim-column objective penalty: prefer delete-only fractional optima
# (mirrors the ladder's preference — a claim only ships price-gated)
CLAIM_PENALTY = 1e-3
# earlier-candidate tie-break weight spread (keeps the optimum a prefix
# of the disruption-cost order among equal-cardinality solutions)
PREFIX_TIEBREAK = 1e-3

RELAX_STATS = {
    "attempts": 0,
    "ships": 0,
    "fallbacks": 0,
    "rounded_drops": 0,
    "kernel_ms": 0.0,
    "iters": 0,
    "last_fallback": "",
    "last_viol": 0.0,
    "last_k_ub": 0,
    "last_iters": 0,
    "floor_calls": 0,
    "floor_raises": 0,
}


# ---------------------------------------------------------------------------
# knobs (utils/envknobs.py — the one os.environ surface; every reader
# below is folded into the kernel cache keys, the GL501 contract)
# ---------------------------------------------------------------------------


def relax_enabled() -> bool:
    """Tri-state enable: KARPENTER_RELAX=1 forces the rung on, =0 kills
    it, unset/empty defers to the backend probe (the LP iteration only
    beats the native FFD engine when the matmuls are an accelerator's)."""
    v = (env_str("KARPENTER_RELAX") or "").strip().lower()
    if v:
        return v not in ("0", "false", "off", "no")
    from karpenter_tpu.models.solver import _accelerated_backend

    return _accelerated_backend()


def _relax_max_iters() -> int:
    return env_int("KARPENTER_RELAX_MAX_ITERS", 384, minimum=1)


def _relax_tol() -> float:
    return env_float("KARPENTER_RELAX_TOL", 5e-3, minimum=0.0)


def _relax_rho() -> float:
    return max(env_float("KARPENTER_RELAX_RHO", 1.0), 1e-6)


def _relax_round_windows() -> int:
    """KARPENTER_RELAX_ROUND_WINDOWS: how many W-prefix windows the
    rounding descent may scan below the LP bound before handing the
    round to the ladder (the LP relaxation gap can exceed one window)."""
    return env_int("KARPENTER_RELAX_ROUND_WINDOWS", 4, minimum=1)


def _fallback(cause: str) -> None:
    RELAX_STATS["fallbacks"] += 1
    RELAX_STATS["last_fallback"] = cause


# ---------------------------------------------------------------------------
# the PDHG joint kernel — one executable per (Gp, Ec, Np, R) shape family
# ---------------------------------------------------------------------------

# compiled kernel caches; the knob readers IN the key are the GL501
# fingerprint contract — a knob flip can never serve a stale executable
_JOINT_KERNELS: dict = {}
_ROUND_KERNELS: dict = {}
_FLOOR_KERNELS: dict = {}


def _pow2(n: int, lo: int = 8) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


def _joint_kernel(Gp, Ec, Np, R, max_iters, tol, rho):
    """Jitted PDHG over the joint consolidation LP.

    Variables: ``x[Gp,Ec]`` (pods of group g on column e; the claim
    envelope rides as an ordinary column), ``y[Np]`` retirement
    fractions. Constraints (dual in parens): demand coverage per group
    (``q``), per-column per-resource capacity with the retired column's
    capacity scaling away as ``y`` rises (``p``), and the monotone
    prefix chain ``y[c+1] <= y[c]`` (``m``). Diagonal preconditioning
    (Pock-Chambolle, alpha=1) with ``rho`` balancing the primal/dual
    steps; over-relaxed dual extrapolation; residual-based termination
    checked every CHECK_EVERY steps on device."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(d, capR, compat, contrib, base_req, w, colcand, candidx,
            nmask, gmask, c_x):
        # --- preconditioners (tensors, traced once per family) ---
        xub = (base_req + contrib.sum(0)) * gmask  # [Gp] max demand
        col_x = (1.0 + d.sum(1))[:, None] * compat  # [Gp,Ec]
        tau_x = jnp.where(col_x > 0, rho / jnp.maximum(col_x, 1e-9), 0.0)
        cand_res = capR.sum(1)[candidx]  # [Np] retired column mass
        col_y = contrib.sum(1) + cand_res + 2.0
        tau_y = jnp.where(nmask > 0, rho / jnp.maximum(col_y, 1e-9), 0.0)
        row_q = compat.sum(1) + contrib.sum(0)
        sig_q = jnp.where(row_q > 0, 1.0 / (rho * jnp.maximum(row_q, 1e-9)),
                          0.0)
        iscand = (colcand < Np).astype(d.dtype)  # [Ec]
        row_p = (compat * 1.0).T @ d + capR * iscand[:, None]
        sig_p = jnp.where(row_p > 0, 1.0 / (rho * jnp.maximum(row_p, 1e-9)),
                          0.0)
        sig_m = 1.0 / (rho * 2.0)
        mpair = nmask[1:] * nmask[:-1]  # [Np-1] real adjacent pairs
        # claim column carries a small objective penalty so delete-only
        # optima win ties (colcand == Np marks non-candidate columns; the
        # claim column is flagged by its capacity living past E — the
        # caller passes it via c_x directly)
        c_y = -w

        def kt_mono(m):
            return (jnp.concatenate([jnp.zeros(1, d.dtype), m])
                    - jnp.concatenate([m, jnp.zeros(1, d.dtype)]))

        def one(state, c_x):
            x, y, q, p, m = state
            ktx = -q[:, None] + d @ p.T
            p_res = (capR * p).sum(1)  # [Ec]
            kty = contrib @ q + p_res[candidx] + kt_mono(m)
            xn = jnp.clip((x - tau_x * (c_x + ktx)) * compat,
                          0.0, xub[:, None])
            yn = jnp.clip(y - tau_y * (c_y + kty), 0.0, 1.0) * nmask
            xb, yb = 2.0 * xn - x, 2.0 * yn - y
            yb_ext = jnp.concatenate([yb, jnp.zeros(1, d.dtype)])
            y_col = yb_ext[colcand]  # [Ec]
            r_q = (base_req + yb @ contrib - xb.sum(1)) * gmask
            qn = jnp.maximum(q + sig_q * r_q, 0.0)
            r_p = xb.T @ d + capR * y_col[:, None] - capR
            pn = jnp.maximum(p + sig_p * r_p, 0.0)
            r_m = (yb[1:] - yb[:-1]) * mpair
            mn = jnp.maximum(m + sig_m * r_m, 0.0)
            return xn, yn, qn, pn, mn

        def viol_of(x, y):
            y_ext = jnp.concatenate([y, jnp.zeros(1, d.dtype)])
            y_col = y_ext[colcand]
            v_q = ((base_req + y @ contrib - x.sum(1)) * gmask
                   / (1.0 + xub)).max()
            v_p = ((x.T @ d + capR * y_col[:, None] - capR)
                   / (1.0 + capR)).max()
            v_m = ((y[1:] - y[:-1]) * mpair).max()
            return jnp.maximum(jnp.maximum(v_q, v_p), v_m)

        def cond(carry):
            _, _, _, _, _, it, done, _ = carry
            return jnp.logical_and(~done, it < max_iters)

        def body(carry):
            x, y, q, p, m, it, _, _ = carry
            y0 = y
            state = lax.fori_loop(
                0, CHECK_EVERY, lambda _, s: one(s, c_x), (x, y, q, p, m))
            x, y, q, p, m = state
            viol = viol_of(x, y)
            dy = jnp.abs(y - y0).max()
            done = jnp.logical_and(viol <= tol, dy <= tol)
            return x, y, q, p, m, it + CHECK_EVERY, done, viol

        z = jnp.zeros
        x0 = z((Gp, Ec), d.dtype)
        carry = (x0, z(Np, d.dtype), z(Gp, d.dtype), z((Ec, R), d.dtype),
                 z(Np - 1, d.dtype), jnp.int32(0), jnp.bool_(False),
                 jnp.asarray(jnp.inf, d.dtype))
        x, y, q, p, m, it, done, viol = lax.while_loop(cond, body, carry)
        return {"y": y, "q": q, "iters": it, "converged": done,
                "viol": viol, "k_frac": y.sum()}

    return jax.jit(run)


def _get_joint_kernel(Gp, Ec, Np, R):
    key = (Gp, Ec, Np, R, _relax_max_iters(), _relax_tol(), _relax_rho())
    fn = _JOINT_KERNELS.get(key)
    if fn is None:
        fn = _joint_kernel(Gp, Ec, Np, R, key[4], key[5], key[6])
        _JOINT_KERNELS[key] = fn
    return fn, key


def _round_kernel(Gp, Ec, R, W, claim_idx):
    """Jitted window-rounding pass: for each of W candidate prefixes
    (their required demands and survivor masks), greedily place every
    group (pre-ordered by demand, the _greedy_displace order) into the
    fullest-fitting columns via a full-length ``lax.top_k`` descent —
    the same floor/stable-tie arithmetic as the host oracle, in f32.
    Returns per-window unplaced totals and claim-column usage; the ONE
    winning prefix is then materialized exactly by the host oracle."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fill(req, surv, d, compat):
        # surv carries the capacity rows directly ([Ec,R] cap * mask,
        # built by the caller) so one tensor is both mask and budget
        resid0 = surv

        def place(carry, inp):
            resid, bad, claim = carry
            d_g, n_g, cm_g = inp
            pos = d_g > 0
            anypos = pos.any()
            n_eff = jnp.where(anypos, n_g, 0.0)
            safe_d = jnp.where(pos, d_g, 1.0)
            ratio = jnp.where(pos[None, :], resid / safe_d[None, :],
                              jnp.inf)
            caps = jnp.floor(ratio.min(1) + 1e-6)
            # RAW caps rank the descent (the host oracle's sort order) —
            # clamping at n_g here would forge ties and pick different
            # columns than _greedy_displace; the cumulative clip below
            # already bounds the takes
            caps = jnp.where(cm_g > 0, jnp.maximum(caps, 0.0), 0.0)
            # survivors-first, claim as LAST resort (the _greedy_displace
            # stance): the fresh envelope is the emptiest column and a
            # flat caps descent would grab it first, branding prefixes
            # claim-bearing — and price-gated — that the survivors could
            # absorb outright
            surv_caps = caps.at[claim_idx].set(0.0)
            vals, idx = lax.top_k(surv_caps, Ec)
            cume = jnp.concatenate(
                [jnp.zeros(1, d.dtype), jnp.cumsum(vals)[:-1]])
            take_s = jnp.clip(n_eff - cume, 0.0, vals)
            takes = jnp.zeros(Ec, d.dtype).at[idx].set(take_s)
            left = jnp.maximum(n_eff - takes.sum(), 0.0)
            c_take = jnp.minimum(left, caps[claim_idx])
            takes = takes.at[claim_idx].add(c_take)
            resid = resid - takes[:, None] * d_g[None, :]
            return (resid, bad + jnp.maximum(left - c_take, 0.0),
                    claim + c_take), None

        (resid, bad, claim), _ = lax.scan(
            place, (resid0, jnp.asarray(0.0, d.dtype),
                    jnp.asarray(0.0, d.dtype)),
            (d, req, compat))
        return bad, claim

    def run(req_w, surv_w, d, compat):
        return jax.vmap(lambda r, s: fill(r, s, d, compat))(req_w, surv_w)

    return jax.jit(run)


def _get_round_kernel(Gp, Ec, R, claim_idx):
    key = (Gp, Ec, R, ROUND_WINDOW, claim_idx,
           _relax_max_iters(), _relax_tol(), _relax_rho())
    fn = _ROUND_KERNELS.get(key)
    if fn is None:
        fn = _round_kernel(Gp, Ec, R, ROUND_WINDOW, claim_idx)
        _ROUND_KERNELS[key] = fn
    return fn


# ---------------------------------------------------------------------------
# joint consolidation entry (called from ops/consolidate.py
# joint_retirement_plan; returns (JointPlan | None, fallback cause))
# ---------------------------------------------------------------------------


def _joint_tensors(bundle, col_arr, contrib, base_req, claim_compat):
    """Host assembly of the LP tensors (padded to the pow-2 family).
    Columns 0..E-1 are the existing-node rows (dead rows zero-capacity),
    column E is the claim envelope; padding columns are zero."""
    snap, esnap = bundle.snap, bundle.esnap
    G, E, R = snap.G, esnap.E, len(snap.resources)
    N = len(col_arr)
    Gp = _pow2(G)
    Ec = _pow2(E + 1)
    Np = _pow2(max(N, 2), lo=2)
    f32 = np.float32

    d = np.zeros((Gp, R), f32)
    d[:G] = snap.g_demand[:G]
    live = np.asarray(esnap.live, dtype=bool)
    capR = np.zeros((Ec, R), f32)
    capR[:E] = np.maximum(np.asarray(esnap.e_avail, f32), 0.0)
    capR[:E][~live] = 0.0
    if snap.T:
        alloc_eff = snap.t_alloc - snap.m_overhead[snap.t_tmpl]
        capR[E] = np.maximum(alloc_eff.max(axis=0), 0.0)
    # per-resource equilibration: raw units span ~10 orders (cpu cores
    # vs memory BYTES), which would crush the Pock-Chambolle diagonal
    # steps to ~1e-11 and stall the iteration at the origin (a stalled y
    # reads as dy=0 and fakes convergence). Scaling d and capR by the
    # same per-resource factor is a pure change of units — every
    # constraint, ratio, and floor(resid/d) downstream is invariant.
    rscale = 1.0 / np.maximum(np.maximum(capR.max(0), d.max(0)), 1e-12)
    d *= rscale[None, :]
    capR *= rscale[None, :]
    compat = np.zeros((Gp, Ec), f32)
    compat[:G, :E] = np.asarray(esnap.ge_ok, bool)[:G, :E] & live[None, :]
    compat[:G, E] = claim_compat[:G]
    contrib_p = np.zeros((Np, Gp), f32)
    contrib_p[:N, :G] = contrib[:, :G]
    base_p = np.zeros(Gp, f32)
    base_p[:G] = base_req[:G]
    w = np.zeros(Np, f32)
    if N > 1:
        w[:N] = 1.0 + PREFIX_TIEBREAK * (N - 1 - np.arange(N)) / (N - 1)
    else:
        w[:N] = 1.0
    # colcand[e] = candidate index retiring column e (Np = none);
    # candidx[c] = column of candidate c (padding points at a dead slot)
    colcand = np.full(Ec, Np, np.int32)
    colcand[col_arr] = np.arange(N, dtype=np.int32)
    candidx = np.full(Np, Ec - 1, np.int32)
    candidx[:N] = col_arr.astype(np.int32)
    nmask = np.zeros(Np, f32)
    nmask[:N] = 1.0
    gmask = np.zeros(Gp, f32)
    gmask[:G] = 1.0
    # claim column's objective penalty (delete-only preference): the
    # claim sits at column E, a per-instance position INSIDE the padded
    # shape family, so it rides a tensor rather than a baked constant
    c_x = np.zeros((Gp, Ec), f32)
    c_x[:G, E] = CLAIM_PENALTY
    return dict(d=d, capR=capR, compat=compat, contrib=contrib_p,
                base_req=base_p, w=w, colcand=colcand, candidx=candidx,
                nmask=nmask, gmask=gmask, c_x=c_x), (Gp, Ec, Np, R)


def joint_relax_plan(bundle, candidates, col_arr, contrib, cum,
                     timings):
    """The relax fast path of ``joint_retirement_plan``: solve the
    fractional retirement LP, round through the device window, price-gate
    and exactly materialize the winning prefix with the FFD oracle.
    Returns ``(JointPlan, None)`` on a shipped plan or ``(None, cause)``
    when the round falls to the ladder (``cause`` is also pinned in
    ``RELAX_STATS['last_fallback']``; the ledger verdict for a ladder
    round that relax first declined is ``relax-fallback``)."""
    from karpenter_tpu.ops import consolidate as _cons

    RELAX_STATS["attempts"] += 1
    snap = bundle.snap
    G, N = snap.G, len(candidates)
    base = bundle.base
    claimable = bundle.claimable_groups()
    if claimable is None:
        if int(base.sum()):
            # claim accounting can't mirror the simulation (claimability
            # too large to prove with pending pods riding the demand):
            # the LP would not be definitive — the ladder's gallop is
            # the recovery machinery, exactly the non-definitive stance
            _fallback("inexpressible")
            return None, "inexpressible"
        base_req = np.zeros(G, dtype=np.float64)
        claim_compat = np.ones(G, dtype=bool) if snap.T else np.zeros(
            G, dtype=bool)
    else:
        base_req = np.where(claimable[:G], base[:G], 0).astype(np.float64)
        claim_compat = np.asarray(claimable[:G], dtype=bool)

    t0 = time.perf_counter()
    tensors, (Gp, Ec, Np, R) = _joint_tensors(
        bundle, col_arr, contrib, base_req, claim_compat)
    fn, key = _get_joint_kernel(Gp, Ec, Np, R)
    with obs.span("relax.solve", family=f"{Gp}x{Ec}", n=N):
        out = fn(tensors["d"], tensors["capR"], tensors["compat"],
                 tensors["contrib"], tensors["base_req"], tensors["w"],
                 tensors["colcand"], tensors["candidx"],
                 tensors["nmask"], tensors["gmask"], tensors["c_x"])
        out = {k: np.asarray(v) for k, v in out.items()}
    secs = time.perf_counter() - t0
    devplane.record_dispatch("relax.kernel", key, secs)
    devplane.record_padding("relax.grid", G * (bundle.esnap.E + 1) * N,
                            Gp * Ec * Np)
    RELAX_STATS["kernel_ms"] += secs * 1000.0
    iters = int(out["iters"])
    RELAX_STATS["iters"] += iters
    RELAX_STATS["last_iters"] = iters
    RELAX_STATS["last_viol"] = float(out["viol"])
    timings["relax_ms"] = timings.get("relax_ms", 0.0) + secs * 1000.0

    if not bool(out["converged"]):
        # the while_loop only exits converged or capped; a capped exit
        # leaves the fractional point uncertified (sum(y) is no bound)
        _fallback("iteration-cap")
        return None, "iteration-cap"
    k_ub = int(min(N, np.floor(float(out["k_frac"]) + 0.5)))
    RELAX_STATS["last_k_ub"] = k_ub
    if k_ub < 2:
        _fallback("lp-no-retirement")
        return None, "lp-no-retirement"

    # --- bounded device rounding descent: vmapped dispatches score W
    # prefixes per window below the LP bound, up to
    # KARPENTER_RELAX_ROUND_WINDOWS windows deep (the LP bound can
    # overshoot the integral optimum by more than one window's reach on
    # wide fleets). Replaces the host repair loop.
    n_windows = _relax_round_windows()
    live = np.asarray(bundle.esnap.live, dtype=bool)
    E = bundle.esnap.E
    # the host oracle's group order (raw-unit demand sum, the
    # _greedy_displace sort) — NOT the equilibrated tensors' order,
    # which can disagree and fail a prefix the oracle would round
    order = np.argsort(
        -np.asarray(snap.g_demand, np.float64)[:G].sum(1), kind="stable")
    order_p = np.concatenate(
        [order, np.arange(G, Gp)]).astype(np.intp)
    d_ord = tensors["d"][order_p]
    compat_ord = tensors["compat"][order_p]
    base_cap = tensors["capR"]
    rfn = _get_round_kernel(Gp, Ec, R, E)
    # price criterion for claim-bearing prefixes — the SAME ladder the
    # FFD path applies (ops/consolidate.py _prefix_price_ok)
    prefix_known, claim_ok = _cons._prefix_price_ok(bundle, candidates)
    price_blocked = False
    attempts = 0
    chosen = None
    k_dev = 0  # first flag-passing window k — the DEVICE decision the
    #            capsule records (host materialization below may descend
    #            further; the shipped k rides the capture as a static)
    # one prefix of headroom above the bound: the iteration terminates
    # on primal residual + movement, not duality gap, so the fractional
    # point can sit up to ~one unit shy of the true optimum — the flag
    # row rejects the extra prefix when the bound was already tight
    k_lo = int(min(N, k_ub + 1))
    for _w in range(n_windows):
        if chosen is not None or k_lo < 2 or attempts >= ROUND_ATTEMPTS:
            break
        ks = [k for k in range(k_lo, max(1, k_lo - ROUND_WINDOW), -1)]
        req_w = np.zeros((ROUND_WINDOW, Gp), np.float32)
        surv_w = np.zeros((ROUND_WINDOW, Ec), np.float32)
        for i, k in enumerate(ks):
            req = base_req.copy()
            req[:G] += contrib[:k, :G].sum(axis=0)
            req_w[i, :Gp] = np.concatenate(
                [req[order], np.zeros(Gp - G)]).astype(np.float32)
            mask = np.ones(Ec, np.float32)
            mask[col_arr[:k]] = 0.0
            surv_w[i] = mask
        # surv rows carry the capacity budget directly (cap * mask)
        surv_w = surv_w[:, :, None] * base_cap[None, :, :]
        t1 = time.perf_counter()
        with obs.span("relax.round", window=len(ks)):
            bad, claim = rfn(req_w, surv_w, d_ord, compat_ord)
            bad = np.asarray(bad)
            claim = np.asarray(claim)
        secs = time.perf_counter() - t1
        devplane.record_dispatch("relax.kernel", ("round",) + key, secs)
        RELAX_STATS["kernel_ms"] += secs * 1000.0
        timings["relax_ms"] += secs * 1000.0
        for i, k in enumerate(ks):
            if k < 2 or bad[i] > 0.5:
                continue
            claim_used = bool(claim[i] > 0.5)
            if claim_used and not (prefix_known[k - 1]
                                   and claim_ok[k - 1]):
                price_blocked = True
                continue
            if k_dev == 0:
                k_dev = k
            if attempts >= ROUND_ATTEMPTS:
                break
            attempts += 1
            surv = live.copy()
            surv[col_arr[:k]] = False
            required = base_req.copy()
            required[:G] += contrib[:k, :G].sum(axis=0)
            plan = _cons._greedy_displace(
                bundle, surv, required, allow_claim=claim_used,
                max_claims=_cons._replace_max_claims())
            if plan is not None:
                chosen = (k, plan, claim_used)
                break
        k_lo = ks[-1] - 1
    cause = None
    if chosen is None:
        cause = "price-gate" if price_blocked else "non-convergence"
        _fallback(cause)
    _capture_joint(bundle, candidates, col_arr, contrib, cum, base_req,
                   tensors, out, key, k_dev,
                   0 if chosen is None else chosen[0],
                   prefix_known, claim_ok, order)
    if chosen is None:
        return None, cause
    k_final, (placements, overflow, n_claims), _ = chosen
    dropped = max(k_ub - k_final, 0)
    RELAX_STATS["ships"] += 1
    RELAX_STATS["rounded_drops"] += dropped
    prefix_feasible = np.zeros(N, dtype=bool)
    prefix_feasible[:k_final] = True
    plan = _cons.JointPlan(
        candidates,
        selected_idx=range(k_final),
        delete_only=not overflow,
        definitive=True,
        displacement=placements,
        overflow=overflow,
        n_claims=n_claims,
        k_device=k_ub,
        dropped=dropped,
        timings=timings,
        prefix_feasible=prefix_feasible,
        single_mask=None,
        generation=bundle.generation,
        transient=False,
        solver="relax",
    )
    return plan, None


def _capture_joint(bundle, candidates, col_arr, contrib, cum, base_req,
                   tensors, out, key, k_dev, k_shipped,
                   prefix_known, claim_ok, order):
    """Record the ``relax.dispatch`` capsule seam: the LP tensors (the
    relax rung's replay inputs) merged with the standard
    counterfactual-row sidecars and shared snapshot args, so the A/B
    table can race the relax rung against the FFD ladder (``_run_probe``
    verbatim) and the host-FFD oracle on ONE capture. The captured
    ``k_sel`` output is the DEVICE window's selection — the first
    flag-passing, price-gated prefix — which replays bit-identically
    from the tensors alone; the host-materialized prefix the round
    actually shipped (which may descend further on ``_greedy_displace``
    refusals, and depends on live bundle state) rides as the
    ``k_shipped`` static."""
    from karpenter_tpu.obs import capsule as _capsule

    if not _capsule.capture_enabled():
        return
    G, N = bundle.snap.G, len(candidates)
    shared, (Gp_probe, Ep_probe) = bundle._shared_args()
    g_count_k = bundle.base[None, :] + cum
    lens = np.array([k + 1 for k in range(N)], dtype=np.int64)
    idx = np.concatenate(
        [col_arr[: k + 1] for k in range(N)]).astype(np.int64) if N else (
            np.zeros(0, dtype=np.int64))
    required = np.repeat(base_req[None, :G], N, axis=0)
    required += np.cumsum(contrib[:, :G], axis=0)
    inputs = dict(shared)
    cf = _capsule.CF_PREFIX
    inputs[cf + "g_count_rows"] = np.asarray(g_count_k)
    inputs[cf + "e_avail"] = np.asarray(bundle.esnap.e_avail)
    inputs[cf + "e_zero_idx"] = idx
    inputs[cf + "e_zero_len"] = lens
    for name in ("d", "capR", "compat", "contrib", "base_req", "w",
                 "colcand", "candidx", "nmask", "gmask", "c_x"):
        inputs[cf + "rx_" + name] = tensors[name]
    inputs[cf + "rx_required"] = required
    inputs[cf + "rx_col_arr"] = col_arr.astype(np.int64)
    # the host oracle's group order (raw-unit demand) — equilibrated
    # tensors can't reproduce it, so it rides the capture
    inputs[cf + "rx_order"] = np.asarray(order, np.int64)
    inputs[cf + "rx_claim_gate"] = (
        np.asarray(prefix_known, bool) & np.asarray(claim_ok, bool))
    _capsule.record_capture(
        "relax.dispatch", inputs,
        {"y": np.asarray(out["y"]), "k_sel": np.int64(k_dev)},
        engine="relax", max_minv=bundle.max_minv,
        Gp=Gp_probe, Ep=Ep_probe, k_shipped=int(k_shipped),
        rx_shape=list(key[:4]), rx_iters=key[4], rx_tol=key[5],
        rx_rho=key[6], rx_windows=_relax_round_windows(),
        rx_n=N, rx_g=G, rx_e=bundle.esnap.E)


# ---------------------------------------------------------------------------
# capsule replay entries (obs/capsule.py "relax.dispatch" seam)
# ---------------------------------------------------------------------------


def replay_joint(cap) -> dict:
    """Re-run the captured LP + rounding decision bit-identically: the
    same kernel family, the same knob values (from the capture statics,
    not the live environment), the same price-gate bits."""
    Gp, Ec, Np, R = (int(v) for v in cap.static("rx_shape"))
    iters = int(cap.static("rx_iters"))
    tol = float(cap.static("rx_tol"))
    rho = float(cap.static("rx_rho"))
    N = int(cap.static("rx_n"))
    G = int(cap.static("rx_g"))
    E = int(cap.static("rx_e"))
    key = (Gp, Ec, Np, R, iters, tol, rho)
    fn = _JOINT_KERNELS.get(key)
    if fn is None:
        fn = _joint_kernel(Gp, Ec, Np, R, iters, tol, rho)
        _JOINT_KERNELS[key] = fn
    t = {n: np.asarray(cap.sidecar("rx_" + n))
         for n in ("d", "capR", "compat", "contrib", "base_req", "w",
                   "colcand", "candidx", "nmask", "gmask", "c_x")}
    out = fn(t["d"], t["capR"], t["compat"], t["contrib"], t["base_req"],
             t["w"], t["colcand"], t["candidx"], t["nmask"], t["gmask"],
             t["c_x"])
    out = {k: np.asarray(v) for k, v in out.items()}
    k_sel = 0
    if bool(out["converged"]):
        k_ub = int(min(N, np.floor(float(out["k_frac"]) + 0.5)))
        if k_ub >= 2:
            n_windows = int(cap.static("rx_windows", 1))
            col_arr = np.asarray(cap.sidecar("rx_col_arr"))
            claim_gate = np.asarray(cap.sidecar("rx_claim_gate"))
            rk = _ROUND_KERNELS.get((Gp, Ec, R, ROUND_WINDOW, E,
                                     iters, tol, rho))
            if rk is None:
                rk = _round_kernel(Gp, Ec, R, ROUND_WINDOW, E)
                _ROUND_KERNELS[(Gp, Ec, R, ROUND_WINDOW, E,
                                iters, tol, rho)] = rk
            order = np.asarray(cap.sidecar("rx_order"))
            order_p = np.concatenate([order, np.arange(G, Gp)]).astype(
                np.intp)
            req_all = np.asarray(cap.sidecar("rx_required"))
            k_lo = int(min(N, k_ub + 1))  # the same one-prefix headroom
            for _w in range(n_windows):
                if k_sel or k_lo < 2:
                    break
                ks = [k for k in
                      range(k_lo, max(1, k_lo - ROUND_WINDOW), -1)]
                req_w = np.zeros((ROUND_WINDOW, Gp), np.float32)
                surv_w = np.zeros((ROUND_WINDOW, Ec), np.float32)
                for i, k in enumerate(ks):
                    req_w[i, :G] = req_all[k - 1][order]
                    mask = np.ones(Ec, np.float32)
                    mask[col_arr[:k]] = 0.0
                    surv_w[i] = mask
                surv_w = surv_w[:, :, None] * t["capR"][None, :, :]
                bad, claim = rk(req_w, surv_w, t["d"][order_p],
                                t["compat"][order_p])
                bad, claim = np.asarray(bad), np.asarray(claim)
                for i, k in enumerate(ks):
                    if k < 2 or bad[i] > 0.5:
                        continue
                    if claim[i] > 0.5 and not claim_gate[k - 1]:
                        continue
                    k_sel = k
                    break
                k_lo = ks[-1] - 1
    return {"y": np.asarray(out["y"]), "k_sel": np.int64(k_sel)}


def replay_host_round(cap) -> dict:
    """The host-FFD oracle leg of the A/B table: pure-numpy greedy
    prefix descent over the captured LP tensors — largest prefix whose
    displaced pods place integrally (f64, the _greedy_displace
    arithmetic), price-gated identically."""
    N = int(cap.static("rx_n"))
    G = int(cap.static("rx_g"))
    d = np.asarray(cap.sidecar("rx_d"), dtype=np.float64)[:G]
    capR = np.asarray(cap.sidecar("rx_capR"), dtype=np.float64)
    compat = np.asarray(cap.sidecar("rx_compat")).astype(bool)[:G]
    col_arr = np.asarray(cap.sidecar("rx_col_arr"))
    req_all = np.asarray(cap.sidecar("rx_required"), dtype=np.float64)
    claim_gate = np.asarray(cap.sidecar("rx_claim_gate"))
    E = int(cap.static("rx_e"))
    order = np.asarray(cap.sidecar("rx_order"))
    k_sel = 0
    for k in range(N, 1, -1):
        resid = capR.copy()
        resid[col_arr[:k]] = 0.0
        required = req_all[k - 1]
        ok = True
        claim_used = False
        for g in order:
            n = float(required[g])
            if n <= 0:
                continue
            dg = d[g]
            pos = dg > 0
            if not pos.any():
                continue
            rows = np.flatnonzero(compat[g])
            # survivors-first, claim as last resort — the same tiering
            # as _greedy_displace and the device window kernel
            surv_rows = rows[rows < E]
            caps = np.floor(
                (resid[np.ix_(surv_rows, np.flatnonzero(pos))]
                 / dg[pos][None, :]).min(axis=1) + 1e-9)
            for j in np.argsort(-caps, kind="stable"):
                if n <= 0:
                    break
                take = min(n, caps[j])
                if take <= 0:
                    break
                resid[surv_rows[j]] -= take * dg
                n -= take
            if n > 0 and (rows >= E).any():
                e = int(rows[rows >= E][0])
                ccap = float(np.floor(
                    (resid[e][pos] / dg[pos]).min() + 1e-9))
                take = min(n, ccap)
                if take > 0:
                    claim_used = True
                    resid[e] -= take * dg
                    n -= take
            if n > 0:
                ok = False
                break
        if ok and claim_used and not claim_gate[k - 1]:
            ok = False
        if ok:
            k_sel = k
            break
    return {"k_sel": np.int64(k_sel)}


# ---------------------------------------------------------------------------
# provisioning bin floor (models/solver.py _run_and_decode)
# ---------------------------------------------------------------------------


def _floor_kernel(Gp, Tp, R, max_iters, tol, rho):
    """PDHG over the provisioning LP (min total fractional bins), with a
    dual projection AFTER the iteration budget: scale the capacity duals
    into the bin constraint's cone, price every group at its cheapest
    compatible type, and weak duality certifies the resulting objective
    as a bin-count lower bound whether or not the primal converged."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(d, n, alloc, compat):
        # vars x[Gp,Tp] (pods of g on type t), b[Tp] (fractional bins)
        col_x = (1.0 + d.sum(1))[:, None] * compat
        tau_x = jnp.where(col_x > 0, rho / jnp.maximum(col_x, 1e-9), 0.0)
        col_b = alloc.sum(1)
        tau_b = jnp.where(col_b > 0, rho / jnp.maximum(col_b, 1e-9), 0.0)
        row_q = compat.sum(1)
        sig_q = jnp.where(row_q > 0, 1.0 / (rho * jnp.maximum(row_q, 1e-9)),
                          0.0)
        row_p = compat.T @ d + alloc
        sig_p = jnp.where(row_p > 0, 1.0 / (rho * jnp.maximum(row_p, 1e-9)),
                          0.0)
        n_tot = n.sum()

        def one(state):
            x, b, q, p = state
            ktx = -q[:, None] + d @ p.T
            ktb = 1.0 - (alloc * p).sum(1)
            xn = jnp.clip((x - tau_x * ktx) * compat, 0.0, n[:, None])
            bn = jnp.clip(b - tau_b * ktb, 0.0, n_tot)
            xb, bb = 2.0 * xn - x, 2.0 * bn - b
            qn = jnp.maximum(q + sig_q * (n - xb.sum(1)), 0.0)
            r_p = xb.T @ d - bb[:, None] * alloc
            pn = jnp.maximum(p + sig_p * r_p, 0.0)
            return xn, bn, qn, pn

        def cond(carry):
            _, _, _, _, it, done = carry
            return jnp.logical_and(~done, it < max_iters)

        def body(carry):
            x, b, q, p, it, _ = carry
            b0 = b.sum()
            x, b, q, p = lax.fori_loop(
                0, CHECK_EVERY, lambda _, s: one(s), (x, b, q, p))
            done = jnp.abs(b.sum() - b0) <= tol * (1.0 + b0)
            return x, b, q, p, it + CHECK_EVERY, done

        z = jnp.zeros
        x, b, q, p, it, _ = lax.while_loop(
            cond, body,
            (z((Gp, Tp), d.dtype), z(Tp, d.dtype), z(Gp, d.dtype),
             z((Tp, R), d.dtype), jnp.int32(0), jnp.bool_(False)))
        # dual projection — valid regardless of convergence: scale each
        # type's capacity duals into the b-constraint cone, price groups
        # at their cheapest compatible type
        scale = jnp.maximum((alloc * p).sum(1), 1.0)
        p_hat = p / scale[:, None]
        cost = d @ p_hat.T  # [Gp,Tp]
        cost = jnp.where(compat > 0, cost, jnp.inf)
        q_hat = cost.min(1)
        q_hat = jnp.where(jnp.isfinite(q_hat), q_hat, 0.0)
        return {"lb": (n * q_hat).sum(), "iters": it}

    return jax.jit(run)


def lp_bin_floor(snap, est: int) -> int:
    """A certified bin-count lower bound for one provisioning solve, or
    ``est`` unchanged when the rung is off/inapplicable. Called from
    ``models/solver.py _run_and_decode`` to tighten the bin-axis
    estimate; a raise is recorded as the ``solver.route`` ``relax`` rung
    when the solve completes (deploy/README.md "LP relaxation rung")."""
    if not relax_enabled():
        return est
    G, T = snap.G, snap.T
    R = len(snap.resources)
    if G < 2 or T < 1 or G * T > (1 << 18):
        return est
    from karpenter_tpu.ops.consolidate import _group_type_compat

    RELAX_STATS["floor_calls"] += 1
    t0 = time.perf_counter()
    compat = _group_type_compat(snap)  # [G,T]
    Gp, Tp = _pow2(G, lo=2), _pow2(T, lo=2)
    f32 = np.float32
    d = np.zeros((Gp, R), f32)
    d[:G] = snap.g_demand[:G]
    n = np.zeros(Gp, f32)
    n[:G] = snap.g_count[:G]
    alloc = np.zeros((Tp, R), f32)
    alloc[:T] = np.maximum(
        snap.t_alloc - snap.m_overhead[snap.t_tmpl], 0.0)
    # per-resource equilibration (same stance as _joint_tensors): the LP
    # is unit-invariant, the diagonal step sizes are not
    rscale = 1.0 / np.maximum(np.maximum(alloc.max(0), d.max(0)), 1e-12)
    d *= rscale[None, :]
    alloc *= rscale[None, :]
    cm = np.zeros((Gp, Tp), f32)
    cm[:G, :T] = compat
    # relax_enabled() in the key: GL501 — every knob read on this path
    # (including the enable gate above) fingerprints the cache entry
    key = (Gp, Tp, R, relax_enabled(),
           _relax_max_iters(), _relax_tol(), _relax_rho())
    fn = _FLOOR_KERNELS.get(key)
    if fn is None:
        fn = _floor_kernel(Gp, Tp, R, key[4], key[5], key[6])
        _FLOOR_KERNELS[key] = fn
    out = fn(d, n, alloc, cm)
    lb = float(np.asarray(out["lb"]))
    secs = time.perf_counter() - t0
    devplane.record_dispatch("relax.kernel", ("floor",) + key, secs)
    devplane.record_padding("relax.grid", G * T, Gp * Tp)
    RELAX_STATS["kernel_ms"] += secs * 1000.0
    floor = int(np.ceil(lb - 1e-6))
    if floor > est:
        RELAX_STATS["floor_raises"] += 1
        return floor
    return est
