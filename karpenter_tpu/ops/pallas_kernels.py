"""Pallas TPU kernel for the requirement-compatibility hot op.

`compat[G,T]` — the inner op of `ops.kernels.feasibility` — is a bitwise
"masked matmul": for every (group, type) pair, AND over requirement keys of
(both-defined ⇒ mask overlap ∨ both-NotIn tolerance). XLA fuses the jnp
formulation well, but the op is also the perfect Pallas shape: a 2D grid of
(8 × 128) tiles doing pure VPU bitwise work with one lane-reduction, no
matmul unit involved (see /opt/skills/guides/pallas_guide.md — grid over
G/8 × T/128, masks padded to the 128-lane register width).

Scope: the single-word vocabulary case (W == 1, i.e. ≤32 interned values
per key — the overwhelmingly common catalog shape); wider vocabularies
keep the jnp path. Enabled with KARPENTER_PALLAS=1 on a real TPU;
`interpret=True` runs the same kernel on CPU for the parity tests.

STATUS — reference kernel, not the default. Measured head-to-head on the
50k-pod × 500-type headline bench (round 5, real TPU, best-of-5 each,
four paired runs): pallas OFF 124/146/167/194 ms vs ON 127/130/201/204 ms.
The deltas sit inside the tunnel's ±40 ms jitter — neither side wins
reliably, which itself is the verdict: the compat op is too small a share
of the solve for hand tiling to pay, and the kernel boundary blocks
fusion with the surrounding feasibility ops, so the simpler XLA-fused jnp
path stays the default. Kept as a parity-tested reference for the day a
bigger vocabulary or a fused feasibility+pack Mosaic kernel changes the
math; bench.py records the on/off comparison in detail.pallas each round.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

TILE_G = 8
TILE_T = 128
LANES = 128  # key axis padded to the register width


def _pad_axis(a, axis, target):
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, target - a.shape[axis])
    return jnp.pad(a, pad)


def _compat_kernel(gm_ref, gh_ref, gtol_ref, tm_ref, th_ref, ttol_ref, out_ref):
    tm = tm_ref[...]  # [TILE_T, LANES] i32 masks
    th = th_ref[...]  # [TILE_T, LANES] i32 0/1
    ttol = ttol_ref[...]
    for i in range(TILE_G):  # static unroll: 8 rows of (128,128) VPU work
        gm = gm_ref[i, :][None, :]
        gh = gh_ref[i, :][None, :]
        gtol = gtol_ref[i, :][None, :]
        both = gh & th
        ov = (gm & tm) != 0
        tol = (gtol & ttol) != 0
        bad = both & jnp.logical_not(ov) & jnp.logical_not(tol)
        out_ref[i, :] = (jnp.sum(bad.astype(jnp.int32), axis=1) == 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def compat_pallas(g_mask, g_has, g_tol, t_mask, t_has, t_tol, *, interpret=False):
    """compat [G,T] bool via Pallas. Inputs: g_mask/t_mask [G|T, K] i32
    single-word value masks; g_has/t_has/g_tol/t_tol [G|T, K] bool."""
    from jax.experimental import pallas as pl

    G, K = g_mask.shape
    T = t_mask.shape[0]
    Gp = -(-G // TILE_G) * TILE_G
    Tp = -(-T // TILE_T) * TILE_T

    def prep(mask, has, tol, n_pad):
        m = _pad_axis(_pad_axis(mask.astype(jnp.int32), 1, LANES), 0, n_pad)
        h = _pad_axis(_pad_axis(has.astype(jnp.int32), 1, LANES), 0, n_pad)
        t = _pad_axis(_pad_axis(tol.astype(jnp.int32), 1, LANES), 0, n_pad)
        return m, h, t

    gm, gh, gtol = prep(g_mask, g_has, g_tol, Gp)
    tm, th, ttol = prep(t_mask, t_has, t_tol, Tp)

    out = pl.pallas_call(
        _compat_kernel,
        grid=(Gp // TILE_G, Tp // TILE_T),
        in_specs=[
            pl.BlockSpec((TILE_G, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_G, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_G, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((TILE_T, LANES), lambda i, j: (j, 0)),
            pl.BlockSpec((TILE_T, LANES), lambda i, j: (j, 0)),
            pl.BlockSpec((TILE_T, LANES), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((TILE_G, TILE_T), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((Gp, Tp), jnp.bool_),
        interpret=interpret,
    )(gm, gh, gtol, tm, th, ttol)
    return out[:G, :T]


def compat_reference(g_mask, g_has, g_tol, t_mask, t_has, t_tol):
    """The jnp formulation (mirrors ops.kernels.feasibility's compat loop)
    — the oracle for the Pallas kernel."""
    ov = (g_mask[:, None, :] & t_mask[None, :, :]) != 0
    tol = g_tol[:, None, :] & t_tol[None, :, :]
    both = g_has[:, None, :] & t_has[None, :, :]
    return jnp.all(~both | ov | tol, axis=-1)
