"""Batched device consolidation probes — the #2 kernel (SURVEY.md §2.6).

The reference's consolidation pass is host-bound twice over: the
MultiNodeConsolidation prefix search runs log2(100) sequential scheduling
simulations (multinodeconsolidation.go:111-163), and SingleNodeConsolidation
then walks every remaining candidate with one FULL simulation each under a
3-minute wall clock (singlenodeconsolidation.go:46-120). On a TPU both
searches become ONE batched counterfactual each: vmap the pack kernel over
N per-candidate (or per-prefix) snapshots that share every tensor with the
master except

- ``g_count``: pending pods plus the reschedulable pods of the
  counterfactual's candidates
- ``e_avail``: the cluster's nodes with those candidates zeroed out

so a batch is two stacked arrays over one shared snapshot. ``max_bins=1``
encodes the m→1 replacement rule (consolidation.go:164): a counterfactual
whose pods don't fit into the surviving nodes plus ONE fresh claim simply
leaves pods unassigned and is infeasible. Probe hits then get the real
confirming simulation (price filter, validation) — a handful of device
dispatches replacing the sequential ladders.

Topology-bearing clusters ride the probes too: the waves compiler
(ops/waves.py) turns the batch's spread/affinity/anti constraints into the
same class tensors the solve path uses, with one counterfactual
approximation — EVERY candidate's pods are excluded from the cluster domain
counts (each counterfactual rebinds them), so counterfactuals that keep
some candidates alive see slightly lower counts than the exact simulation.
The error runs in BOTH directions (lower anti/spread counts loosen the
probe; lower affinity match counts tighten it, so an affinity-dependent
counterfactual can read infeasible), which is why every probe answer is
only a SEED: winners are confirmed by the real simulation and a
mis-estimate degenerates into the reference's sequential search, never a
skipped consolidation.

The probes are sound PREFILTERS, not the decision: anything they cannot
express (waves-inexpressible shapes, non-basic-eligible pods, volume
limits) returns None and the caller falls back to the sequential search; a
probe hit is always re-validated by the full simulation before a command
ships.

Snapshot-cache invalidation contract
------------------------------------

``SnapshotCache`` memoizes ONE :class:`DisruptionSnapshot` — the tensorized
cluster view plus the solver inputs it was derived from — keyed on the
cluster-state generation counter (``state/cluster.py
Cluster.consolidation_state``). Within one generation the cache serves
every consumer of the disruption round: the MultiNode prefix probe, the
SingleNode candidate probe, and (via ``inputs``) the confirming
``simulate_scheduling`` calls and the controller's ``_validate`` re-check.

* **Generation key.** Every informer event that can change the scheduling
  answer bumps the counter (pod/node/nodeclaim updates, nodepool AND
  daemonset changes, deletion marks). A bundle whose generation no longer
  matches is dead: the next ``get`` re-tensorizes from scratch. Executing a
  command always bumps the generation (``mark_for_deletion``), so a
  validation round never sees a pre-command snapshot.
* **What delta-updates cover.** Candidate exclusion only: per-counterfactual
  ``g_count`` (pending base + the candidates' reschedulable pods, derived
  from the cached per-pod group index) and ``e_avail`` (the candidates'
  node columns zeroed). Everything else — group masks, type/offering
  tensors, existing-node admission, topology class tensors — is shared
  read-only from the one tensorization.
* **When full re-tensorize is mandatory.** Any generation bump; a build
  candidate set that is not a superset of the queried one (methods pass the
  full consolidatable pool as ``build_candidates`` so MultiNode's build
  also serves SingleNode); and any in-place catalog mutation that bypasses
  the informer plane (offerings flipped without a store event) — the cache
  cannot see those, which is safe only because probe answers are seeds:
  the confirming simulation re-tensorizes through ``tensorize``'s own
  offering-fingerprinted type cache and rejects stale hits.

Cache efficacy is scrapeable: ``karpenter_disruption_snapshot_cache_hits/
misses_total`` count bundle reuse, and the
``karpenter_disruption_probe_batch_size`` histogram records how many
counterfactuals each dispatch ranked.
"""

from __future__ import annotations

import functools

import numpy as np

from karpenter_tpu.ops.tensorize import (
    bucket as _bucket,
    device_basic_eligible,
    group_by_signature,
    kernel_args,
    pad_to as pad,
    tensorize,
    tensorize_existing,
)

# counterfactual rows per dispatch: 128 is exactly the shape family the
# capped prefix probe compiles (bucket(MULTI_NODE_CANDIDATE_CAP+1) = 128),
# so a 1000-candidate single-node scan re-uses the multi probe's compiled
# kernel instead of paying a fresh XLA compile per fleet size
PROBE_CHUNK_ROWS = 128


def _pow2(n: int, lo: int = 8) -> int:
    """Next power of two >= n (>= lo) — the probe's pad ladder."""
    import math

    return max(lo, 1 << math.ceil(math.log2(max(n, 1))))


@functools.lru_cache(maxsize=8)
def _batched_kernel(max_bins: int, max_minv: int = 0):
    import jax

    from karpenter_tpu.ops import kernels

    def probe(args):
        # max_minv is threaded statically: solve_step's host-side read of
        # m_minv cannot run on a tracer under this jit/vmap
        out = kernels.solve_step(args, max_bins=max_bins, use_pallas=False,
                                 max_minv=max_minv)
        # PER-GROUP placed counts, not a scalar: feasibility is "all the
        # candidates' pods land", and pods within a group are spec-
        # identical (interchangeable), so group-wise `placed >= the
        # candidates' contribution` is exact — a scalar total cannot tell
        # a stuck PENDING pod (which the reference's all_pods_scheduled
        # ignores) from a stuck candidate pod (which blocks the command)
        placed_g = out["assign"].sum(axis=1) + out["assign_e"].sum(axis=1)
        return placed_g, out["used"].sum()

    # g_count and e_avail carry the batch axis; everything else broadcasts
    def batched(varying, shared):
        def one(v):
            return probe({**shared, **v})

        return jax.vmap(one)(varying)

    return jax.jit(batched)


class DisruptionSnapshot:
    """One tensorized cluster view shared by a whole disruption round.

    Holds the solver inputs, the existing-node axis, the master device
    snapshot over (pending pods + every probeable candidate's reschedulable
    pods), and the per-pod group index that lets each probe derive its
    counterfactual ``g_count`` rows without re-tensorizing."""

    def __init__(self, generation, build_key, inputs, pending, enodes,
                 col_by_pid, unprobeable, plan, snap, esnap, gidx_of, base):
        self.generation = generation
        self.build_key = build_key  # frozenset of build-candidate provider ids
        self.inputs = inputs  # (templates, its_by_pool, overhead, limits, domains)
        self.pending = pending
        self.enodes = enodes
        self.col_by_pid = col_by_pid  # provider_id -> existing-node column
        self.unprobeable = unprobeable  # provider ids the probe cannot express
        self.plan = plan
        self.snap = snap
        self.esnap = esnap
        self.gidx_of = gidx_of  # pod uid -> group index
        self.base = base  # [G] i32: pending-pod counts (every counterfactual's floor)
        self.max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
        # cheapest AVAILABLE offering across the whole catalog: the lower
        # bound of any replacement claim's launch price, used by the probes'
        # price prefilter (it under-estimates the true replacement price —
        # compatibility can only raise it — so pruning on it is sound)
        avail_prices = snap.off_price[snap.off_avail]
        self.min_price = float(avail_prices.min()) if avail_prices.size else float("inf")
        self._shared = None
        self._dims = None

    def columns_for(self, candidates):
        """Existing-node columns for the queried candidates; None when any
        of them is invisible or inexpressible (caller stays sequential)."""
        cols = []
        for c in candidates:
            col = self.col_by_pid.get(c.provider_id)
            if col is None:
                return None
            cols.append(col)
        return cols

    def contribs_for(self, candidates):
        """[N,G] per-candidate reschedulable-pod counts over the snapshot's
        group axis; None when a pod is missing from the snapshot (a stale
        view the generation key should have caught — stay sequential)."""
        G = self.snap.G
        contrib = np.zeros((len(candidates), G), dtype=np.int32)
        for j, c in enumerate(candidates):
            for p in c.reschedulable_pods:
                g = self.gidx_of.get(p.uid)
                if g is None:
                    return None
                contrib[j, g] += 1
        return contrib

    def _shared_args(self):
        if self._shared is None:
            # pure power-of-two ladder (no 3·2^k steps): the solver's finer
            # ladder trades compiles for scan width, but the probe re-keys
            # its XLA compile on every fleet-size family and a consolidating
            # fleet walks DOWN through them (1000 → 334 nodes crosses 4 fine
            # buckets but only 2 power-of-two ones) — compile count, not
            # padded-scan width, dominates the probe's wall clock
            Gp = _pow2(self.snap.G)
            Ep = _pow2(self.esnap.E)
            Tp = _pow2(self.snap.T)
            # NOTE: kernel_args is the assembly point shared with
            # models/solver.py — a field missed there weakens both paths at
            # once and the lockstep test catches it
            self._shared = kernel_args(
                self.snap, self.esnap, Gp=Gp, Tp=Tp, Ep=Ep,
                include_counts=False,
            )
            self._dims = (Gp, Ep)
        return self._shared, self._dims

    def dispatch(self, g_count_k, e_zero_cols):
        """Run the batched pack kernel over the counterfactual rows; returns
        (placed_g, used) — per-row PER-GROUP placed-pod counts (shape
        [rows, Gp]) and per-row fresh-claim counts.

        ``e_zero_cols[i]`` holds the existing-node columns row i removes
        from the cluster; counterfactual ``e_avail`` rows materialize
        chunk-locally from the master tensor — never the full [rows, E, R]
        array host-side, which an uncapped single-node batch over a large
        fleet would blow into hundreds of MB before the first dispatch.
        Rows are chunked (and the chunk axis padded on the same pure pow-2
        ladder as the snapshot axes) so the batch stays inside a handful of
        compiled shape families. Small-work snapshots route through the C++
        engine under the solver's routing gate (models/solver.py
        NATIVE_CUTOFF_PODS stance): few-group batches are short sequential
        loops the native engine finishes without paying an XLA compile per
        fleet-size family."""
        if self._native_routable():
            try:
                return self._dispatch_native(g_count_k, e_zero_cols)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "native probe dispatch failed; using the XLA kernel",
                    exc_info=True)
        shared, (Gp, Ep) = self._shared_args()
        R = len(self.snap.resources)
        rows = g_count_k.shape[0]
        placed_g = np.empty((rows, Gp), dtype=np.int64)
        used = np.empty(rows, dtype=np.int64)
        for lo in range(0, rows, PROBE_CHUNK_ROWS):
            hi = min(lo + PROBE_CHUNK_ROWS, rows)
            n = hi - lo
            Np = _pow2(n, lo=4)
            e_chunk = np.repeat(self.esnap.e_avail[None, :, :], n, axis=0)
            for i in range(n):
                cols = e_zero_cols[lo + i]
                if cols is not None and len(cols):
                    e_chunk[i, cols, :] = 0.0
            varying = dict(
                g_count=pad(g_count_k[lo:hi], (Np, Gp)),
                e_avail=pad(e_chunk, (Np, Ep, R)),
            )
            out_placed, out_used = _batched_kernel(1, self.max_minv)(
                varying, shared)
            placed_g[lo:hi] = np.asarray(out_placed)[:n]
            used[lo:hi] = np.asarray(out_used)[:n]
        return placed_g, used

    def _native_routable(self) -> bool:
        """The solver's engine-routing gate applied to the probe: the same
        KARPENTER_NATIVE_CUTOFF master switch (0 disables all routing, so
        tests keep the XLA path under coverage) and the same feasibility-
        work floor — a probe row's parallelism is G×T, and below the floor
        the accelerator (or its CPU emulation) can't amortize dispatch and
        compile."""
        import os

        from karpenter_tpu.models.solver import DEVICE_MIN_WORK, _native_cutoff

        if _native_cutoff() <= 0:
            return False
        min_work = int(os.environ.get("KARPENTER_DEVICE_MIN_WORK", DEVICE_MIN_WORK))
        if self.snap.G * self.snap.T >= min_work:
            return False
        try:
            from karpenter_tpu import native

            return native.available()
        except Exception:
            return False

    def _dispatch_native(self, g_count_k, e_zero_cols):
        from karpenter_tpu import native

        shared, (Gp, Ep) = self._shared_args()
        R = len(self.snap.resources)
        rows = g_count_k.shape[0]
        placed_g = np.empty((rows, Gp), dtype=np.int64)
        used = np.empty(rows, dtype=np.int64)
        for i in range(rows):
            e_row = self.esnap.e_avail.copy()
            cols = e_zero_cols[i]
            if cols is not None and len(cols):
                e_row[cols, :] = 0.0
            args = dict(shared)
            args["g_count"] = pad(g_count_k[i], (Gp,))
            args["e_avail"] = pad(e_row, (Ep, R))
            out = native.solve_step(args, 1)
            placed_g[i] = (
                np.asarray(out["assign"]).sum(axis=1)
                + np.asarray(out["assign_e"]).sum(axis=1)
            )
            used[i] = int(np.asarray(out["used"]).sum())
        return placed_g, used


def build_disruption_snapshot(provisioner, cluster, store, candidates):
    """Assemble the shared tensor bundle for one disruption round. Returns
    None when the device path cannot express the scenario at all (the
    probes then fall back to the sequential search)."""
    try:
        import jax  # noqa: F401
    except Exception:
        return None
    from karpenter_tpu.utils import pod as pod_util

    generation = cluster.consolidation_state()
    pending = [p for p in store.list("pods") if pod_util.is_provisionable(p)]
    if any(not device_basic_eligible(p) for p in pending):
        return None  # every counterfactual row must hold the pending pods

    # candidates whose pods the kernel can't express are dropped from the
    # bundle (not fatal): queries naming them fall back to the sequential
    # search, everyone else still rides the shared snapshot
    probeable, unprobeable = [], set()
    for c in candidates:
        pods = list(c.reschedulable_pods)
        if any(not device_basic_eligible(p) for p in pods):
            unprobeable.add(c.provider_id)
        else:
            probeable.append((c, pods))
    all_pods = pending + [p for _, ps in probeable for p in ps]
    if not all_pods:
        return None

    templates, its_by_pool, overhead, limits, domains = provisioner.solver_inputs()
    if not templates:
        return None

    # counterfactual topology: all candidate pods excluded from the cluster
    # domain counts (helpers.go:51's excluded-pod stance, applied across
    # every counterfactual at once)
    from karpenter_tpu.controllers.provisioning.provisioner import ClusterStateView
    from karpenter_tpu.models.topology import Topology
    from karpenter_tpu.ops import waves

    view = ClusterStateView(cluster, store)
    topology = Topology(cluster=view, domains=domains, pods=all_pods)

    state_nodes = list(cluster.nodes())
    enodes = provisioner._existing_nodes(state_nodes, topology)
    by_pid = {e.state_node.provider_id: i for i, e in enumerate(enodes)}
    col_by_pid = {}
    for c, _ in probeable:
        i = by_pid.get(c.provider_id)
        if i is None:
            unprobeable.add(c.provider_id)  # invisible to the probe
        else:
            col_by_pid[c.provider_id] = i

    plan = None
    if topology.has_groups:
        plan = waves.compile_topology(group_by_signature(all_pods), topology)
        if plan.host_pods:
            return None  # waves-inexpressible shape: stay sequential

    snap = tensorize(
        all_pods if plan is None else None, templates, its_by_pool,
        daemon_overhead=overhead, limits=limits or None, device_plan=plan,
    )
    if snap.G == 0:
        return None
    esnap = tensorize_existing(snap, enodes, plan)

    gidx_of = {}
    for g, pods_g in enumerate(snap.groups):
        for p in pods_g:
            gidx_of[p.uid] = g
    # pending pods join every counterfactual row (they contend for capacity
    # exactly as in the real simulation), but feasibility is judged PER
    # GROUP against the candidates' contribution only — a pending pod that
    # cannot schedule anywhere (and would not block the sequential path,
    # which only requires the candidates' pods to land —
    # SimulationResults.all_pods_scheduled) cannot poison the batch
    base = np.zeros(snap.G, dtype=np.int32)
    for p in pending:
        base[gidx_of[p.uid]] += 1

    return DisruptionSnapshot(
        generation=generation,
        build_key=frozenset(c.provider_id for c in candidates),
        inputs=(templates, its_by_pool, overhead, limits, domains),
        pending=pending,
        enodes=enodes,
        col_by_pid=col_by_pid,
        unprobeable=unprobeable,
        plan=plan,
        snap=snap,
        esnap=esnap,
        gidx_of=gidx_of,
        base=base,
    )


class SnapshotCache:
    """Round-scoped memo of the latest :class:`DisruptionSnapshot`, keyed
    on the cluster-state generation (see the module docstring for the full
    invalidation contract). One instance lives on the DisruptionContext so
    Emptiness → MultiNode → SingleNode → validation share one
    tensorization per generation."""

    def __init__(self):
        self._bundle = None
        self._neg = None  # (generation, build_key) of a failed build

    def get(self, provisioner, cluster, store, candidates, registry=None):
        from karpenter_tpu.operator import metrics as m

        generation = cluster.consolidation_state()
        key = frozenset(c.provider_id for c in candidates)
        b = self._bundle
        if b is not None and b.generation == generation and key <= b.build_key:
            if registry is not None:
                registry.counter(
                    m.DISRUPTION_SNAPSHOT_CACHE_HITS,
                    "disruption probes served from the snapshot cache",
                ).inc(kind="snapshot")
            return b
        if self._neg == (generation, key):
            # an inexpressible build is generation-stable: don't re-pay the
            # assembly for every method in the round. Counted under its own
            # label — a permanently-inexpressible cluster must not read as
            # a healthy snapshot cache on the scrape
            if registry is not None:
                registry.counter(
                    m.DISRUPTION_SNAPSHOT_CACHE_HITS,
                    "disruption probes served from the snapshot cache",
                ).inc(kind="negative")
            return None
        if registry is not None:
            registry.counter(
                m.DISRUPTION_SNAPSHOT_CACHE_MISSES,
                "disruption snapshot rebuilds (generation bump or wider "
                "candidate set)",
            ).inc()
        b = build_disruption_snapshot(provisioner, cluster, store, candidates)
        if b is not None:
            self._bundle = b
            self._neg = None
        else:
            self._neg = (generation, key)
        return b

    def inputs_for(self, cluster):
        """The cached solver inputs when still generation-current, else
        None — lets the confirming simulations skip re-assembling
        templates/catalog/overhead inside one disruption round. Safe
        because every structural input change bumps the generation and the
        catalog objects are shared by identity."""
        b = self._bundle
        if (
            b is not None
            and cluster is not None
            and b.generation == cluster.consolidation_state()
        ):
            return b.inputs
        return None


def _bundle_for(provisioner, cluster, store, candidates, cache, registry,
                build_candidates):
    build = build_candidates if build_candidates else list(candidates)
    if cache is not None:
        return cache.get(provisioner, cluster, store, build, registry=registry)
    return build_disruption_snapshot(provisioner, cluster, store, build)


def batched_feasible_prefix(provisioner, cluster, store, candidates,
                            cache=None, registry=None, build_candidates=None):
    """Largest k such that candidates[:k] consolidate into the remaining
    cluster plus at most one fresh claim, decided in one device call.
    Returns None when the probe cannot express the scenario (the caller
    falls back to the sequential binary search)."""
    bundle = _bundle_for(
        provisioner, cluster, store, candidates, cache, registry,
        build_candidates,
    )
    if bundle is None:
        return None
    cols = bundle.columns_for(candidates)
    if cols is None:
        return None
    contrib = bundle.contribs_for(candidates)
    if contrib is None:
        return None

    base = bundle.base
    N = len(candidates)
    G = bundle.snap.G
    cum = np.cumsum(contrib, axis=0)  # [N,G]: row k = prefix k+1's candidate pods
    g_count_k = base[None, :] + cum  # pending pods contend exactly as in the real sim
    col_arr = np.asarray(cols, dtype=np.intp)
    # row k removes candidates[:k+1] (views into one array, not copies)
    e_zero_cols = [col_arr[: k + 1] for k in range(N)]

    placed_g, used = bundle.dispatch(g_count_k, e_zero_cols)
    # prefix k feasible iff EVERY group placed at least the prefix's own
    # candidate contribution: pods within a group are spec-identical
    # (interchangeable), so the group-wise test is exactly "all displaced
    # pods land" — and a stuck PENDING pod, which the reference's
    # all_pods_scheduled ignores (helpers.py SimulationResults), can never
    # poison the batch
    feasible = (placed_g[:, :G] >= cum).all(axis=1)
    if bundle.plan is None:
        # price prefilter (consolidation.go filterByPrice as a batch
        # prune): a prefix that needs the one fresh claim can only ship if
        # SOME available offering launches strictly cheaper than the prefix
        # costs today; the cheapest catalog offering under-estimates the
        # replacement price. Plan-free bundles only: the kernel fills
        # existing nodes before opening the fresh bin, so `used` is
        # reliable there — topology tightening can inflate it, and a wrong
        # prune would burn the binary-search simulations the batch exists
        # to avoid
        prices = np.array(
            [getattr(c, "price", 0.0) for c in candidates], dtype=np.float64
        )
        # a prefix containing an unpriceable candidate aborts its replace
        # path outright (candidate_prices' getCandidatePrices stance)
        prefix_known = np.logical_and.accumulate(prices > 0)
        prefix_price = np.cumsum(prices)
        feasible &= (used == 0) | (
            prefix_known & (bundle.min_price < prefix_price)
        )
    ks = np.flatnonzero(feasible)
    if ks.size == 0:
        return 0
    return int(ks[-1]) + 1


def batched_single_feasible(provisioner, cluster, store, candidates,
                            cache=None, registry=None, build_candidates=None):
    """Per-candidate consolidation feasibility, every candidate probed in
    one batched device call: counterfactual c removes ONLY candidate c and
    asks whether its reschedulable pods land on the surviving nodes plus at
    most one fresh claim.

    Returns ``(mask, definitive)`` — a bool array over ``candidates``
    (probe hits are SEEDS for the real confirming simulation) and whether
    the MISSES may be trusted: for topology-compiled bundles the waves
    counterfactual approximation can tighten the probe (module docstring),
    and unlike the prefix probe there is no binary-search recovery around a
    mis-estimated candidate, so non-definitive misses must be re-checked by
    the caller's sequential scan rather than skipped. Returns None when the
    scenario is inexpressible (the caller falls back to the sequential
    scan)."""
    bundle = _bundle_for(
        provisioner, cluster, store, candidates, cache, registry,
        build_candidates,
    )
    if bundle is None:
        return None
    cols = bundle.columns_for(candidates)
    if cols is None:
        return None
    contrib = bundle.contribs_for(candidates)
    if contrib is None:
        return None

    base = bundle.base
    N = len(candidates)
    G = bundle.snap.G
    g_count_k = base[None, :] + contrib  # [N,G]
    col_arr = np.asarray(cols, dtype=np.intp)
    # row c removes ONLY candidate c
    e_zero_cols = [col_arr[c : c + 1] for c in range(N)]

    placed_g, used = bundle.dispatch(g_count_k, e_zero_cols)
    # same group-wise criterion as the prefix probe: candidate c's pods all
    # land iff every group places at least c's contribution (stuck pending
    # pods are not the candidate's problem — all_pods_scheduled checks only
    # candidate pods)
    mask = (placed_g[:, :G] >= contrib).all(axis=1)
    if bundle.plan is None:
        # price prefilter, mirroring the prefix probe: a candidate whose
        # pods need the one fresh claim only consolidates if SOME available
        # offering could launch strictly cheaper than the candidate costs
        # today (an unpriceable candidate aborts the replace path
        # outright); a used==0 counterfactual is a pure delete — no price
        # involved. Plan-free bundles only: the kernel fills existing nodes
        # before opening the fresh bin, so `used` is reliable there, while
        # a topology bundle's tightened fit can inflate it — which is
        # exactly why those misses are flagged non-definitive.
        prices = np.array(
            [getattr(c, "price", 0.0) for c in candidates], dtype=np.float64
        )
        mask = mask & (
            (used == 0) | ((prices > 0) & (bundle.min_price < prices))
        )
    return mask, bundle.plan is None
