"""Batched device consolidation probes — the #2 kernel (SURVEY.md §2.6).

The reference's consolidation pass is host-bound twice over: the
MultiNodeConsolidation prefix search runs log2(100) sequential scheduling
simulations (multinodeconsolidation.go:111-163), and SingleNodeConsolidation
then walks every remaining candidate with one FULL simulation each under a
3-minute wall clock (singlenodeconsolidation.go:46-120). On a TPU both
searches become ONE batched counterfactual each: vmap the pack kernel over
N per-candidate (or per-prefix) snapshots that share every tensor with the
master except

- ``g_count``: pending pods plus the reschedulable pods of the
  counterfactual's candidates
- ``e_avail``: the cluster's nodes with those candidates zeroed out

so a batch is two stacked arrays over one shared snapshot. ``max_bins=1``
encodes the m→1 replacement rule (consolidation.go:164): a counterfactual
whose pods don't fit into the surviving nodes plus ONE fresh claim simply
leaves pods unassigned and is infeasible. Probe hits then get the real
confirming simulation (price filter, validation) — a handful of device
dispatches replacing the sequential ladders. Since ISSUE 19 the rule
generalizes to the joint REPLACE program: ``max_bins`` threads through
the dispatch seam, ``_claims_fit`` splits a set's overflow across up to
``KARPENTER_REPLACE_MAX_CLAIMS`` fresh claims (default 1 keeps m→1),
and a confirmed multi-claim plan records the ``replace`` verdict — see
deploy/README.md "Fused cluster round".

Topology-bearing clusters ride the probes too: the waves compiler
(ops/waves.py) turns the batch's spread/affinity/anti constraints into the
same class tensors the solve path uses, with one counterfactual
approximation — EVERY candidate's pods are excluded from the cluster domain
counts (each counterfactual rebinds them), so counterfactuals that keep
some candidates alive see slightly lower counts than the exact simulation.
The error runs in BOTH directions (lower anti/spread counts loosen the
probe; lower affinity match counts tighten it, so an affinity-dependent
counterfactual can read infeasible), which is why every probe answer is
only a SEED: winners are confirmed by the real simulation and a
mis-estimate degenerates into the reference's sequential search, never a
skipped consolidation.

The probes are sound PREFILTERS, not the decision: anything they cannot
express (waves-inexpressible shapes, non-basic-eligible pods, volume
limits) returns None and the caller falls back to the sequential search; a
probe hit is always re-validated by the full simulation before a command
ships.

Global consolidation (ISSUE 13) lifts the same counterfactual machinery
into ONE joint solve: :func:`joint_retirement_plan` runs the prefix
ladder over EVERY candidate simultaneously (no per-method cap), scores
it with the identical shared criterion (:func:`_prefix_criterion`),
rounds the winning row integrally on the host
(``KARPENTER_GLOBAL_REPAIR_MAX`` bounded repair, the parallel/mesh.py
stance), and hands the caller a whole retirement set plus its
displacement plan for exactly one confirming simulation — the
per-candidate ladder is retired to oracle/fallback duty. Mode knob,
fallback ladder, confirm contract, and the ``consolidate.global`` ledger
site are documented in deploy/README.md ("Global consolidation"); the
joint dispatch records the ``global.dispatch`` replay-capsule seam.

ISSUE 14 makes the whole disruption round device-bound end to end:

* **Vectorized formulation.** The bundle caches a per-node-row ``[E,G]``
  contribution matrix (built lazily from its own node snapshots,
  row-invalidated by delta advances — a delta-advanced snapshot reuses
  the prior round's formulation rows) and :meth:`DisruptionSnapshot.
  contribs_for` is one fancy-index gather; :func:`_prefix_criterion`'s
  per-type price vectors are cached the same way and its cheapest-cum
  pass is one ``minimum.accumulate`` per present type. The original
  per-candidate Python loops stay as the bit-exactness ORACLE —
  ``KARPENTER_GLOBAL_FORMULATE_LOOP=1`` forces them everywhere, and the
  parity suite pins gather ≡ loop across 100+ seeded snapshots.
* **Joint-verdict short-circuit.** :func:`joint_retirement_plan` can
  carry the per-candidate SINGLE rows in the same dispatch (scored by
  the shared :func:`_single_criterion`), and its answers publish as the
  round's :class:`JointSeed` — the MultiNode/SingleNode probes of the
  SAME generation answer off it (``probe.confirm`` reason
  ``joint-seeded``) instead of re-dispatching, and a definitive
  mid-transition no-retirement verdict closes the round outright
  (``consolidate.global`` ``joint-noop-fenced``). One state bump pays
  ONE dispatch; :func:`note_probe_dispatch` accounts the per-generation
  contract perf/bench gate on.
* The round's bundle acquisition is hoisted into the controller's
  prewarm (``bundle_ms``), and the post-command wave batches through
  the store's ``evict_wave`` — see deploy/README.md "Global
  consolidation" for the row schema and knob table.

Spot resilience rides the same machinery with zero new dispatch paths
(ISSUE 15, deploy/README.md "Spot resilience"): the snapshot's
``off_price`` tensor carries the risk-discounted EFFECTIVE price
(``price × (1 + λ·risk)``, cloudprovider/types.effective_price — nominal
at λ=0), so ``min_price``, ``_type_price_vectors``, and both criteria
below are risk-aware through the numbers they already read; and the
``InterruptionDrain`` method's absorb probe is one counterfactual row
through :meth:`DisruptionSnapshot.dispatch` under the
``interruption.dispatch`` capture seam.

Snapshot-cache invalidation contract
------------------------------------

``SnapshotCache`` memoizes ONE :class:`DisruptionSnapshot` — the tensorized
cluster view plus the solver inputs it was derived from — keyed on the
cluster-state generation counter (``state/cluster.py
Cluster.consolidation_state``). Within one generation the cache serves
every consumer of the disruption round: the MultiNode prefix probe, the
SingleNode candidate probe, and (via ``inputs``) the confirming
``simulate_scheduling`` calls and the controller's ``_validate`` re-check.

* **Generation key.** Every informer event that can change the scheduling
  answer bumps the counter (pod/node/nodeclaim updates, nodepool AND
  daemonset changes, deletion marks). A bundle whose generation no longer
  matches is stale; ``get`` then consults the cluster's structured delta
  journal (``Cluster.deltas_since``) and PATCHES the bundle in place
  (``DisruptionSnapshot.advance`` → ``ExistingSnapshot.apply_delta``,
  tensorize.py "Existing-node delta contract") when every bump is
  node/pod-scoped and expressible on the existing group axis; otherwise it
  re-tensorizes from scratch. Executing a command always bumps the
  generation (``mark_for_deletion``), so a validation round never sees a
  pre-command snapshot — delta-advanced or rebuilt, it reflects the marks.
* **What delta-updates cover.** Two layers. Per-QUERY (unchanged from
  PR 2): candidate exclusion only — counterfactual ``g_count`` rows and
  zeroed ``e_avail`` columns over the shared tensors. Per-GENERATION (this
  PR): dirty node rows rebuilt from live state, removed nodes masked in
  place (the E axis never shrinks, keeping the compiled shape family),
  added nodes appended, and new pods registered onto the group axis by
  scheduling signature so rebound replicas keep ``contribs_for`` exact.
* **When full re-tensorize is mandatory.** An opaque journal entry
  (nodepool/daemonset change, resync) or a journal gap; a pod whose
  signature matches no tensorized group (new vocabulary/group set); a
  topology-compiled plan (waves domain counts are position-dependent);
  nodepool limits (remaining = spec − usage drifts with node churn); churn
  above half the fleet (a rebuild also re-compacts the E axis); a build
  candidate set that is not a superset of the queried one (methods pass
  the full consolidatable pool as ``build_candidates`` so MultiNode's
  build also serves SingleNode); and any in-place catalog mutation that
  bypasses the informer plane (offerings flipped without a store event) —
  the cache cannot see those, which is safe only because probe answers are
  seeds: the confirming simulation re-tensorizes through ``tensorize``'s
  own offering-fingerprinted type cache and rejects stale hits.
* **The confirming simulations ride the bundle too.** Within one
  generation, ``helpers.simulate_scheduling`` forks the bundle's
  ExistingNode prototypes (``sim_enodes``) instead of re-running the O(E)
  constructor sweep, and the solver derives the sub-solve's existing-node
  tensors from the bundle's rows (``derive_esnap``) instead of an O(E×G)
  re-tensorize; both decline — and the slow path runs — whenever a node or
  group fails to map.

Cache efficacy is scrapeable: ``karpenter_disruption_snapshot_cache_hits/
misses_total`` count bundle reuse, and the
``karpenter_disruption_probe_batch_size`` histogram records how many
counterfactuals each dispatch ranked. The same stages also speak the
reconcile flight recorder's span protocol (:mod:`karpenter_tpu.obs`):
snapshot builds/advances open ``cache``-kind spans, probe dispatches open
``device``-kind spans, and a full rebuild that displaces a held bundle
marks the round anomalous (``snapshot-rebuild``) so its Chrome trace
dumps — the causal complement to the counters above. Probe dispatches
also feed the device-plane telemetry (:mod:`karpenter_tpu.obs.devplane`):
each chunk records its pow-2 row-ladder waste
(``karpenter_pad_waste_ratio{site="probe.rows"}``) and its executable
family in the compile ledger (``probe.kernel`` — a cold compile during a
long warm streak trips the ``cold-compile-in-steady-state`` trace dump).
Metric semantics live in deploy/README.md ("Device-plane & SLO
telemetry").

The same transitions also feed the DECISION ledger
(:mod:`karpenter_tpu.obs.decisions`): every stale-bundle resolution
records exactly one ``("snapshot.advance", delta|rebuild, reason)``
verdict — the rebuild reason is the actual inexpressible-delta cause
(opaque-entry / journal-gap / plan / limits / unseen-signature /
unseen-pending / ineligible-pending / churn / candidate-widened, a closed
enum) — so a delta path that quietly dies in steady state fires the
``rung-regression`` trace dump instead of only nudging a miss counter.
See deploy/README.md "Decision plane".

Probe dispatches also record a replay capture (the shared snapshot, the
counterfactual rows, and their zeroed-column sets — everything
``dispatch_counterfactual_rows`` needs to re-execute the exact chunked
program offline): an anomalous disruption round yields a replay capsule
(:mod:`karpenter_tpu.obs.capsule`, deploy/README.md "Replay capsules").
"""

from __future__ import annotations

import functools
import time
from collections import OrderedDict

import numpy as np

from karpenter_tpu import obs
from karpenter_tpu.obs import devplane
from karpenter_tpu.ops.tensorize import (
    ExistingSnapshot,
    bucket as _bucket,
    device_basic_eligible,
    group_by_signature,
    interned_signature,
    kernel_args,
    pad_to as pad,
    tensorize,
    tensorize_existing,
)

# counterfactual rows per dispatch: 128 is exactly the shape family the
# capped prefix probe compiles (bucket(MULTI_NODE_CANDIDATE_CAP+1) = 128),
# so a 1000-candidate single-node scan re-uses the multi probe's compiled
# kernel instead of paying a fresh XLA compile per fleet size
PROBE_CHUNK_ROWS = 128

# the native (C++) probe entry has no XLA compile to re-key, so its only
# chunking constraint is the counterfactual e_avail materialization
# (rows × E × R floats) — and the engine rebuilds feasibility once per
# chunk, so a 2k-row joint ladder at 128-row chunks paid 16 redundant
# builds. 1024 rows × 2048 nodes × a handful of resources stays in the
# tens of MB; results are row-independent, so the chunk size can never
# change an answer (replay included: the capsule re-executes through
# this same constant).
NATIVE_PROBE_CHUNK_ROWS = 1024


def _pow2(n: int, lo: int = 8) -> int:
    """Next power of two >= n (>= lo) — the probe's pad ladder."""
    import math

    return max(lo, 1 << math.ceil(math.log2(max(n, 1))))


def _formulate_loop() -> bool:
    """The vectorized-formulation oracle knob (ISSUE 14):
    ``KARPENTER_GLOBAL_FORMULATE_LOOP=1`` forces the original
    per-candidate Python loops (``_contribs_loop`` and the
    ``_cheapest_cum_loop`` half of :func:`_prefix_criterion`) everywhere
    the batched array construction would otherwise run — the bit-exactness
    oracle the parity suite pins the gather against, and the production
    kill-switch if a gather bug ever surfaces in the field."""
    from karpenter_tpu.utils.envknobs import env_bool

    return env_bool("KARPENTER_GLOBAL_FORMULATE_LOOP", False)


# per-generation probe-dispatch accounting (ISSUE 14): the short-circuit
# contract is ONE batched probe dispatch per cluster-state generation on
# short-circuited rounds — `python -m perf global` reads the max over its
# run (`max_dispatches_per_generation`) and bench.py hard-gates it; the
# seeded slow tests pin it directly. Bounded LRU so a long-lived process
# never grows the log without limit.
_DISPATCH_GEN_CAP = 4096
DISPATCHES_BY_GEN: "OrderedDict[int, int]" = OrderedDict()


def note_probe_dispatch(generation) -> None:
    """One probe-dispatch invocation (prefix, single, or joint rows)
    against a bundle at ``generation`` — called by
    :meth:`DisruptionSnapshot.dispatch`, the one funnel every
    consolidation counterfactual batch runs through."""
    n = DISPATCHES_BY_GEN.pop(generation, 0)
    DISPATCHES_BY_GEN[generation] = n + 1
    while len(DISPATCHES_BY_GEN) > _DISPATCH_GEN_CAP:
        DISPATCHES_BY_GEN.popitem(last=False)


def reset_dispatch_log() -> None:
    DISPATCHES_BY_GEN.clear()


def max_dispatches_per_generation() -> int:
    return max(DISPATCHES_BY_GEN.values(), default=0)


@functools.lru_cache(maxsize=8)
def _batched_kernel(max_bins: int, max_minv: int = 0):
    import jax

    from karpenter_tpu.ops import kernels

    def probe(args):
        # max_minv is threaded statically: solve_step's host-side read of
        # m_minv cannot run on a tracer under this jit/vmap
        out = kernels.solve_step(args, max_bins=max_bins, use_pallas=False,
                                 max_minv=max_minv)
        # PER-GROUP placed counts, not a scalar: feasibility is "all the
        # candidates' pods land", and pods within a group are spec-
        # identical (interchangeable), so group-wise `placed >= the
        # candidates' contribution` is exact — a scalar total cannot tell
        # a stuck PENDING pod (which the reference's all_pods_scheduled
        # ignores) from a stuck candidate pod (which blocks the command)
        placed_g = out["assign"].sum(axis=1) + out["assign_e"].sum(axis=1)
        return placed_g, out["used"].sum()

    # g_count and e_avail carry the batch axis; everything else broadcasts
    def batched(varying, shared):
        def one(v):
            return probe({**shared, **v})

        return jax.vmap(one)(varying)

    return jax.jit(batched)


class DisruptionSnapshot:
    """One tensorized cluster view shared by a whole disruption round.

    Holds the solver inputs, the existing-node axis, the master device
    snapshot over (pending pods + every probeable candidate's reschedulable
    pods), and the per-pod group index that lets each probe derive its
    counterfactual ``g_count`` rows without re-tensorizing."""

    def __init__(self, generation, build_key, inputs, pending, enodes,
                 col_by_pid, unprobeable, plan, snap, esnap, gidx_of, base,
                 topology=None, daemons=(), deleting_pods=()):
        self.generation = generation
        self.build_key = set(build_key)  # build-candidate provider ids
        self.inputs = inputs  # (templates, its_by_pool, overhead, limits, domains)
        self.pending = pending
        # the esnap's node list IS the prototype list — one list, kept
        # row-aligned by apply_delta, so sims and dispatches agree on rows
        self.enodes = esnap.nodes if esnap is not None else enodes
        self.col_by_pid = col_by_pid  # provider_id -> existing-node column
        self.unprobeable = unprobeable  # provider ids the probe cannot express
        self.plan = plan
        self.snap = snap
        self.esnap = esnap
        self.gidx_of = gidx_of  # pod uid -> group index
        self.base = base  # [G] i32: pending-pod counts (every counterfactual's floor)
        self.topology = topology  # build-time Topology (prototype plumbing)
        self.daemons = list(daemons)  # daemonset pod templates at build
        self.deleting_pods = list(deleting_pods)  # reschedulable pods of
        # deleting/marked nodes (pre-provision targets, helpers.go:340)
        # scheduling signature -> group row, for delta-registering pods the
        # build never saw and for mapping sub-solve groups onto this axis
        self.sig_to_group = {}
        for g, pods_g in enumerate(snap.groups):
            p0 = pods_g[0]
            sig = p0.__dict__.get("_sig_cache")
            if sig is None and plan is None:
                sig = interned_signature(p0)
            if sig is not None:
                self.sig_to_group.setdefault(sig, g)
        self.base = self._with_deleting(self.base)
        self.max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
        # cheapest AVAILABLE offering across the whole catalog: the lower
        # bound of any replacement claim's launch price, used by the probes'
        # price prefilter (it under-estimates the true replacement price —
        # compatibility can only raise it — so pruning on it is sound)
        avail_prices = snap.off_price[snap.off_avail]
        self.min_price = float(avail_prices.min()) if avail_prices.size else float("inf")
        self._shared = None
        self._dims = None
        self._claimable = None
        # vectorized-formulation row cache (ISSUE 14): per existing-node
        # row, the reschedulable-pod contribution over the group axis —
        # built lazily from the bundle's own node snapshots, row-
        # invalidated by delta advances, and GATHERED by contribs_for so
        # a 2k-candidate formulation is one fancy-index instead of a
        # Python loop over every candidate pod (the loop stays as the
        # oracle, KARPENTER_GLOBAL_FORMULATE_LOOP=1)
        self._contrib_rows = None  # [E, G] int32
        self._contrib_ok = None  # [E] bool: every pod of the row mapped
        self._contrib_built = None  # [E] bool: row computed since dirty
        # _prefix_criterion's static half: cheapest available offering
        # price per instance-type name (the tensorized offering tables
        # never mutate within a bundle's lifetime — catalog flips arrive
        # via rebuilds, and probe answers are seeds either way)
        self._type_prices = None
        # why the most recent delta-advance attempt declined (the
        # snapshot.advance decision ledger's rebuild reason — one of the
        # site's closed-enum causes, obs/decisions.py)
        self.advance_refusal: str | None = None

    def columns_for(self, candidates):
        """Existing-node columns for the queried candidates; None when any
        of them is invisible or inexpressible (caller stays sequential)."""
        cols = []
        for c in candidates:
            col = self.col_by_pid.get(c.provider_id)
            if col is None:
                return None
            cols.append(col)
        return cols

    def contribs_for(self, candidates, cols=None):
        """[N,G] per-candidate reschedulable-pod counts over the snapshot's
        group axis; None when a pod is missing from the snapshot (a stale
        view the generation key should have caught — stay sequential).

        The default path GATHERS rows from the bundle's cached [E,G]
        contribution matrix — built lazily from the bundle's own node
        snapshots (same generation as the candidates, so the same pod
        sets) and row-invalidated by delta advances, which is what lets a
        delta-advanced snapshot reuse the prior round's formulation rows.
        ``cols`` optionally carries an already-resolved ``columns_for``
        result so the three probe entry points don't pay the lookup
        twice. Any candidate without a cached row (invisible to the
        bundle, or a row whose pods failed to map) falls back to
        ``_contribs_loop`` — the original per-candidate loop, bit-exact by
        definition and forced everywhere by
        ``KARPENTER_GLOBAL_FORMULATE_LOOP=1`` (the parity oracle)."""
        if _formulate_loop():
            return self._contribs_loop(candidates)
        if cols is None:
            cols = self.columns_for(candidates)
        if cols is None:
            return self._contribs_loop(candidates)
        rows = np.asarray(cols, dtype=np.intp)
        self._ensure_contrib_rows(rows)
        if not self._contrib_ok[rows].all():
            return self._contribs_loop(candidates)
        return self._contrib_rows[rows]

    def _contribs_loop(self, candidates):
        """The original per-candidate Python loop — the vectorized
        gather's bit-exactness oracle, and the fallback whenever a
        candidate falls outside the cached matrix."""
        G = self.snap.G
        contrib = np.zeros((len(candidates), G), dtype=np.int32)
        for j, c in enumerate(candidates):
            for p in c.reschedulable_pods:
                g = self.gidx_of.get(p.uid)
                if g is None:
                    return None
                contrib[j, g] += 1
        return contrib

    def _ensure_contrib_rows(self, rows):
        """Materialize the cached contribution rows the gather needs.
        Each row is computed ONCE from the bundle's node snapshot (the
        same pod set a same-generation Candidate carries) and reused
        until a delta advance dirties it."""
        E, G = self.esnap.E, self.snap.G
        if self._contrib_rows is None or len(self._contrib_rows) < E:
            old_rows, old_ok, old_built = (
                self._contrib_rows, self._contrib_ok, self._contrib_built)
            self._contrib_rows = np.zeros((E, G), dtype=np.int32)
            self._contrib_ok = np.zeros(E, dtype=bool)
            self._contrib_built = np.zeros(E, dtype=bool)
            if old_rows is not None:
                k = len(old_rows)
                self._contrib_rows[:k] = old_rows
                self._contrib_ok[:k] = old_ok
                self._contrib_built[:k] = old_built
        for r in np.unique(rows[~self._contrib_built[rows]]):
            self._build_contrib_row(int(r))

    def _build_contrib_row(self, r):
        row = self._contrib_rows[r]
        row[:] = 0
        ok = True
        for p in self.enodes[r].state_node.reschedulable_pods():
            g = self.gidx_of.get(p.uid)
            if g is None:
                ok = False  # unmapped pod: the loop oracle answers None
                break
            row[g] += 1
        self._contrib_ok[r] = ok
        self._contrib_built[r] = True

    def _contrib_invalidate(self, pids):
        """Mark rows dirty after a delta advance: the next gather
        recomputes exactly these rows and keeps every other one."""
        if self._contrib_built is None:
            return
        E = self.esnap.E
        if len(self._contrib_built) < E:
            grow = E - len(self._contrib_built)
            self._contrib_rows = np.concatenate(
                [self._contrib_rows,
                 np.zeros((grow, self.snap.G), dtype=np.int32)])
            self._contrib_ok = np.concatenate(
                [self._contrib_ok, np.zeros(grow, dtype=bool)])
            self._contrib_built = np.concatenate(
                [self._contrib_built, np.zeros(grow, dtype=bool)])
        for pid in pids:
            r = self.esnap.row_of.get(pid)
            if r is not None:
                self._contrib_built[r] = False

    def type_price_vectors(self):
        """``(p_cat, name_idx)``: cheapest AVAILABLE offering price per
        instance-type NAME over the snapshot's catalog — the static half
        of :func:`_prefix_criterion`'s same-type ladder, cached on the
        bundle so every probe invocation stops re-scanning the T-axis."""
        if self._type_prices is None:
            self._type_prices = _type_price_vectors(self.snap)
        return self._type_prices

    def claimable_groups(self):
        """[G] bool — groups a fresh claim could ever be opened for
        (template compat + requirement overlap + fit net of daemon
        overhead + an available offering inside the group's allowed
        zone/capacity-type sets), or None when G×T is too large to prove
        cheaply. The prefix ladder uses it to mirror the simulation's
        claim accounting exactly: an UNclaimable pod can never consume the
        one fresh claim (the sim ignores it when it lands nowhere —
        SimulationResults.all_pods_scheduled), so requiring its placement
        would only under-estimate k. Mis-classifying a claimable group as
        unclaimable over-estimates feasibility, which the confirming
        simulation catches — the safe direction."""
        if self._claimable is None:
            s = self.snap
            G, T = s.G, s.T
            if G == 0 or T == 0:
                self._claimable = np.zeros(G, dtype=bool)
            elif G * T > (1 << 18):
                return None  # too big to prove; callers hedge instead
            else:
                compat = _group_type_compat(s)  # [G,T]
                alloc_eff = s.t_alloc - s.m_overhead[s.t_tmpl]
                fit = (
                    s.g_demand[:, None, :] <= alloc_eff[None, :, :] + 1e-6
                ).all(-1)
                self._claimable = (compat & fit).any(1)
        return self._claimable

    def _with_deleting(self, base):
        """Pending baseline plus drain-in-flight pods: the real simulation
        pre-provisions deleting/marked nodes' pods (helpers.go:340) and
        their claims count toward the m→1 rule, so a probe baseline that
        ignored them read feasible mid-drain and burned a binary search
        per disagreement. Pods whose signature maps to no group are simply
        not counted — the probe then over-estimates for the round and the
        confirming simulation catches it, never the reverse."""
        if self.plan is not None or not self.deleting_pods:
            return base
        base = base.copy()
        for p in self.deleting_pods:
            g = self.sig_to_group.get(interned_signature(p))
            if g is not None:
                base[g] += 1
        return base

    # -- delta maintenance (tensorize.py "Existing-node delta contract") --

    def _make_enode(self, state_node, store):
        """One ExistingNode prototype from live state — the per-node body
        of provisioner._existing_nodes, for dirty/added rows."""
        from karpenter_tpu.models.existing import ExistingNode
        from karpenter_tpu.scheduling import daemon_schedulable, label_requirements
        from karpenter_tpu.utils import resources as resutil

        sn = state_node.snapshot()
        taints = sn.taints()
        node_reqs = label_requirements(sn.labels()) if self.daemons else None
        daemon_resources: dict = {}
        for p in self.daemons:
            if daemon_schedulable(p, taints, node_reqs):
                daemon_resources = resutil.merge(
                    daemon_resources, p.effective_requests())
        return ExistingNode(sn, self.topology, daemon_resources, kube=store)

    def advance(self, cluster, store, deltas, generation, registry=None) -> bool:
        """Patch this bundle to `generation` from the cluster's structured
        delta journal instead of rebuilding. Returns False when any delta
        is inexpressible — opaque entries, a pod whose signature matches no
        tensorized group, topology-compiled plans, nodepool limits (usage
        drifts with node churn), a journal gap, or a churn so large a
        rebuild is cheaper — and the caller re-tensorizes from scratch."""
        with obs.span("snapshot.advance", kind="cache",
                      deltas=len(deltas)) as sp:
            ok = self._advance(cluster, store, deltas, generation, registry)
            if sp is not None:
                sp.attrs["applied"] = ok
            return ok

    def _advance(self, cluster, store, deltas, generation, registry) -> bool:
        from karpenter_tpu.utils import pod as pod_util

        self.advance_refusal = None
        if self.plan is not None or self.topology is None:
            self.advance_refusal = "plan"
            return False
        if self.inputs[3]:
            # nodepool limits are remaining = spec - usage: every node
            # add/delete moves usage, and the cached inputs would go stale
            self.advance_refusal = "limits"
            return False
        dirty_pids: set = set()
        pod_events = []
        for d in deltas:
            if d is None:
                self.advance_refusal = "opaque-entry"
                return False  # opaque: nodepool/daemonset/resync
            if d[0] == "node":
                dirty_pids.add(d[1])
            else:  # ("pod", pod, node_name | None, gone)
                pod_events.append(d)

        # pods first: register new/refreshed pods onto the group axis (so
        # contribs_for keeps working for rebound replicas) and attribute
        # their nodes as dirty
        for _, pod, node_name, gone in pod_events:
            if node_name:
                sn = cluster.node_by_name(node_name)
                if sn is not None:
                    dirty_pids.add(sn.provider_id)
                # a vanished node has its own ("node", pid) entry
            if gone:
                continue
            if not device_basic_eligible(pod):
                if not node_name:
                    self.advance_refusal = "ineligible-pending"
                    return False  # pending pods must stay expressible
                sn = cluster.node_by_name(node_name)
                if sn is not None:
                    # the candidate's pods left the device vocabulary:
                    # queries naming it fall back to the sequential scan,
                    # exactly like an unprobeable candidate at build
                    self.unprobeable.add(sn.provider_id)
                    self.col_by_pid.pop(sn.provider_id, None)
                continue
            g = self.sig_to_group.get(interned_signature(pod))
            if g is None:
                self.advance_refusal = "unseen-signature"
                return False  # unseen scheduling shape: new group/vocab
            self.gidx_of[pod.uid] = g

        # node rows: rebuild dirty, append new, mask gone/ineligible
        esnap = self.esnap
        removed, dirty_nodes, added_nodes, added_pids = [], [], [], []
        for pid in dirty_pids:
            sn = cluster.node_for(pid)
            eligible = sn is not None and not (
                sn.marked_for_deletion or sn.deleting())
            row = esnap.row_of.get(pid)
            if eligible:
                en = self._make_enode(sn, store)
                if row is None:
                    added_nodes.append(en)
                    added_pids.append(pid)
                else:
                    dirty_nodes.append(en)  # revives masked rows too
            else:
                if row is not None and esnap.live[row]:
                    removed.append(pid)
                self.col_by_pid.pop(pid, None)
        # removals are cheap in-place masks (no row rebuild, no splice),
        # so an eviction wave's drained nodes never count against the
        # delta budget — only rows that must actually re-tensorize do.
        # Counting removals here used to force a full rebuild once per
        # drain wave, exactly the 0.6 s the fused round reclaims
        # (deploy/README.md "Fused cluster round").
        churn = len(dirty_nodes) + len(added_nodes)
        if churn > max(16, esnap.E // 2):
            self.advance_refusal = "churn"
            return False  # a wave: rebuilding also re-compacts the E axis
        t_delta = time.perf_counter()
        esnap.apply_delta(
            self.snap, dirty=dirty_nodes, removed=removed, added=added_nodes,
            registry=registry,
        )
        GLOBAL_STATS["tensorize_delta_ms"] += (
            time.perf_counter() - t_delta) * 1000.0
        # formulation rows ride the delta too: exactly the touched rows
        # recompute on next gather, every other row is reused verbatim
        self._contrib_invalidate(
            [en.state_node.provider_id for en in dirty_nodes]
            + added_pids + removed)
        for pid in added_pids:
            self.col_by_pid[pid] = esnap.row_of[pid]
            self.build_key.add(pid)
        for en in dirty_nodes:
            pid = en.state_node.provider_id
            if pid not in self.unprobeable:
                self.col_by_pid[pid] = esnap.row_of[pid]

        # pending baseline + pre-provision targets, from live state
        pending = [p for p in store.list("pods") if pod_util.is_provisionable(p)]
        base = np.zeros(self.snap.G, dtype=np.int32)
        for p in pending:
            g = self.gidx_of.get(p.uid)
            if g is None:
                self.advance_refusal = "unseen-pending"
                return False  # a pod the journal never surfaced
            base[g] += 1
        self.pending = pending
        self.deleting_pods = [
            p
            for sn in cluster.state_nodes()
            if sn.marked_for_deletion or sn.deleting()
            for p in sn.reschedulable_pods()
        ]
        self.base = self._with_deleting(base)
        self.generation = generation
        self._shared = None  # padded-arg cache carries esnap rows
        return True

    # -- simulation fast path (helpers.simulate_scheduling) --------------

    def sim_enodes(self, excluded):
        """Prototype ExistingNodes for a counterfactual excluding the given
        provider ids, row-ordered; None when an excluded candidate is
        unknown to this bundle (the caller runs the slow path). Masked rows
        (nodes that left the fleet) and the excluded candidates drop out —
        exactly the `cluster minus candidates` view helpers.go:51 builds."""
        row_of, live = self.esnap.row_of, self.esnap.live
        for pid in excluded:
            if pid not in row_of:
                return None
        return [
            en
            for r, en in enumerate(self.enodes)
            if live[r] and en.state_node.provider_id not in excluded
        ]

    def sim_deleting_pods(self, seen):
        """Reschedulable pods of deleting/marked nodes not already in the
        sim's pod set (provisioner.deleting_node_pods over the cached
        view)."""
        return [p for p in self.deleting_pods if p.uid not in seen]

    def derive_esnap(self, sim_snap, existing_nodes):
        """ExistingSnapshot for a sub-solve, derived from this bundle's
        rows instead of an O(E×G) re-tensorize. Sound only within one
        cluster-state generation (the caller gates on that): every node
        must map to a live row and every sim group must map — by scheduling
        signature, which fixes its tensors — onto this snapshot's group
        axis over the SAME interned vocabulary. Returns None when any of
        that fails and the caller pays the full build."""
        base_snap, base = self.snap, self.esnap
        if self.plan is not None:
            return None
        if (
            sim_snap.keys != base_snap.keys
            or sim_snap.resources != base_snap.resources
            or sim_snap.W != base_snap.W
            or sim_snap.vocab != base_snap.vocab
        ):
            return None
        rows = []
        for en in existing_nodes:
            r = base.row_of.get(en.state_node.provider_id)
            if r is None or not base.live[r]:
                return None
            rows.append(r)
        gsel = []
        for pods_g in sim_snap.groups:
            g = self.sig_to_group.get(interned_signature(pods_g[0]))
            if g is None:
                return None
            gsel.append(g)
        rows = np.asarray(rows, dtype=np.intp)
        gsel = np.asarray(gsel, dtype=np.intp)
        return ExistingSnapshot(
            nodes=list(existing_nodes),
            e_avail=base.e_avail[rows],
            ge_ok=base.ge_ok[np.ix_(gsel, rows)],
            e_npods=base.e_npods[rows],
            e_scnt=base.e_scnt[rows],
            e_decl=base.e_decl[rows],
            e_match=base.e_match[rows],
            e_aff=base.e_aff[rows],
        )

    def _shared_args(self):
        if self._shared is None:
            # pure power-of-two ladder (no 3·2^k steps): the solver's finer
            # ladder trades compiles for scan width, but the probe re-keys
            # its XLA compile on every fleet-size family and a consolidating
            # fleet walks DOWN through them (1000 → 334 nodes crosses 4 fine
            # buckets but only 2 power-of-two ones) — compile count, not
            # padded-scan width, dominates the probe's wall clock
            Gp = _pow2(self.snap.G)
            Ep = _pow2(self.esnap.E)
            Tp = _pow2(self.snap.T)
            # NOTE: kernel_args is the assembly point shared with
            # models/solver.py — a field missed there weakens both paths at
            # once and the lockstep test catches it
            self._shared = kernel_args(
                self.snap, self.esnap, Gp=Gp, Tp=Tp, Ep=Ep,
                include_counts=False,
            )
            self._dims = (Gp, Ep)
        return self._shared, self._dims

    def dispatch(self, g_count_k, e_zero_cols, seam="probe.dispatch",
                 max_bins=1):
        """Run the batched pack kernel over the counterfactual rows; returns
        (placed_g, used) — per-row PER-GROUP placed-pod counts (shape
        [rows, Gp]) and per-row fresh-claim counts. ``max_bins`` caps how
        many fresh claims a row may open: 1 is the reference's m->1 rule;
        the joint REPLACE program passes ``_replace_max_claims()``.
        ``seam`` names the
        replay-capture seam the dispatch records under (the per-candidate
        probes use ``probe.dispatch``; the global joint ladder records the
        same tensor layout under ``global.dispatch`` so an anomalous joint
        round replays through the identical chunked program).

        ``e_zero_cols[i]`` holds the existing-node columns row i removes
        from the cluster; counterfactual ``e_avail`` rows materialize
        chunk-locally from the master tensor — never the full [rows, E, R]
        array host-side, which an uncapped single-node batch over a large
        fleet would blow into hundreds of MB before the first dispatch.
        Rows are chunked (and the chunk axis padded on the same pure pow-2
        ladder as the snapshot axes) so the batch stays inside a handful of
        compiled shape families. Small-work snapshots route through the C++
        engine under the solver's routing gate (models/solver.py
        NATIVE_CUTOFF_PODS stance): few-group batches are short sequential
        loops the native engine finishes without paying an XLA compile per
        fleet-size family."""
        # per-generation invocation accounting: the short-circuit contract
        # (one probe dispatch per generation) is read off this log
        note_probe_dispatch(self.generation)
        if self._native_routable():
            try:
                return self._dispatch_native(g_count_k, e_zero_cols, seam,
                                             max_bins=max_bins)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "native probe dispatch failed; using the XLA kernel",
                    exc_info=True)
        shared, (Gp, Ep) = self._shared_args()
        rows = g_count_k.shape[0]
        with obs.span("probe.dispatch", rows=rows, engine="device"):
            placed_g, used = dispatch_counterfactual_rows(
                shared, Gp, Ep, self.esnap.e_avail, self.max_minv,
                g_count_k, e_zero_cols, max_bins=max_bins)
        self._capture(shared, Gp, Ep, g_count_k, e_zero_cols, placed_g,
                      used, "device", seam)
        return placed_g, used

    def _capture(self, shared, Gp, Ep, g_count_k, e_zero_cols, placed_g,
                 used, engine, seam="probe.dispatch"):
        """Replay capture of this probe dispatch (obs/capsule.py): the
        shared snapshot by reference plus the counterfactual rows and
        their zeroed-column sets (flattened idx+len, None rows as -1) —
        everything ``dispatch_counterfactual_rows`` needs to re-execute
        the exact same chunked program offline."""
        from karpenter_tpu.obs import capsule as _capsule

        if not _capsule.capture_enabled():
            return
        lens = np.array(
            [-1 if c is None else len(c) for c in e_zero_cols],
            dtype=np.int64)
        parts = [np.asarray(c, dtype=np.int64).ravel()
                 for c in e_zero_cols if c is not None and len(c)]
        idx = (np.concatenate(parts) if parts
               else np.zeros(0, dtype=np.int64))
        inputs = dict(shared)
        inputs[_capsule.CF_PREFIX + "g_count_rows"] = np.asarray(g_count_k)
        inputs[_capsule.CF_PREFIX + "e_avail"] = np.asarray(
            self.esnap.e_avail)
        inputs[_capsule.CF_PREFIX + "e_zero_idx"] = idx
        inputs[_capsule.CF_PREFIX + "e_zero_len"] = lens
        _capsule.record_capture(
            seam, inputs,
            {"placed_g": placed_g, "used": used},
            engine=engine, max_minv=self.max_minv, Gp=Gp, Ep=Ep)

    def _native_routable(self) -> bool:
        """The solver's engine-routing gate applied to the probe: the same
        KARPENTER_NATIVE_CUTOFF master switch (0 disables all routing, so
        tests keep the XLA path under coverage) and the same feasibility-
        work floor — a probe row's parallelism is G×T, and below the floor
        the accelerator (or its CPU emulation) can't amortize dispatch and
        compile."""
        from karpenter_tpu.models.solver import DEVICE_MIN_WORK, _native_cutoff
        from karpenter_tpu.utils.envknobs import env_int

        if _native_cutoff() <= 0:
            return False
        min_work = env_int("KARPENTER_DEVICE_MIN_WORK", DEVICE_MIN_WORK)
        if self.snap.G * self.snap.T >= min_work:
            return False
        try:
            from karpenter_tpu import native

            return native.available()
        except Exception:
            return False

    def _dispatch_native(self, g_count_k, e_zero_cols,
                         seam="probe.dispatch", max_bins=1):
        """One native call per chunk (ROADMAP's open lever closed): the C++
        engine builds feasibility once per chunk and packs every
        counterfactual row in-process, returning only the per-row
        reductions — the old path re-entered the engine (and re-derived
        F/template overlap, and marshalled the full snapshot) once per row."""
        shared, (Gp, Ep) = self._shared_args()
        rows = g_count_k.shape[0]
        with obs.span("probe.dispatch", rows=rows, engine="native"):
            placed_g, used = dispatch_counterfactual_rows_native(
                shared, Gp, Ep, self.esnap.e_avail, self.max_minv,
                g_count_k, e_zero_cols, max_bins=max_bins)
        self._capture(shared, Gp, Ep, g_count_k, e_zero_cols, placed_g,
                      used, "native", seam)
        return placed_g, used


def dispatch_counterfactual_rows(shared, Gp, Ep, e_avail, max_minv,
                                 g_count_k, e_zero_cols, e_free=None,
                                 max_bins=1):
    """The XLA probe dispatch over EXPLICIT tensors: chunked at
    PROBE_CHUNK_ROWS, the chunk axis padded on the pow-2 ladder, each
    chunk one vmapped device call. ONE body shared by
    ``DisruptionSnapshot.dispatch``, the preemption counterfactual
    (admission/preempt.py), and the replay capsule's offline probe replay
    (obs/capsule.py) — sharing the code is what makes the replay bit-exact
    by construction instead of by re-implementation.

    ``e_free`` (optional, len == rows) carries per-row capacity RELEASES:
    ``None`` or ``(col, delta[R])`` meaning row i sees ``e_avail[col]``
    grown by ``delta`` — the preemption counterfactual's "these victims
    are evicted" row shape, applied after the zeroed columns so the two
    edits compose the same way on every engine."""
    R = e_avail.shape[1]
    rows = g_count_k.shape[0]
    placed_g = np.empty((rows, Gp), dtype=np.int64)
    used = np.empty(rows, dtype=np.int64)
    for lo in range(0, rows, PROBE_CHUNK_ROWS):
        hi = min(lo + PROBE_CHUNK_ROWS, rows)
        n = hi - lo
        Np = _pow2(n, lo=4)
        e_chunk = np.repeat(e_avail[None, :, :], n, axis=0)
        for i in range(n):
            cols = e_zero_cols[lo + i]
            if cols is not None and len(cols):
                e_chunk[i, cols, :] = 0.0
            fr = e_free[lo + i] if e_free is not None else None
            if fr is not None:
                e_chunk[i, int(fr[0]), :] += np.asarray(
                    fr[1], dtype=e_chunk.dtype)
        varying = dict(
            g_count=pad(g_count_k[lo:hi], (Np, Gp)),
            e_avail=pad(e_chunk, (Np, Ep, R)),
        )
        # pow-2 row-ladder waste of this chunk (real counterfactual
        # rows vs the padded batch axis the kernel vmapped over)
        devplane.record_padding("probe.rows", n, Np)
        # dispatch + host pull in one device-kind leaf: the probe
        # kernel is synchronous-by-consumption (np.asarray blocks)
        with obs.span("probe.kernel", kind="device", rows=n):
            kfn = _batched_kernel(max_bins, max_minv)
            t0 = time.perf_counter()
            out_placed, out_used = kfn(varying, shared)
            # first sight of this (row axis, snapshot shapes)
            # family paid its XLA compile inside the call above;
            # the key mirrors the solver's base_key dims — R and
            # the mask widths change the compiled program even
            # when the padded axes do not (max_bins: the REPLACE
            # row shape is its own compiled family)
            devplane.record_dispatch(
                "probe.kernel",
                (Np, shared["g_mask"].shape, shared["t_mask"].shape,
                 Ep, R, max_minv, max_bins),
                time.perf_counter() - t0)
            placed_g[lo:hi] = np.asarray(out_placed)[:n]
            used[lo:hi] = np.asarray(out_used)[:n]
    return placed_g, used


def dispatch_counterfactual_rows_native(shared, Gp, Ep, e_avail, max_minv,
                                        g_count_k, e_zero_cols, e_free=None,
                                        max_bins=1):
    """The native-engine half of :func:`dispatch_counterfactual_rows` —
    same chunking, same counterfactual materialization (zeroed columns,
    then per-row ``e_free`` releases), the C++ batched probe entry per
    chunk. ``max_minv`` rides only for capture symmetry (the native entry
    reads m_minv from the arg dict itself)."""
    from karpenter_tpu import native

    R = e_avail.shape[1]
    rows = g_count_k.shape[0]
    placed_g = np.empty((rows, Gp), dtype=np.int64)
    used = np.empty(rows, dtype=np.int64)
    for lo in range(0, rows, NATIVE_PROBE_CHUNK_ROWS):
        hi = min(lo + NATIVE_PROBE_CHUNK_ROWS, rows)
        n = hi - lo
        e_chunk = np.repeat(e_avail[None, :, :], n, axis=0)
        for i in range(n):
            cols = e_zero_cols[lo + i]
            if cols is not None and len(cols):
                e_chunk[i, cols, :] = 0.0
            fr = e_free[lo + i] if e_free is not None else None
            if fr is not None:
                e_chunk[i, int(fr[0]), :] += np.asarray(
                    fr[1], dtype=e_chunk.dtype)
        with obs.span("probe.native", kind="device", rows=n):
            pg, u = native.solve_probe_batch(
                shared,
                pad(np.asarray(g_count_k[lo:hi], dtype=np.int32),
                    (n, Gp)),
                pad(e_chunk.astype(np.float32, copy=False),
                    (n, Ep, R)),
                max_bins,
            )
        placed_g[lo:hi] = pg
        used[lo:hi] = u
    return placed_g, used


def build_disruption_snapshot(provisioner, cluster, store, candidates):
    """Assemble the shared tensor bundle for one disruption round. Returns
    None when the device path cannot express the scenario at all (the
    probes then fall back to the sequential search)."""
    with obs.span("snapshot.build", kind="cache",
                  candidates=len(candidates)):
        return _build_disruption_snapshot(
            provisioner, cluster, store, candidates)


def _build_disruption_snapshot(provisioner, cluster, store, candidates):
    try:
        import jax  # noqa: F401
    except Exception:
        return None
    from karpenter_tpu.utils import pod as pod_util

    generation = cluster.consolidation_state()
    pending = [p for p in store.list("pods") if pod_util.is_provisionable(p)]
    if any(not device_basic_eligible(p) for p in pending):
        return None  # every counterfactual row must hold the pending pods

    # candidates whose pods the kernel can't express are dropped from the
    # bundle (not fatal): queries naming them fall back to the sequential
    # search, everyone else still rides the shared snapshot
    probeable, unprobeable = [], set()
    for c in candidates:
        pods = list(c.reschedulable_pods)
        if any(not device_basic_eligible(p) for p in pods):
            unprobeable.add(c.provider_id)
        else:
            probeable.append((c, pods))
    all_pods = pending + [p for _, ps in probeable for p in ps]
    if not all_pods:
        return None

    templates, its_by_pool, overhead, limits, domains = provisioner.solver_inputs()
    if not templates:
        return None

    # counterfactual topology: all candidate pods excluded from the cluster
    # domain counts (helpers.go:51's excluded-pod stance, applied across
    # every counterfactual at once)
    from karpenter_tpu.controllers.provisioning.provisioner import ClusterStateView
    from karpenter_tpu.models.topology import Topology
    from karpenter_tpu.ops import waves

    view = ClusterStateView(cluster, store)
    topology = Topology(cluster=view, domains=domains, pods=all_pods)

    state_nodes = list(cluster.nodes())
    enodes = provisioner._existing_nodes(state_nodes, topology)
    by_pid = {e.state_node.provider_id: i for i, e in enumerate(enodes)}
    col_by_pid = {}
    for c, _ in probeable:
        i = by_pid.get(c.provider_id)
        if i is None:
            unprobeable.add(c.provider_id)  # invisible to the probe
        else:
            col_by_pid[c.provider_id] = i

    plan = None
    if topology.has_groups:
        plan = waves.compile_topology(group_by_signature(all_pods), topology)
        if plan.host_pods:
            return None  # waves-inexpressible shape: stay sequential

    snap = tensorize(
        all_pods if plan is None else None, templates, its_by_pool,
        daemon_overhead=overhead, limits=limits or None, device_plan=plan,
    )
    if snap.G == 0:
        return None
    esnap = tensorize_existing(
        snap, enodes, plan, registry=getattr(provisioner, "registry", None))

    gidx_of = {}
    for g, pods_g in enumerate(snap.groups):
        for p in pods_g:
            gidx_of[p.uid] = g
    # pending pods join every counterfactual row (they contend for capacity
    # exactly as in the real simulation), but feasibility is judged PER
    # GROUP against the candidates' contribution only — a pending pod that
    # cannot schedule anywhere (and would not block the sequential path,
    # which only requires the candidates' pods to land —
    # SimulationResults.all_pods_scheduled) cannot poison the batch
    base = np.zeros(snap.G, dtype=np.int32)
    for p in pending:
        base[gidx_of[p.uid]] += 1

    return DisruptionSnapshot(
        generation=generation,
        build_key=frozenset(c.provider_id for c in candidates),
        inputs=(templates, its_by_pool, overhead, limits, domains),
        pending=pending,
        enodes=enodes,
        col_by_pid=col_by_pid,
        unprobeable=unprobeable,
        plan=plan,
        snap=snap,
        esnap=esnap,
        gidx_of=gidx_of,
        base=base,
        topology=topology,
        daemons=[
            ds.template for ds in store.list("daemonsets")
            if ds.template is not None
        ],
        deleting_pods=[
            p
            for sn in state_nodes
            if sn.marked_for_deletion or sn.deleting()
            for p in sn.reschedulable_pods()
        ],
    )


class SnapshotCache:
    """Round-scoped memo of the latest :class:`DisruptionSnapshot`, keyed
    on the cluster-state generation (see the module docstring for the full
    invalidation contract). One instance lives on the DisruptionContext so
    Emptiness → MultiNode → SingleNode → validation share one
    tensorization per generation."""

    def __init__(self):
        self._bundle = None
        self._neg = None  # (generation, build_key) of a failed build
        self._last_refusal = None  # why the last delta-advance declined

    def get(self, provisioner, cluster, store, candidates, registry=None):
        from karpenter_tpu.operator import metrics as m

        generation = cluster.consolidation_state()
        key = frozenset(c.provider_id for c in candidates)
        b = self._bundle
        advanced = None
        if b is not None and b.generation == generation and key <= b.build_key:
            if registry is not None:
                registry.counter(
                    m.DISRUPTION_SNAPSHOT_CACHE_HITS,
                    "disruption probes served from the snapshot cache",
                ).inc(kind="snapshot")
            return b
        if b is not None and b.generation < generation:
            # incremental maintenance: patch the bundle from the cluster's
            # structured delta journal instead of re-tensorizing the fleet
            # (tensorize.py "Existing-node delta contract"); anything the
            # journal can't express falls through to the full rebuild below
            b2 = advanced = self._try_advance(cluster, store, generation,
                                             registry)
            if b2 is not None and key <= b2.build_key:
                from karpenter_tpu.obs import decisions

                decisions.record_decision("snapshot.advance", "delta",
                                          registry=registry)
                return b2
        if self._neg == (generation, key):
            # an inexpressible build is generation-stable: don't re-pay the
            # assembly for every method in the round. Counted under its own
            # label — a permanently-inexpressible cluster must not read as
            # a healthy snapshot cache on the scrape
            if registry is not None:
                registry.counter(
                    m.DISRUPTION_SNAPSHOT_CACHE_HITS,
                    "disruption probes served from the snapshot cache",
                ).inc(kind="negative")
            return None
        if registry is not None:
            registry.counter(
                m.DISRUPTION_SNAPSHOT_CACHE_MISSES,
                "disruption snapshot rebuilds (generation bump or wider "
                "candidate set)",
            ).inc()
        if self._bundle is not None:
            # anomaly trigger: a held bundle is being displaced by a full
            # tensorization — the delta layer declined (opaque journal
            # entry, inexpressible churn) or the candidate key widened.
            # The round's trace shows which; the first-ever build of a
            # process is NOT an anomaly (there was nothing to advance).
            # The decision ledger records the same transition with the
            # actual inexpressible-delta cause (closed enum,
            # obs/decisions.py) — a delta path quietly dying shows up as a
            # rung regression, not just a miss counter.
            from karpenter_tpu.obs import decisions

            b_old = self._bundle
            if b_old.generation >= generation or advanced is not None:
                # same-generation (or already-advanced) displacement: only
                # a wider candidate key forces the rebuild
                reason = "candidate-widened"
            else:
                reason = self._last_refusal or "journal-gap"
            decisions.record_decision("snapshot.advance", "rebuild", reason,
                                      registry=registry)
            obs.anomaly("snapshot-rebuild", registry=registry,
                        generation=generation)
        b = build_disruption_snapshot(provisioner, cluster, store, candidates)
        if b is not None:
            self._bundle = b
            self._neg = None
        else:
            self._neg = (generation, key)
        return b

    def current(self, cluster):
        """The cached bundle when still generation-current, else None —
        the gate every simulation fast-path consumer must pass: a bundle
        whose generation matches the cluster's is a faithful mirror of
        live state (every informer mutation bumps the counter)."""
        b = self._bundle
        if (
            b is not None
            and cluster is not None
            and b.generation == cluster.consolidation_state()
        ):
            return b
        return None

    def refresh(self, provisioner, cluster, store, registry=None):
        """`current`, but a stale bundle first gets one delta-advance
        attempt — NEVER a rebuild (consumers here want the fast path if
        it's cheap, not to pay a tensorization the probes didn't ask for).
        Serves the confirming simulations and the controller's validation
        round, which run between probe queries at generations the probes
        never saw."""
        if cluster is None or self._bundle is None:
            return None
        b = self._bundle
        generation = cluster.consolidation_state()
        if b.generation == generation:
            return b
        if b.generation < generation:
            b2 = self._try_advance(cluster, store, generation, registry)
            if b2 is not None:
                from karpenter_tpu.obs import decisions

                decisions.record_decision("snapshot.advance", "delta",
                                          registry=registry)
            return b2
        return None

    def _try_advance(self, cluster, store, generation, registry):
        """One delta-advance attempt on the cached bundle (shared by `get`
        and `refresh`): journal lookup → advance → delta-hit accounting.
        Returns the advanced bundle or None (opaque/inexpressible/gap)."""
        b = self._bundle
        deltas = getattr(cluster, "deltas_since", lambda g: None)(b.generation)
        if deltas is None:
            self._last_refusal = "journal-gap"
            return None
        if not b.advance(cluster, store, deltas, generation,
                         registry=registry):
            self._last_refusal = b.advance_refusal or "opaque-entry"
            return None
        self._last_refusal = None
        if registry is not None:
            from karpenter_tpu.operator import metrics as m

            registry.counter(
                m.DISRUPTION_SNAPSHOT_CACHE_HITS,
                "disruption probes served from the snapshot cache",
            ).inc(kind="delta")
        return b

    def inputs_for(self, cluster):
        """The cached solver inputs when still generation-current, else
        None — lets the confirming simulations skip re-assembling
        templates/catalog/overhead inside one disruption round. Safe
        because every structural input change bumps the generation and the
        catalog objects are shared by identity."""
        b = self.current(cluster)
        return b.inputs if b is not None else None


def _bundle_for(provisioner, cluster, store, candidates, cache, registry,
                build_candidates):
    build = build_candidates if build_candidates else list(candidates)
    if cache is not None:
        return cache.get(provisioner, cluster, store, build, registry=registry)
    return build_disruption_snapshot(provisioner, cluster, store, build)


def batched_feasible_prefix(provisioner, cluster, store, candidates,
                            cache=None, registry=None, build_candidates=None):
    """Largest k such that candidates[:k] consolidate into the remaining
    cluster plus at most one fresh claim, decided in one device call over
    the WHOLE prefix ladder (every prefix is a counterfactual row, so the
    reference's log2(k) sequential solves collapse into one dispatch).

    Returns ``(k, definitive)``: ``definitive`` says the ladder's MISSES
    may be trusted — plan-free bundles whose claim accounting provably
    mirrored the simulation's (per-group claimability proven, or no
    pending/drain pods rode the rows), where every modeled host check can
    only over-estimate feasibility; the caller then pays exactly ONE
    confirming simulation at k. Everything else (topology-compiled
    bundles, mid-flight batches too large to prove claimability for)
    hands k over as a seed the caller gallops/searches around — the
    reference's answer at the reference's cost. Returns None when the
    probe cannot express the scenario (the caller falls back to the
    sequential binary search)."""
    bundle = _bundle_for(
        provisioner, cluster, store, candidates, cache, registry,
        build_candidates,
    )
    if bundle is None:
        return None
    cols = bundle.columns_for(candidates)
    if cols is None:
        return None
    contrib = bundle.contribs_for(candidates, cols=cols)
    if contrib is None:
        return None

    base = bundle.base
    N = len(candidates)
    G = bundle.snap.G
    cum = np.cumsum(contrib, axis=0)  # [N,G]: row k = prefix k+1's candidate pods
    g_count_k = base[None, :] + cum  # pending pods contend exactly as in the real sim
    col_arr = np.asarray(cols, dtype=np.intp)
    # row k removes candidates[:k+1] (views into one array, not copies)
    e_zero_cols = [col_arr[: k + 1] for k in range(N)]

    placed_g, used = bundle.dispatch(g_count_k, e_zero_cols)
    if bundle.plan is None:
        feasible, definitive = _prefix_criterion(
            bundle, candidates, cum, placed_g, used)
    else:
        # topology ladders stay a SEED: per-group "the candidates' pods
        # land" only (a stuck pending pod must not poison the batch — the
        # waves counterfactual already makes these rows approximate)
        feasible = (placed_g[:, :G] >= cum).all(axis=1)
        definitive = False
    ks = np.flatnonzero(feasible)
    k = 0 if ks.size == 0 else int(ks[-1]) + 1
    return k, definitive


def batched_single_feasible(provisioner, cluster, store, candidates,
                            cache=None, registry=None, build_candidates=None):
    """Per-candidate consolidation feasibility, every candidate probed in
    one batched device call: counterfactual c removes ONLY candidate c and
    asks whether its reschedulable pods land on the surviving nodes plus at
    most one fresh claim.

    Returns ``(mask, definitive)`` — a bool array over ``candidates``
    (probe hits are SEEDS for the real confirming simulation) and whether
    the MISSES may be trusted: for topology-compiled bundles the waves
    counterfactual approximation can tighten the probe (module docstring),
    and unlike the prefix probe there is no binary-search recovery around a
    mis-estimated candidate, so non-definitive misses must be re-checked by
    the caller's sequential scan rather than skipped. Returns None when the
    scenario is inexpressible (the caller falls back to the sequential
    scan)."""
    bundle = _bundle_for(
        provisioner, cluster, store, candidates, cache, registry,
        build_candidates,
    )
    if bundle is None:
        return None
    cols = bundle.columns_for(candidates)
    if cols is None:
        return None
    contrib = bundle.contribs_for(candidates, cols=cols)
    if contrib is None:
        return None

    base = bundle.base
    N = len(candidates)
    g_count_k = base[None, :] + contrib  # [N,G]
    col_arr = np.asarray(cols, dtype=np.intp)
    # row c removes ONLY candidate c
    e_zero_cols = [col_arr[c : c + 1] for c in range(N)]

    placed_g, used = bundle.dispatch(g_count_k, e_zero_cols)
    mask = _single_criterion(bundle, candidates, contrib, placed_g, used)
    return mask, bundle.plan is None


def _single_criterion(bundle, candidates, contrib, placed_g, used):
    """The per-candidate feasibility criterion — ONE copy shared by
    :func:`batched_single_feasible` and the joint ladder's single-
    candidate rows (:func:`joint_retirement_plan`), so the SingleNode
    probe and the short-circuit seed can never drift on what a single
    hit means.

    Candidate c's pods all land iff every group places at least c's
    contribution (stuck pending pods are not the candidate's problem —
    all_pods_scheduled checks only candidate pods). Plan-free bundles
    additionally apply the price prefilter, mirroring the prefix probe:
    a candidate whose pods need the one fresh claim only consolidates if
    SOME available offering could launch strictly cheaper than the
    candidate costs today (an unpriceable candidate aborts the replace
    path outright); a used==0 counterfactual is a pure delete — no price
    involved. Plan-free bundles only: the kernel fills existing nodes
    before opening the fresh bin, so ``used`` is reliable there, while a
    topology bundle's tightened fit can inflate it — which is exactly why
    those misses are flagged non-definitive."""
    G = bundle.snap.G
    mask = (placed_g[:, :G] >= contrib).all(axis=1)
    if bundle.plan is None:
        prices = np.array(
            [getattr(c, "price", 0.0) for c in candidates], dtype=np.float64
        )
        mask = mask & (
            (used == 0) | ((prices > 0) & (bundle.min_price < prices))
        )
    return mask


def _prefix_criterion(bundle, candidates, cum, placed_g, used):
    """The plan-free prefix ladder's model of the host's WHOLE decision —
    shared verbatim by :func:`batched_feasible_prefix` (the per-candidate
    ladder) and :func:`joint_retirement_plan` (the global joint ladder), so
    the two paths can never drift on what "feasible" means. Returns
    ``(feasible[N], definitive)``.

    (1) every pod the simulation would open a claim for — pending and
    drain pods of CLAIMABLE groups included — must place within the
    surviving nodes plus the one fresh bin, because the reference's m→1
    rule counts the claims those pods consume too (consolidation.go:164):
    a mid-flight batch whose pending pods need their own claim can never
    confirm, and rows that ignore them burn a binary search per
    disagreement. Pods of UNclaimable groups are exempt exactly like the
    sim exempts them (a pod that can land nowhere takes no claim and
    all_pods_scheduled ignores it) — and when claimability is too large
    to prove, the ladder simply stops being definitive instead of
    guessing.

    (2) the price ladder, modeling filterByPrice AND the same-type
    anti-churn filter (filter_out_same_type): a prefix that needs the
    fresh claim only ships if some available offering is both cheaper
    than the prefix's total cost and — once ANY option type overlaps a
    deleted node — cheaper than the cheapest such node. A prefix
    containing an unpriceable candidate (price <= 0) aborts its replace
    path outright (candidate_prices' getCandidatePrices stance), which on
    the joint path degrades the selection toward the largest DELETE-ONLY
    prefix — the ADVICE.md round-5 unknown-price stance, applied
    identically on both ladders. Per-type cheapest-available prices
    under-estimate real option prices, which over-includes types on the
    OPTION side (safe) but can over-include them on the same-type CAP
    side too (a type whose only requirement-compatible offerings are
    pricier than the global cheapest would not cap the host's filter):
    the ladder's misses are therefore only DEFINITIVE when every type's
    available offerings carry one price — heterogeneous catalogs hand the
    caller a seed instead, and the gallop/search recovers.

    Misses are definitive when the claim accounting above mirrored the
    sim (claimability proven, or no pending/drain pods rode the rows at
    all). The same-type cap-side corner noted above is the one residual
    under-approximation and is benign in direction: a rare
    smaller-than-optimal command this round, re-examined at the next
    generation — never an unsafe or permanently-skipped consolidation
    (the k<2 path always escalates total misses to the reference's full
    search)."""
    base = bundle.base
    G = bundle.snap.G
    N = len(candidates)
    claimable = bundle.claimable_groups()
    if claimable is None:
        required = base[None, :] + cum
        base_exempt_ok = int(base.sum()) == 0
    else:
        required = cum + np.where(claimable[:G], base, 0)[None, :]
        base_exempt_ok = True
    feasible = (placed_g[:, :G] >= required).all(axis=1)
    prefix_known, claim_ok = _prefix_price_ok(bundle, candidates)
    feasible &= (used == 0) | (prefix_known & claim_ok)
    if _replace_max_claims() > 1:
        # joint REPLACE rows (max_bins>1): a prefix opening u>1 fresh
        # claims must still beat its retirement credit with u claims of
        # the cheapest admissible offering — a relaxed seed only; the
        # host rounding pass re-verifies the chosen split in exact
        # arithmetic and the confirming simulation owns the command
        credit = _prefix_credit(candidates)
        min_p = float(getattr(bundle, "min_price", 0.0) or 0.0)
        feasible &= (used <= 1) | (
            (min_p > 0) & (used.astype(np.float64) * min_p < credit))
    return feasible, base_exempt_ok


def _prefix_credit(candidates) -> np.ndarray:
    """[N] f64 — cumulative retirement credit of each prefix: summed
    candidate prices, discounted by ``KARPENTER_TIER_WEIGHT x`` the
    displaced priority mass (w=0 leaves the raw price sum)."""
    prices = np.array(
        [getattr(c, "price", 0.0) for c in candidates], dtype=np.float64)
    w = _tier_weight()
    if w > 0.0:
        prices = prices - w * _tier_mass(candidates)
    return np.cumsum(prices)


def _prefix_price_ok(bundle, candidates):
    """The price half of the shared criterion — filterByPrice AND the
    same-type anti-churn cap modeled per prefix (the docstring of
    :func:`_prefix_criterion` owns the full argument). ONE copy shared
    by the FFD prefix ladder and the LP relaxation rung
    (``ops/relax.py joint_relax_plan``), so a claim-bearing relax prefix
    can never ship under a price stance the ladder would refuse.
    Returns ``(prefix_known[N], claim_ok[N])``: whether every price in
    the prefix is known, and whether some offering passes both price
    gates for that prefix."""
    N = len(candidates)
    prices = np.array(
        [getattr(c, "price", 0.0) for c in candidates], dtype=np.float64
    )
    prefix_known = np.logical_and.accumulate(prices > 0)
    prefix_price = np.cumsum(prices)
    w = _tier_weight()
    if w > 0.0:
        # tier-weighted criterion (KARPENTER_TIER_WEIGHT): the credit a
        # prefix earns by retiring nodes shrinks by w x the priority
        # mass its evictions displace, so a replace only ships when the
        # offering beats the DISCOUNTED credit. w=0 is bit-identical
        # (parity-pinned like the LP rung's lambda=0); shared here so
        # the relax rung (ops/relax.py) can never drift from the ladder
        prefix_price = np.cumsum(prices - w * _tier_mass(candidates))
    tp = getattr(bundle, "type_price_vectors", None)
    p_cat, name_idx = (tp() if tp is not None
                       else _type_price_vectors(bundle.snap))
    if p_cat.size:
        # cumulative cheapest candidate price per type over the prefix —
        # one minimum.accumulate per type PRESENT among the candidates
        # (absent types stay +inf in every row); the original running-min
        # loop stays as the oracle under KARPENTER_GLOBAL_FORMULATE_LOOP
        j_arr = np.fromiter(
            (name_idx.get(
                getattr(getattr(c, "instance_type", None), "name", None),
                -1)
             for c in candidates),
            dtype=np.int64, count=N)
        if _formulate_loop():
            cheapest = _cheapest_cum_loop(prices, j_arr, len(p_cat))
        else:
            cheapest = _cheapest_cum_vec(prices, j_arr, len(p_cat))
        is_option = p_cat[None, :] < prefix_price[:, None]
        overlap = is_option & np.isfinite(cheapest)
        max_price = np.where(overlap, cheapest, np.inf).min(axis=1)
        claim_ok = (
            is_option & (p_cat[None, :] < max_price[:, None])
        ).any(axis=1)
    else:
        claim_ok = np.zeros(N, dtype=bool)
    return prefix_known, claim_ok


def _type_price_vectors(snap):
    """Module-level body of :meth:`DisruptionSnapshot.type_price_vectors`
    for callers holding a bare snapshot (test doubles, the oracle path):
    cheapest available offering price per instance-type name."""
    p_by_name: dict = {}
    for t, (_, it) in enumerate(snap.type_refs):
        avail = snap.off_price[t][snap.off_avail[t]]
        if avail.size:
            p = float(avail.min())
            if p < p_by_name.get(it.name, np.inf):
                p_by_name[it.name] = p
    p_cat = (np.fromiter(p_by_name.values(), dtype=np.float64)
             if p_by_name else np.zeros(0, dtype=np.float64))
    return p_cat, {nm: j for j, nm in enumerate(p_by_name)}


def _cheapest_cum_loop(prices, j_arr, M):
    """Oracle: the original per-candidate running-min loop over the
    prefix (cheapest already-seen candidate price per type)."""
    N = len(prices)
    cheapest = np.full((N, M), np.inf)
    cur = np.full(M, np.inf)
    for i in range(N):
        j = int(j_arr[i])
        if j >= 0 and prices[i] > 0:
            cur[j] = min(cur[j], prices[i])
        cheapest[i] = cur
    return cheapest


def _cheapest_cum_vec(prices, j_arr, M):
    """Vectorized :func:`_cheapest_cum_loop` — bit-identical by
    construction: the same float64 min over the same values in the same
    prefix order, just one ``np.minimum.accumulate`` per present type."""
    N = len(prices)
    cheapest = np.full((N, M), np.inf)
    for j in np.unique(j_arr):
        if j < 0:
            continue
        col = np.where((j_arr == j) & (prices > 0), prices, np.inf)
        cheapest[:, int(j)] = np.minimum.accumulate(col)
    return cheapest


# ---------------------------------------------------------------------------
# global consolidation: ONE joint device-solved retirement over every
# candidate (the 2k-node config) — deploy/README.md "Global consolidation"
# ---------------------------------------------------------------------------

# host rounding/repair drop budget: how many trailing candidates the
# integral pass may shed from the device ladder's relaxed selection before
# the round falls back to the per-candidate ladder (the
# KARPENTER_SHARD_REPAIR_MAX stance applied to retirement sets)
GLOBAL_REPAIR_MAX = 64

# per-process joint-solve accounting, delta'd by `python -m perf global`
# (the formulate/solve/round_repair breakdown the ISSUE-13 row emits)
GLOBAL_STATS = {
    "plans": 0,
    "rows": 0,
    "formulate_ms": 0.0,
    "solve_ms": 0.0,
    "round_repair_ms": 0.0,
    # LP relaxation rung wall clock (ops/relax.py PDHG solve + device
    # rounding window) — deploy/README.md "LP relaxation rung"
    "relax_ms": 0.0,
    "repair_drops": 0,
    # the round's shared snapshot acquisition (build or delta-advance),
    # hoisted out of formulate_ms by the controller's prewarm — ISSUE-14
    # schema note in deploy/README.md "Global consolidation"
    "bundle_ms": 0.0,
    # incremental re-tensorization wall across eviction waves: time spent
    # INSIDE ExistingSnapshot.apply_delta when SnapshotCache.advance kept
    # delta-advancing instead of rebuilding (the fused round's ~0.6 s
    # host lever — deploy/README.md "Fused cluster round")
    "tensorize_delta_ms": 0.0,
}


def _replace_max_claims() -> int:
    """KARPENTER_REPLACE_MAX_CLAIMS (default 1): how many fresh claims a
    joint retirement row may open — the REPLACE generalization of the
    reference's m->1 rule (consolidation.go:164). At 1 the program is
    bit-identical to the m->1 ladder; r>1 lets the joint selection keep
    prefixes whose displaced pods need several replacement nodes (shapes
    the m->1 rule strands), with the host rounding pass splitting the
    overflow across at most r single-template claims and the confirming
    simulation still owning the shipped command."""
    from karpenter_tpu.utils.envknobs import env_int

    return env_int("KARPENTER_REPLACE_MAX_CLAIMS", 1, minimum=1)


def _tier_weight() -> float:
    """KARPENTER_TIER_WEIGHT (default 0): discount each candidate's
    retirement credit by ``w x`` the priority mass its eviction displaces
    (the tier-weighted ``_prefix_criterion`` — higher-tier pods make
    their node proportionally less attractive to retire). 0 is
    bit-identical to the unweighted criterion, parity-pinned exactly
    like the LP rung's lambda=0."""
    from karpenter_tpu.utils.envknobs import env_float

    return env_float("KARPENTER_TIER_WEIGHT", 0.0)


def _tier_mass(candidates) -> np.ndarray:
    """[N] f64 — summed effective priority of each candidate's
    reschedulable pods (the displaced tier mass the weighted criterion
    charges against its price credit)."""
    return np.array(
        [sum((getattr(p, "priority", 0) or 0)
             for p in getattr(c, "reschedulable_pods", ()) or ())
         for c in candidates],
        dtype=np.float64)


def _global_repair_bound() -> int:
    from karpenter_tpu.utils.envknobs import env_int

    return env_int("KARPENTER_GLOBAL_REPAIR_MAX", GLOBAL_REPAIR_MAX,
                   minimum=0)


class JointPlan:
    """One global-consolidation proposal: the retirement set the joint
    device ladder selected (post rounding/repair), the integral
    displacement plan the host pass built for it, and the decision/timing
    story the perf row and the ``consolidate.global`` ledger verdict are
    written from. ``viable=False`` plans carry the fallback ``reason``
    (a ``consolidate.global`` closed-enum member) instead of a set."""

    def __init__(self, candidates, selected_idx=(), delete_only=True,
                 definitive=False, displacement=(), overflow=None,
                 n_claims=1, k_device=0, dropped=0, timings=None,
                 viable=True, reason="ok", prefix_feasible=None,
                 single_mask=None, generation=None, transient=False,
                 solver="ladder", relax_fallback=False):
        self._candidates = list(candidates)
        self.selected_idx = list(selected_idx)
        self.delete_only = delete_only
        self.definitive = definitive
        # [(provider_id, group_index, pod_count)] — where each displaced
        # pod group lands among the survivors (exact-arithmetic integral)
        self.displacement = list(displacement)
        # {group_index: pod_count} headed for the fresh claim(s) (empty
        # on delete-only plans)
        self.overflow = dict(overflow or {})
        # fresh claims the displacement plan opens: 1 is the reference's
        # m->1 rule; >1 marks a joint REPLACE command
        # (KARPENTER_REPLACE_MAX_CLAIMS — ledger reason "replace")
        self.n_claims = n_claims
        self.k_device = k_device  # the device ladder's pre-repair k
        self.dropped = dropped  # candidates shed by the repair pass
        self.timings = dict(timings or {})
        self.viable = viable
        self.reason = reason
        # short-circuit seed data (ISSUE 14): the dispatch's per-prefix
        # criterion verdicts (always present when the joint dispatch ran),
        # the per-candidate single-row mask (present when the dispatch
        # carried the single rows too), the bundle generation they were
        # solved at, and whether the snapshot was mid-transition (pending
        # or drain-in-flight pods) when it answered
        self.prefix_feasible = prefix_feasible
        self.single_mask = single_mask
        self.generation = generation
        self.transient = transient
        # which rung selected the set: "relax" (the LP relaxation rung,
        # ops/relax.py — ledger reason relax/relax-rounded) or "ladder"
        # (the FFD prefix ladder); relax_fallback marks a ladder round
        # the relax rung first attempted and declined (ledger reason
        # relax-fallback when the ladder then ships)
        self.solver = solver
        self.relax_fallback = relax_fallback

    @property
    def selected(self):
        return [self._candidates[i] for i in self.selected_idx]


class JointSeed:
    """The joint dispatch's answer re-keyed for the per-candidate probes
    (the ISSUE-14 short-circuit): the prefix criterion verdicts ARE
    MultiNode's capped question over the same disruption-cost order
    (every criterion row depends only on its own prefix), and the single
    rows — when the dispatch carried them — ARE SingleNodeConsolidation's
    per-candidate question scored by the shared ``_single_criterion``. So
    within one cluster-state generation the ladder's probes answer off
    this seed instead of re-paying a device dispatch; any state bump
    invalidates it (generation check at use time), and any
    order/membership mismatch between the querying method's candidate
    list and the seeded pool declines the seed rather than guessing."""

    def __init__(self, generation, pids, prefix_feasible, definitive,
                 single_mask):
        self.generation = generation
        self.pids = tuple(pids)
        self.prefix_feasible = np.asarray(prefix_feasible, dtype=bool)
        self.definitive = bool(definitive)
        self.single_mask = (
            None if single_mask is None
            else np.asarray(single_mask, dtype=bool))

    def valid(self, cluster) -> bool:
        return (cluster is not None
                and cluster.consolidation_state() == self.generation)

    def _aligned(self, pids) -> bool:
        n = len(pids)
        return bool(n) and tuple(pids) == self.pids[:n]

    def prefix_answer(self, pids):
        """``(k, definitive)`` for a capped prefix query over the same
        candidate order — exactly what ``batched_feasible_prefix`` would
        have dispatched — or None when the query is not a prefix of the
        seeded pool."""
        if not self._aligned(pids):
            return None
        feas = self.prefix_feasible[: len(pids)]
        ks = np.flatnonzero(feas)
        return (0 if ks.size == 0 else int(ks[-1]) + 1), self.definitive

    def single_answer(self, pids):
        """``(mask, definitive)`` for a per-candidate query — exactly
        ``batched_single_feasible``'s answer (the joint path is always
        plan-free, so its misses are definitive) — or None when the seed
        carried no single rows or the query order mismatches."""
        if self.single_mask is None or not self._aligned(pids):
            return None
        return self.single_mask[: len(pids)].copy(), True


def joint_retirement_plan(provisioner, cluster, store, candidates,
                          cache=None, registry=None, build_candidates=None,
                          want_singles=False):
    """The global consolidation solve: ONE joint device ladder over ALL
    candidates simultaneously — every prefix of the disruption-cost order
    is a counterfactual row of a single batched dispatch (the LP-relaxed
    selection), and a host-side rounding/repair pass (the
    parallel/mesh.py bounded-repair stance) makes the winning row's
    displacement plan integral, shedding trailing candidates when exact
    arithmetic disagrees with the kernel's f32 fit. The caller pays
    exactly ONE confirming ``simulate_scheduling`` for the returned set;
    any disagreement there falls back to the per-candidate ladder, which
    this mode retires to oracle duty.

    ``want_singles`` asks the SAME dispatch to also carry the
    per-candidate single rows (candidate c removed alone — exactly
    SingleNodeConsolidation's question, row 0 shared with prefix row 0),
    so a definitive verdict can seed or fence the whole method ladder
    off one device solve; the rows are ALWAYS included when the bundle
    is mid-transition (pending or drain-in-flight pods — the rounds the
    noop fence exists for), because those rounds resolve no-retirement
    almost surely and the fence needs the single answer to be provable.

    Returns ``None`` when the probe cannot express the scenario at all
    (no bundle, invisible candidates, unmapped pods — the caller records
    the ``sequential`` rung), else a :class:`JointPlan`; non-``viable``
    plans name their fallback cause (``topology-plan``,
    ``no-retirement``, ``repair-bound``).

    On settled snapshots the LP relaxation rung (``ops/relax.py
    joint_relax_plan`` — deploy/README.md "LP relaxation rung") runs
    FIRST: a device-resident PDHG solve of the fractional retirement
    program whose bound seeds a bounded device rounding window, with
    this ladder demoted to rounding oracle and fallback. A shipped
    relax plan carries ``solver="relax"``; every relax decline falls
    through to the ladder below with ``relax_fallback`` marked."""
    t0 = time.perf_counter()
    bundle = _bundle_for(
        provisioner, cluster, store, candidates, cache, registry,
        build_candidates,
    )
    if bundle is None:
        return None
    if bundle.plan is not None:
        # waves-compiled bundles make every counterfactual row approximate
        # (module docstring): a joint set chosen from approximate rows
        # would burn its one confirm routinely — the per-candidate ladder
        # (whose gallop recovers cheaply) keeps topology clusters
        return JointPlan(candidates, viable=False, reason="topology-plan")
    cols = bundle.columns_for(candidates)
    if cols is None:
        return None
    contrib = bundle.contribs_for(candidates, cols=cols)
    if contrib is None:
        return None

    N = len(candidates)
    cum = np.cumsum(contrib, axis=0)  # [N,G]
    g_count_k = bundle.base[None, :] + cum
    col_arr = np.asarray(cols, dtype=np.intp)
    e_zero_cols = [col_arr[: k + 1] for k in range(N)]
    transient = bool(int(bundle.base.sum())) or bool(bundle.deleting_pods)

    # LP relaxation fast path (ops/relax.py, deploy/README.md "LP
    # relaxation rung"): on settled snapshots the fractional retirement
    # program picks the prefix in O(iters) device work instead of N
    # counterfactual rows, with the FFD machinery demoted to rounding
    # oracle. Mid-transition rounds skip it outright — they resolve
    # no-retirement almost surely and the noop fence needs the single
    # rows only the FFD dispatch carries. EVERY non-ship outcome falls
    # through to the ladder below (the fallback matrix), so the shipped
    # end state can never be worse than the ladder's.
    relax_fb = False
    if not transient and N >= 2:
        from karpenter_tpu.ops import relax as _relax

        if _relax.relax_enabled():
            rt = {"formulate_ms": (time.perf_counter() - t0) * 1000.0}
            with obs.span("global.relax", candidates=N):
                rplan, _cause = _relax.joint_relax_plan(
                    bundle, candidates, col_arr, contrib, cum, rt)
            if rplan is not None:
                _account(rt, 0, 0)
                return rplan
            relax_fb = True

    singles = (want_singles or transient) and N >= 2
    if singles:
        # the per-candidate single rows ride the SAME dispatch: row 0 is
        # prefix row 0 (remove only candidate 0), rows N.. are candidates
        # 1..N-1 removed alone — _single_criterion (shared verbatim with
        # batched_single_feasible) scores them below
        g_single = bundle.base[None, :] + contrib
        g_count_k = np.concatenate([g_count_k, g_single[1:]], axis=0)
        e_zero_cols = e_zero_cols + [
            col_arr[c: c + 1] for c in range(1, N)]
    rows_total = g_count_k.shape[0]
    t1 = time.perf_counter()

    with obs.span("global.dispatch", rows=rows_total, singles=singles):
        placed_g, used = bundle.dispatch(g_count_k, e_zero_cols,
                                         seam="global.dispatch",
                                         max_bins=_replace_max_claims())
    t2 = time.perf_counter()

    single_mask = None
    if singles:
        placed_single = np.concatenate(
            [placed_g[0:1], placed_g[N:]], axis=0)
        used_single = np.concatenate([used[0:1], used[N:]])
        single_mask = _single_criterion(
            bundle, candidates, contrib, placed_single, used_single)
        placed_g, used = placed_g[:N], used[:N]
    feasible, definitive = _prefix_criterion(
        bundle, candidates, cum, placed_g, used)
    ks = np.flatnonzero(feasible)
    k = 0 if ks.size == 0 else int(ks[-1]) + 1
    timings = {
        "formulate_ms": (t1 - t0) * 1000.0,
        "solve_ms": (t2 - t1) * 1000.0,
    }
    seed_kw = dict(prefix_feasible=feasible, single_mask=single_mask,
                   generation=bundle.generation, transient=transient,
                   relax_fallback=relax_fb)
    if not definitive:
        # a non-definitive ladder (claimability too large to prove, with
        # pending/drain pods riding the rows) UNDER-estimates k; the
        # MultiNode ladder gallops/searches above such a seed, and a
        # joint command shipped at the seed would both retire fewer
        # nodes than the reference AND preempt that recovery (this
        # method runs first) — so the round is handed to the ladder,
        # whose gallop is exactly the machinery the seed needs
        _account(timings, rows_total, 0)
        return JointPlan(candidates, k_device=k, timings=timings,
                         viable=False, reason="non-definitive", **seed_kw)
    if k < 2:
        # nothing worth a joint command: single-candidate rounds (and the
        # probe's residual false-negative corner) stay the ladder's job —
        # unless the single rows above prove the whole round noop, in
        # which case the caller fences it (methods.py GlobalConsolidation)
        _account(timings, rows_total, 0)
        return JointPlan(candidates, definitive=definitive,
                         k_device=k, timings=timings, viable=False,
                         reason="no-retirement", **seed_kw)

    t3 = time.perf_counter()
    k_final, plan, dropped = _round_repair(
        bundle, col_arr, contrib, k, used, feasible)
    timings["round_repair_ms"] = (time.perf_counter() - t3) * 1000.0
    _account(timings, rows_total, dropped)
    if plan is None:
        # the device ladder scored k>=2 feasible but integral rounding
        # failed at every prefix it tried (budget spent, or shed below
        # 2): ARMED as repair-bound either way — a fleet persistently
        # losing its joint rounds to f32-vs-f64 disagreement is exactly
        # the steady-state descent the ledger site exists to catch,
        # never the benign nothing-to-do verdict
        return JointPlan(candidates, definitive=definitive, k_device=k,
                         dropped=dropped, timings=timings, viable=False,
                         reason="repair-bound", **seed_kw)
    placements, overflow, n_claims = plan
    return JointPlan(
        candidates,
        selected_idx=range(k_final),
        delete_only=not overflow,
        definitive=definitive,
        displacement=placements,
        overflow=overflow,
        n_claims=n_claims,
        k_device=k,
        dropped=dropped,
        timings=timings,
        **seed_kw,
    )


def _account(timings, rows, dropped):
    GLOBAL_STATS["plans"] += 1
    GLOBAL_STATS["rows"] += rows
    GLOBAL_STATS["repair_drops"] += dropped
    for key in ("formulate_ms", "solve_ms", "round_repair_ms",
                "relax_ms"):
        GLOBAL_STATS[key] += timings.get(key, 0.0)


def _round_repair(bundle, col_arr, contrib, k, used, feasible):
    """Host-side integral rounding of the device ladder's relaxed
    selection (the parallel/mesh.py ``_repair_merged`` stance applied to
    retirement sets): re-derive the winning prefix's displacement plan in
    exact float64 arithmetic over the survivors' residual capacity, and
    when the kernel's f32 fit over-estimated, shed TRAILING candidates
    down to the next prefix the device ladder itself scored feasible
    (shedding strictly loosens the problem — the trailing node returns
    to the survivor pool AND its pods leave the demand; prefixes the
    kernel already rejected are skipped, not re-derived) and retry,
    attempts bounded by ``KARPENTER_GLOBAL_REPAIR_MAX``. Returns
    ``(k_final, (placements, overflow) | None, drops)`` — ``drops`` is
    the number of candidates shed from the device selection, and the
    plan is ``None`` when the attempt budget ran out or the set shrank
    below 2."""
    base = bundle.base
    G = bundle.snap.G
    claimable = bundle.claimable_groups()
    if claimable is not None:
        base_req = np.where(claimable[:G], base, 0)
    else:
        base_req = base
    live = np.asarray(bundle.esnap.live, dtype=bool)
    budget = _global_repair_bound()
    attempts = 0
    k_cur = k
    while k_cur >= 2:
        surv = live.copy()
        surv[col_arr[:k_cur]] = False
        required = contrib[:k_cur].sum(axis=0) + base_req
        plan = _greedy_displace(
            bundle, surv, required, allow_claim=bool(used[k_cur - 1] > 0),
            max_claims=_replace_max_claims())
        if plan is not None:
            return k_cur, plan, k - k_cur
        if attempts >= budget:
            return k_cur, None, k - k_cur
        attempts += 1
        ks = np.flatnonzero(feasible[:k_cur - 1])
        k_cur = int(ks[-1]) + 1 if ks.size else 0
    return k_cur, None, k - k_cur


def _greedy_displace(bundle, surv, required, allow_claim, max_claims=1):
    """Exact-arithmetic displacement plan for one retirement set: place
    each group's required pods into surviving nodes' residual capacity
    (ge_ok-compatible, biggest-demand groups first, fullest-fitting nodes
    first — the FFD stance of the mesh repair pass), route any remainder
    to at most ``max_claims`` fresh claims when the ladder row allowed it
    (1 — the default — is the reference's m->1 rule; the joint REPLACE
    program passes ``_replace_max_claims()``). Returns ``(placements,
    overflow, n_claims)`` or ``None`` when the set does not round
    integrally (the caller repairs by shrinking it).

    Residual capacity + ``ge_ok`` is the COMPLETE constraint set here:
    the joint path only reaches this pass on plan-free bundles (topology
    plans fell back before the solve), so the kernel's spread/anti/
    affinity columns (e_scnt/e_decl/e_match/e_aff) are all empty,
    per-node max-pods rides the PODS column of ``e_avail``, and
    ``e_npods`` is a fill-priority heuristic, not a constraint."""
    snap, esnap = bundle.snap, bundle.esnap
    G = snap.G
    g_demand = np.asarray(snap.g_demand, dtype=np.float64)
    resid = np.maximum(np.asarray(esnap.e_avail, dtype=np.float64), 0.0)
    resid[~surv] = 0.0
    ge_ok = np.asarray(esnap.ge_ok, dtype=bool)
    placements: list = []
    overflow: dict = {}
    order = np.argsort(-g_demand.sum(axis=1), kind="stable")
    for g in order:
        n = int(required[g])
        if n <= 0:
            continue
        d = g_demand[g]
        pos = d > 0
        if not pos.any():
            continue  # zero-demand pods land anywhere; the sim agrees
        rows = np.flatnonzero(surv & ge_ok[g])
        if rows.size:
            cap = np.floor(
                (resid[np.ix_(rows, np.flatnonzero(pos))] / d[pos][None, :])
                .min(axis=1) + _REPAIR_EPS
            ).astype(np.int64)
            for j in np.argsort(-cap, kind="stable"):
                if n <= 0:
                    break
                take = min(n, int(cap[j]))
                if take <= 0:
                    break  # caps are sorted descending: the rest are 0 too
                e = int(rows[j])
                placements.append((esnap.nodes[e].state_node.provider_id,
                                   int(g), take))
                resid[e] -= take * d
                n -= take
        if n > 0:
            if not allow_claim:
                return None
            overflow[int(g)] = overflow.get(int(g), 0) + n
    if not overflow:
        return placements, overflow, 0
    if max_claims <= 1:
        if not _one_claim_fits(snap, overflow):
            return None
        return placements, overflow, 1
    split = _claims_fit(snap, overflow, max_claims)
    if split is None:
        return None
    return placements, overflow, len(split)


def _claims_fit(snap, overflow, max_claims):
    """The REPLACE generalization of :func:`_one_claim_fits`: greedily
    split the overflow pods across at most ``max_claims`` fresh
    single-template claims — groups biggest-demand first, first-fit over
    already-open claims (largest addable count by binary search, the
    aggregate-fit check monotone in count), a fresh claim only when no
    open one takes a single pod. Returns the per-claim
    ``{group: count}`` dicts, or None when even ``max_claims`` claims
    cannot carry the overflow (the caller sheds candidates instead).
    Same safe direction as the single-claim check: an over-estimate here
    is caught by the confirming simulation; an under-estimate only
    sheds one more candidate than strictly needed."""
    claims: list = []
    order = sorted(overflow,
                   key=lambda g: -float(snap.g_demand[g].sum()))
    for g in order:
        n = int(overflow[g])
        while n > 0:
            placed = False
            for claim in claims:
                lo, hi, take = 1, n, 0
                while lo <= hi:
                    mid = (lo + hi) // 2
                    trial = dict(claim)
                    trial[g] = trial.get(g, 0) + mid
                    if _one_claim_fits(snap, trial):
                        take, lo = mid, mid + 1
                    else:
                        hi = mid - 1
                if take:
                    claim[g] = claim.get(g, 0) + take
                    n -= take
                    placed = True
                    break
            if placed:
                continue
            if len(claims) >= max_claims:
                return None
            lo, hi, take = 1, n, 0
            while lo <= hi:
                mid = (lo + hi) // 2
                if _one_claim_fits(snap, {g: mid}):
                    take, lo = mid, mid + 1
                else:
                    hi = mid - 1
            if take == 0:
                return None  # a pod no single fresh node can carry
            claims.append({g: take})
            n -= take
    return claims


_REPAIR_EPS = 1e-9


def _group_type_compat(snap, gsel=None):
    """[n,T] bool — template compat ∧ requirement overlap (with the
    Intersects tolerance rule) ∧ some offering admissible for the
    group's zone/capacity-type sets, availability included. ONE copy
    shared by :meth:`DisruptionSnapshot.claimable_groups` and
    :func:`_one_claim_fits` so the joint path's claim check can never
    drift from the per-candidate ladder's; the per-pod vs aggregate FIT
    check stays with each caller."""
    s = snap
    sel = slice(None) if gsel is None else gsel
    tmpl_ok = s.g_tmpl_ok[sel][:, s.t_tmpl]  # [n,T]
    shared = s.g_has[sel][:, None, :] & s.t_has[None, :, :]
    ov = ((s.g_mask[sel][:, None] & s.t_mask[None, :]) != 0).any(-1)
    both_tol = s.g_tol[sel][:, None, :] & s.t_tol[None, :, :]
    req_ok = (~shared | ov | both_tol).all(-1)  # [n,T]
    zo, co = s.off_zone, s.off_ct
    zok = np.where(
        zo[None, :, :] >= 0,
        s.g_zone_allowed[sel][:, np.maximum(zo, 0)], True)
    cok = np.where(
        co[None, :, :] >= 0,
        s.g_ct_allowed[sel][:, np.maximum(co, 0)], True)
    off_ok = (s.off_avail[None] & zok & cok).any(-1)  # [n,T]
    return tmpl_ok & req_ok & off_ok


def _one_claim_fits(snap, overflow) -> bool:
    """Whether SOME instance type can carry every overflow pod on one
    fresh node: the shared group×type compat mask, jointly over every
    overflow group, and the aggregate demand (net of daemon overhead)
    inside the type's allocatable. Over-estimating here is caught by the
    confirming simulation (the safe direction); an under-estimate only
    sheds one more candidate than strictly needed."""
    gsel = np.fromiter(overflow.keys(), dtype=np.intp)
    counts = np.fromiter(overflow.values(), dtype=np.int64)
    if snap.T == 0:
        return False
    ok_t = _group_type_compat(snap, gsel).all(axis=0)  # [T]
    if not ok_t.any():
        return False
    demand = (counts[:, None] * snap.g_demand[gsel]).sum(axis=0)
    alloc_eff = snap.t_alloc - snap.m_overhead[snap.t_tmpl]
    fits = (demand[None, :] <= alloc_eff + 1e-6).all(-1)  # [T]
    return bool((ok_t & fits).any())
