"""Batched device consolidation probe — the #2 kernel (SURVEY.md §2.6).

The reference's MultiNodeConsolidation binary-searches prefix length over
the disruption-cost-ordered candidates, each probe a full scheduling
simulation (multinodeconsolidation.go:111-163) — log2(100) sequential
solves. On a TPU the search becomes ONE batched counterfactual: vmap the
pack kernel over all N prefixes at once. Prefix k's snapshot shares every
tensor with the master except

- ``g_count``: pending pods plus the reschedulable pods of candidates[:k]
- ``e_avail``: the cluster's nodes with candidates[:k] zeroed out

so the batch is two stacked arrays over a shared snapshot. ``max_bins=1``
encodes the m→1 replacement rule (consolidation.go:164): a prefix whose
pods don't fit into the surviving nodes plus ONE fresh claim simply leaves
pods unassigned and is infeasible. The largest feasible prefix then gets
the one real simulation (price filter, validation) — ≤2 device dispatches
replacing the sequential ladder.

Topology-bearing clusters ride the probe too: the waves compiler
(ops/waves.py) turns the batch's spread/affinity/anti constraints into the
same class tensors the solve path uses, with one counterfactual
approximation — EVERY candidate's pods are excluded from the cluster domain
counts (each prefix rebinds them), so prefixes that keep some candidates
alive see slightly lower counts than the exact simulation. The error runs
in BOTH directions (lower anti/spread counts loosen the probe; lower
affinity match counts tighten it, so an affinity-dependent prefix can read
infeasible), which is why every probe answer is only a SEED: the winner is
confirmed by the real simulation and a mis-estimate degenerates into the
sequential binary search around k, never a skipped consolidation.

The probe is a sound PREFILTER, not the decision: anything it cannot
express (waves-inexpressible shapes, non-basic-eligible pods, volume
limits) returns None and the caller falls back to the sequential search; a
probe hit is always re-validated by the full simulation before a command
ships.
"""

from __future__ import annotations

import functools

import numpy as np

from karpenter_tpu.ops.tensorize import (
    bucket as _bucket,
    device_basic_eligible,
    group_by_signature,
    pad_to as pad,
    tensorize,
    tensorize_existing,
)


@functools.lru_cache(maxsize=8)
def _batched_kernel(max_bins: int, max_minv: int = 0):
    import jax

    from karpenter_tpu.ops import kernels

    def probe(args):
        # max_minv is threaded statically: solve_step's host-side read of
        # m_minv cannot run on a tracer under this jit/vmap
        out = kernels.solve_step(args, max_bins=max_bins, use_pallas=False,
                                 max_minv=max_minv)
        placed = out["assign"].sum() + out["assign_e"].sum()
        return placed, out["used"].sum()

    # g_count and e_avail carry the batch axis; everything else broadcasts
    def batched(varying, shared):
        def one(v):
            return probe({**shared, **v})

        return jax.vmap(one)(varying)

    return jax.jit(batched)


def batched_feasible_prefix(provisioner, cluster, store, candidates):
    """Largest k such that candidates[:k] consolidate into the remaining
    cluster plus at most one fresh claim, decided in one device call.
    Returns None when the probe cannot express the scenario (the caller
    falls back to the sequential binary search)."""
    try:
        import jax  # noqa: F401
    except Exception:
        return None
    from karpenter_tpu.utils import pod as pod_util

    pending = [p for p in store.list("pods") if pod_util.is_provisionable(p)]
    cand_pods = [list(c.reschedulable_pods) for c in candidates]
    all_pods = pending + [p for ps in cand_pods for p in ps]
    if not all_pods:
        return None
    if any(not device_basic_eligible(p) for p in all_pods):
        return None

    templates, its_by_pool, overhead, limits, domains = provisioner.solver_inputs()
    if not templates:
        return None

    # counterfactual topology: all candidate pods excluded from the cluster
    # domain counts (helpers.go:51's excluded-pod stance, applied across
    # every prefix at once)
    from karpenter_tpu.controllers.provisioning.provisioner import ClusterStateView
    from karpenter_tpu.models.topology import Topology
    from karpenter_tpu.ops import waves

    view = ClusterStateView(cluster, store)
    topology = Topology(cluster=view, domains=domains, pods=all_pods)

    state_nodes = list(cluster.nodes())
    enodes = provisioner._existing_nodes(state_nodes, topology)
    by_pid = {e.state_node.provider_id: i for i, e in enumerate(enodes)}
    cand_cols = []
    for c in candidates:
        i = by_pid.get(c.provider_id)
        if i is None:
            return None  # candidate invisible to the probe: stay sequential
        cand_cols.append(i)

    plan = None
    if topology.has_groups:
        plan = waves.compile_topology(group_by_signature(all_pods), topology)
        if plan.host_pods:
            return None  # waves-inexpressible shape: stay sequential

    snap = tensorize(
        all_pods if plan is None else None, templates, its_by_pool,
        daemon_overhead=overhead, limits=limits or None, device_plan=plan,
    )
    if snap.G == 0:
        return None
    esnap = tensorize_existing(snap, enodes, plan)

    # per-group pod counts: pending base + per-candidate contributions.
    # Row 0 is the PREFIX-0 BASELINE (pending pods only, every node alive):
    # feasibility is judged on the INCREMENT over it, so a pending pod that
    # cannot schedule anywhere (and would not block the sequential path,
    # which only requires the candidates' pods to land —
    # SimulationResults.all_pods_scheduled) does not poison every prefix.
    gidx_of = {}
    for g, pods_g in enumerate(snap.groups):
        for p in pods_g:
            gidx_of[p.uid] = g
    G = snap.G
    base = np.zeros(G, dtype=np.int32)
    for p in pending:
        base[gidx_of[p.uid]] += 1
    N = len(candidates)
    contrib = np.zeros((N, G), dtype=np.int32)
    for j, ps in enumerate(cand_pods):
        for p in ps:
            contrib[j, gidx_of[p.uid]] += 1
    g_count_k = np.concatenate(
        [base[None, :], base[None, :] + np.cumsum(contrib, axis=0)], axis=0
    )  # [N+1,G]: row 0 = baseline, row k = prefix k

    E = esnap.E
    e_avail_k = np.repeat(esnap.e_avail[None, :, :], N + 1, axis=0)  # [N+1,E,R]
    for j in range(N):
        for col in cand_cols[: j + 1]:
            e_avail_k[j + 1, col, :] = 0.0

    # shared args padded once; the batch axis buckets so XLA compiles per
    # shape family, not per candidate count
    Np = _bucket(N + 1, lo=4)
    Gp, Ep = _bucket(G, lo=8), _bucket(E, lo=8)
    Tp = _bucket(snap.T, lo=8)

    R = len(snap.resources)
    M = len(snap.templates)
    K = len(snap.keys)
    # NOTE: keep this assembly in lockstep with models/solver.py
    # _run_and_decode's args dict — a field missed here silently weakens
    # the probe (it under- or over-estimates and burns the dispatch)
    shared = dict(
        g_mask=pad(snap.g_mask, (Gp,) + snap.g_mask.shape[1:]),
        g_has=pad(snap.g_has, (Gp,) + snap.g_has.shape[1:]),
        g_tol=pad(snap.g_tol, (Gp, K)),
        g_demand=pad(snap.g_demand, (Gp, R)),
        g_zone_allowed=pad(snap.g_zone_allowed, (Gp, snap.g_zone_allowed.shape[1])),
        g_ct_allowed=pad(snap.g_ct_allowed, (Gp, snap.g_ct_allowed.shape[1])),
        g_tmpl_ok=pad(snap.g_tmpl_ok, (Gp, M)),
        g_bin_cap=pad(snap.g_bin_cap, (Gp,)),
        g_single=pad(snap.g_single, (Gp,)),
        g_decl=pad(snap.g_decl, (Gp, snap.g_decl.shape[1])),
        g_match=pad(snap.g_match, (Gp, snap.g_match.shape[1])),
        g_sown=pad(snap.g_sown, (Gp, snap.g_sown.shape[1])),
        g_smatch=pad(snap.g_smatch, (Gp, snap.g_smatch.shape[1])),
        g_aneed=pad(snap.g_aneed, (Gp, snap.g_aneed.shape[1])),
        g_amatch=pad(snap.g_amatch, (Gp, snap.g_amatch.shape[1])),
        ge_ok=pad(esnap.ge_ok, (Gp, Ep)),
        e_npods=pad(esnap.e_npods, (Ep,)),
        e_scnt=pad(esnap.e_scnt, (Ep, esnap.e_scnt.shape[1])),
        e_decl=pad(esnap.e_decl, (Ep, esnap.e_decl.shape[1])),
        e_match=pad(esnap.e_match, (Ep, esnap.e_match.shape[1])),
        e_aff=pad(esnap.e_aff, (Ep, esnap.e_aff.shape[1])),
        t_mask=pad(snap.t_mask, (Tp,) + snap.t_mask.shape[1:]),
        t_has=pad(snap.t_has, (Tp,) + snap.t_has.shape[1:]),
        t_tol=pad(snap.t_tol, (Tp, K)),
        t_alloc=pad(snap.t_alloc, (Tp, R)),
        t_cap=pad(snap.t_cap, (Tp, R)),
        t_tmpl=pad(snap.t_tmpl, (Tp,)),
        off_zone=pad(snap.off_zone, (Tp, snap.off_zone.shape[1]), fill=-1),
        off_ct=pad(snap.off_ct, (Tp, snap.off_ct.shape[1]), fill=-1),
        off_avail=pad(snap.off_avail, (Tp, snap.off_avail.shape[1])),
        off_price=pad(snap.off_price, (Tp, snap.off_price.shape[1])),
        m_mask=snap.m_mask,
        m_has=snap.m_has,
        m_tol=snap.m_tol,
        m_overhead=snap.m_overhead,
        m_limits=snap.m_limits,
        m_minv=snap.m_minv,
    )
    varying = dict(
        g_count=pad(g_count_k, (Np, Gp)),
        e_avail=pad(e_avail_k, (Np, Ep, R)),
    )

    max_minv = int(snap.m_minv.max()) if snap.m_minv.size else 0
    placed, _used = _batched_kernel(1, max_minv)(varying, shared)
    placed = np.asarray(placed)[: N + 1]
    need = g_count_k.sum(axis=1)
    # prefix k feasible iff its displaced pods ALL land on top of whatever
    # the baseline already achieves (baseline deficit = stuck pending pods)
    deficit0 = int(need[0] - placed[0])
    feasible = (need[1:] - placed[1:]) <= deficit0
    ks = np.flatnonzero(feasible)
    if ks.size == 0:
        return 0
    return int(ks[-1]) + 1
