"""Device kernels: batched feasibility + grouped bin-packing.

TPU-native reformulation of the reference's two hot loops (SURVEY.md §2.5):

- `feasibility`: the per-pod, per-instance-type constraint checks of
  scheduler.go's inner loop (requirement intersection nodeclaim.go:242,
  resource fit, offering availability) become one batched tensor expression
  over [G groups x T types] with requirements as packed uint32 bitmasks.

- `pack`: the FFD loop (scheduler.go:195-296) becomes a lax.scan over pod
  GROUPS. The reference tries open claims emptiest-first and a claim keeps
  every instance type still feasible for its accumulated pods (capacity =
  max over remaining types). We replicate that with a level-fill: a binary
  search finds the pod-count water level L such that filling every
  compatible bin up to L absorbs the group, which is exactly where the
  reference's ascending-pod-count ordering converges, without the per-pod
  serialization.

All shapes are static (pad groups with count 0, types with alloc 0); the
solver buckets shapes and caches compiled executables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from karpenter_tpu.ops.tensorize import SPREAD_OWNED_MIN, UNCAPPED

_EPS = 1e-6
_LEVEL_SEARCH_ITERS = 20  # supports levels up to ~1M pods per bin


def feasibility(
    g_mask,  # [G,K,W] u32
    g_has,  # [G,K] bool
    g_demand,  # [G,R] f32
    t_mask,  # [T,K,W] u32
    t_has,  # [T,K] bool
    t_alloc,  # [T,R] f32
    g_zone_allowed,  # [G,Vz] bool
    g_ct_allowed,  # [G,Vc] bool
    off_zone,  # [T,O] i32
    off_ct,  # [T,O] i32
    off_avail,  # [T,O] bool
    off_price,  # [T,O] f32
    g_tmpl_ok,  # [G,M] bool (taints + custom-label definedness)
    m_mask,  # [M,K,W] u32
    m_has,  # [M,K] bool
    g_tol=None,  # [G,K] bool NotIn/DoesNotExist operators
    t_tol=None,  # [T,K] bool
    m_tol=None,  # [M,K] bool
    use_pallas: bool = False,  # route compat through the Mosaic kernel
):
    """Returns (F [G,T] bool, price [G,T] f32, tmpl_full [G,M] bool)."""
    G, K, W = g_mask.shape
    T = t_mask.shape[0]
    if g_tol is None:
        g_tol = jnp.zeros((G, K), dtype=bool)
    if t_tol is None:
        t_tol = jnp.zeros((T, K), dtype=bool)
    if m_tol is None:
        m_tol = jnp.zeros((m_mask.shape[0], K), dtype=bool)

    # requirement overlap, key by key. An empty meet is tolerated iff BOTH
    # operators are NotIn/DoesNotExist (requirements.py Intersects:249),
    # matching the host engine exactly. Two equivalent formulations:
    # the hand-tiled Pallas kernel (single-word vocabularies, unsharded,
    # KARPENTER_PALLAS=1) or the jnp loop XLA fuses (K is small; the
    # python loop unrolls into fused vector ops — no [G,T,K,W]
    # intermediate is materialized).
    if use_pallas and W == 1 and K <= 128:
        from karpenter_tpu.ops.pallas_kernels import compat_pallas

        compat = compat_pallas(
            g_mask[:, :, 0].astype(jnp.int32), g_has, g_tol,
            t_mask[:, :, 0].astype(jnp.int32), t_has, t_tol,
        )
    else:
        compat = jnp.ones((G, T), dtype=bool)
        for k in range(K):
            ov = jnp.zeros((G, T), dtype=bool)
            for w in range(W):
                ov = ov | ((g_mask[:, None, k, w] & t_mask[None, :, k, w]) != 0)
            ov = ov | (g_tol[:, None, k] & t_tol[None, :, k])
            both = g_has[:, None, k] & t_has[None, :, k]
            compat = compat & (~both | ov)

    # resource fit: every demanded resource within allocatable
    fits = jnp.all(g_demand[:, None, :] <= t_alloc[None, :, :] + _EPS, axis=-1)

    # offerings: available ∧ zone allowed ∧ capacity-type allowed
    zo = jnp.where(
        off_zone[None, :, :] >= 0, g_zone_allowed[:, jnp.maximum(off_zone, 0)], True
    )  # [G,T,O]
    co = jnp.where(off_ct[None, :, :] >= 0, g_ct_allowed[:, jnp.maximum(off_ct, 0)], True)
    off_ok = off_avail[None, :, :] & zo & co  # [G,T,O]
    has_off = jnp.any(off_ok, axis=-1)
    price = jnp.min(jnp.where(off_ok, off_price[None, :, :], jnp.inf), axis=-1)

    F = compat & fits & has_off

    # template-level requirement overlap for new-bin placement (Compatible
    # routes through Intersects, so the same tolerance applies)
    M = m_mask.shape[0]
    tm_ov = jnp.ones((G, M), dtype=bool)
    for k in range(K):
        ov = jnp.zeros((G, M), dtype=bool)
        for w in range(W):
            ov = ov | ((g_mask[:, None, k, w] & m_mask[None, :, k, w]) != 0)
        ov = ov | (g_tol[:, None, k] & m_tol[None, :, k])
        both = g_has[:, None, k] & m_has[None, :, k]
        tm_ov = tm_ov & (~both | ov)
    tmpl_full = g_tmpl_ok & tm_ov

    return F, price, tmpl_full


def _combine_masks(a_mask, a_has, b_mask, b_has):
    """Requirement-set union with per-key intersection of allowed values.
    a:[...,K,W]/[...,K]; b broadcastable to a."""
    both = a_has & b_has
    out_mask = jnp.where(
        both[..., None], a_mask & b_mask, jnp.where(b_has[..., None], b_mask, a_mask)
    )
    return out_mask, a_has | b_has


def _level_fill(q, npods, n, level_bits: int = _LEVEL_SEARCH_ITERS):
    """Distribute n pods across bins filling emptiest-first up to per-bin
    caps q — the batched equivalent of the reference's ascending-pod-count
    claim ordering (scheduler.go:258). Returns per-bin take.

    `level_bits` bounds the search range at 2^bits pods per bin: when the
    catalog carries a pods-resource cap (kubelet max-pods, 110 by default)
    the caller shrinks it to ~8, cutting the scan step's dominant op count
    by >2x."""
    total_cap = jnp.sum(q)
    n_eff = jnp.minimum(n, total_cap)

    def fill(level):
        return jnp.sum(jnp.minimum(q, jnp.maximum(level - npods, 0)))

    lo = jnp.int32(0)
    hi = jnp.int32(1) << level_bits

    # unrolled at trace time: a lax loop pays per-iteration dispatch
    # overhead ~L times per scan step, which dominated the scan's device
    # time; inlined, the search is pure dataflow XLA fuses freely
    for _ in range(level_bits):
        mid = (lo + hi) // 2
        enough = fill(mid) >= n_eff
        lo = jnp.where(enough, lo, mid)
        hi = jnp.where(enough, mid, hi)
    level = hi
    take = jnp.minimum(q, jnp.maximum(level - npods, 0))
    # overshoot: bins whose take reaches the final level can each give back 1
    excess = jnp.sum(take) - n_eff
    cand = (take > 0) & (npods + take == level)
    give_back = cand & (jnp.cumsum(cand.astype(jnp.int32)) <= excess)
    return take - give_back.astype(jnp.int32)


def pack(
    # per-group (scan xs), already in FFD order
    g_demand,  # [G,R]
    g_count,  # [G]
    g_mask,  # [G,K,W]
    g_has,  # [G,K]
    F,  # [G,T] feasibility
    tmpl_full,  # [G,M]
    g_bin_cap,  # [G] i32: max pods of the group per bin (waves topology)
    g_single,  # [G] bool: whole group confined to one bin (hostname affinity)
    g_decl,  # [G,CW] u32: hostname-anti classes the group declares
    g_match,  # [G,CW] u32: hostname-anti classes whose selector matches it
    g_sown,  # [G,C] i32: per-bin cap where the group owns the spread class
    g_smatch,  # [G,C] bool: the spread class counts this group's pods
    g_aneed,  # [G,A] bool: hostname-affinity classes the group owns
    g_amatch,  # [G,A] bool: the affinity-class selector matches this group
    g_tier,  # [G] i32: priority tier (scan arrives tier-major — the order
    # IS the fence: lower tiers only ever see residual capacity)
    # existing/in-flight nodes as pre-loaded bins (existingnode.go:64)
    ge_ok,  # [G,E] bool: group admissible on node (taints + strict labels)
    e_avail,  # [E,R] f32: fixed available capacity (allocatable - usage)
    e_npods,  # [E] i32: current pod count (fill priority)
    e_scnt,  # [E,C] i32: spread-class counts from the nodes' current pods
    e_decl,  # [E,CW] u32: anti classes declared by current pods
    e_match,  # [E,CW] u32: anti classes matching current pods
    e_aff,  # [E,A] i32: affinity-class matched-pod counts on the node
    # static catalog
    t_alloc,  # [T,R]
    t_cap,  # [T,R]
    t_tmpl,  # [T]
    m_mask,  # [M,K,W]
    m_has,  # [M,K]
    m_overhead,  # [M,R]
    m_limits,  # [M,R]
    m_minv,  # [M] i32: required distinct instance types per claim
    *,
    max_bins: int,
    with_existing: bool = True,
    level_bits: int = _LEVEL_SEARCH_ITERS,
    max_minv: int = 0,
):
    """Grouped greedy pack. Returns dict with:
    assign [G,B] i32, used [B] bool, npods [B] i32, types [B,T] bool,
    tmpl [B] i32. Pods a group couldn't place are implied by
    count - sum(assign[g]) and re-routed by the decoder.

    Topology structure compiled by ops/waves.py arrives as per-group
    scalars: `g_bin_cap` bounds a bin's share of the group (hostname
    spread maxSkew / anti-affinity cap 1, topologygroup.go:167,252) and
    `g_single` confines the whole group to one bin (hostname pod
    affinity, topologygroup.go:219). Hostname anti-affinity across groups
    is conflict classes: a bin hosting pods MATCHED by class c excludes
    groups DECLARING c and vice versa (the direct/inverse TopologyGroup
    pair, topology.go:225); bins carry declared/matched class bitmask
    state. Hostname SPREAD is per-bin class COUNTS (topologygroup.go:167
    counts by selector match): every matched group's take increments
    `bscnt[b,c]`, and a group OWNING class c only lands where
    bscnt + take <= maxSkew — exact across co-owner groups and
    unconstrained same-label groups. Zone constraints ride the ordinary
    requirement masks as zone-pinned subgroups and need nothing here.

    Hostname pod AFFINITY is per-bin class match counts `baff[b,a]`
    (topologygroup.go nextDomainAffinity:219): a group OWNING class a may
    only land on bins whose count is already positive; when the class has
    no matches anywhere yet, a self-matching owner bootstraps exactly ONE
    fresh bin (the host bootstrap, topology.py:211) and every later group
    in the scan sees its count — cross-group chains resolve inside one
    dispatch because the compiler (ops/waves.py) orders followers after
    their targets.
    """
    G, R = g_demand.shape
    T = t_alloc.shape[0]
    M = m_overhead.shape[0]
    B = max_bins
    E = e_avail.shape[0]
    t_is_m = t_tmpl[:, None] == jnp.arange(M)[None, :]  # [T,M]

    CW = g_decl.shape[1]
    C = g_sown.shape[1]
    A = g_aneed.shape[1]
    # static per-type check: template overhead fits the type's allocatable
    # on EVERY dim (a group's d=0 dims never re-check it inside the scan)
    ovh_ok = jnp.all(m_overhead[t_tmpl] <= t_alloc + _EPS, axis=-1)  # [T]
    state = dict(
        used=jnp.zeros(B, dtype=bool),
        npods=jnp.zeros(B, dtype=jnp.int32),
        load=jnp.zeros((B, R), dtype=jnp.float32),
        types=jnp.zeros((B, T), dtype=bool),
        bmask=jnp.zeros((B,) + g_mask.shape[1:], dtype=jnp.uint32),
        bhas=jnp.zeros((B,) + g_has.shape[1:], dtype=bool),
        btmpl=jnp.zeros(B, dtype=jnp.int32),
        rem=m_limits.astype(jnp.float32),
        bdecl=jnp.zeros((B, CW), dtype=jnp.uint32),
        bmatch=jnp.zeros((B, CW), dtype=jnp.uint32),
        bscnt=jnp.zeros((B, C), dtype=jnp.int32),
        baff=jnp.zeros((B, A), dtype=jnp.int32),
        # tier of the group that OPENED the bin — pure observability for
        # the fused admission round (which tier each claim charges to);
        # it never gates packing, the tier-major scan order is the fence
        btier=jnp.zeros(B, dtype=jnp.int32),
    )
    if with_existing:
        state.update(
            eload=jnp.zeros((E, R), dtype=jnp.float32),
            enpods=e_npods.astype(jnp.int32),
            escnt=e_scnt.astype(jnp.int32),
            edecl=e_decl,
            ematch=e_match,
            eaff=e_aff.astype(jnp.int32),
        )

    def step(state, xs):
        (d, n, gm, gh, Fg, tfull, cap_g, single, decl_g, match_g,
         sown_g, smatch_g, aneed_g, amatch_g, tier_g, ge_g) = xs
        any_aneed = jnp.any(aneed_g)
        has_pods = n > 0
        owned = sown_g < SPREAD_OWNED_MIN  # [C]

        # ---- phase A: existing nodes first (scheduler.go:250) ----
        # fixed capacity (no instance-type choice), admission precomputed
        # host-side in ge_ok; anti/spread class state evolves like bins'.
        # Structurally omitted (with_existing is a compile-time arg) when
        # the snapshot has no existing nodes — the empty-cluster burst path
        # pays nothing for steady-state support.
        if with_existing:
            avail_e = e_avail - state["eload"]  # [E,R]
            ratio_e = jnp.where(
                d[None, :] > 0, avail_e / jnp.maximum(d[None, :], _EPS), jnp.inf
            )
            q_e = jnp.floor(jnp.min(ratio_e, axis=-1) + _EPS).astype(jnp.int32)  # [E]
            anti_e = jnp.all(
                (state["ematch"] & decl_g[None, :]) == 0, axis=-1
            ) & jnp.all((state["edecl"] & match_g[None, :]) == 0, axis=-1)
            rem_e = sown_g[None, :] - state["escnt"]  # [E,C]
            rem_e_eff = jnp.where(
                smatch_g[None, :], rem_e, jnp.where(rem_e > 0, UNCAPPED, 0)
            )
            q_cls_e = jnp.min(jnp.where(owned[None, :], rem_e_eff, UNCAPPED), axis=-1)
            # affinity classes: owners land only where matched pods already
            # sit (batch groups that landed here earlier in the scan, or
            # cluster pods seeded into e_aff)
            aff_e = jnp.all(~aneed_g[None, :] | (state["eaff"] > 0), axis=-1)
            q_e = jnp.where(ge_g & anti_e & aff_e, q_e, 0)
            q_e = jnp.minimum(jnp.minimum(q_e, cap_g), jnp.maximum(q_cls_e, 0))
            # single-bin groups (hostname pod affinity) stay on the claim
            # path: waves routes groups with existing matches to the host
            # engine, so a device single group always bootstraps a fresh claim
            q_e = jnp.where(single | ~has_pods, 0, q_e)
            take_e = _level_fill(q_e, state["enpods"], n, level_bits)
            n = n - jnp.sum(take_e)

            eload2 = state["eload"] + take_e[:, None].astype(jnp.float32) * d[None, :]
            enpods2 = state["enpods"] + take_e
            escnt2 = state["escnt"] + take_e[:, None] * smatch_g[None, :].astype(jnp.int32)
            eaff2 = state["eaff"] + take_e[:, None] * amatch_g[None, :].astype(jnp.int32)
            landed_e = (take_e > 0)[:, None]
            edecl2 = jnp.where(landed_e, state["edecl"] | decl_g[None, :], state["edecl"])
            ematch2 = jnp.where(landed_e, state["ematch"] | match_g[None, :], state["ematch"])
        else:
            take_e = jnp.zeros(E, dtype=jnp.int32)

        # ---- phase B: open claim bins: compatibility ----
        both = state["bhas"] & gh[None, :]
        ov = jnp.any((state["bmask"] & gm[None, :, :]) != 0, axis=-1)
        compat_b = jnp.all(~both | ov, axis=-1)
        compat_b = compat_b & state["used"] & jnp.take(tfull, state["btmpl"])
        # hostname anti-affinity conflict classes: a declarer avoids bins
        # hosting matched pods; a matched group avoids bins with declarers
        anti_ok = jnp.all(
            (state["bmatch"] & decl_g[None, :]) == 0, axis=-1
        ) & jnp.all((state["bdecl"] & match_g[None, :]) == 0, axis=-1)
        compat_b = compat_b & anti_ok
        # hostname-affinity classes: an owner lands only on bins already
        # holding matched pods (nextDomainAffinity options, topology.py:209)
        aff_ok = jnp.all(~aneed_g[None, :] | (state["baff"] > 0), axis=-1)
        compat_b = compat_b & aff_ok

        # ---- per-bin capacity for this group (max over remaining types) ----
        # (alloc - load)/d = alloc/d - load/d: hoisting the divisions to
        # [T,R] and [B,R] turns the [B,T,R] inner op into subtract+min —
        # the scan's dominant tensor, so op cost here is wall-clock
        inv_d = jnp.where(d > 0, 1.0 / jnp.maximum(d, _EPS), 0.0)  # [R]
        ad = jnp.where(d[None, :] > 0, t_alloc * inv_d[None, :], jnp.inf)  # [T,R]
        ld = state["load"] * inv_d[None, :]  # [B,R] (0 where d=0)
        cap_bt = jnp.floor(
            jnp.min(ad[None, :, :] - ld[:, None, :], axis=-1) + _EPS
        ).astype(jnp.int32)  # [B,T]
        cap_bt = jnp.where(state["types"] & Fg[None, :], jnp.maximum(cap_bt, 0), 0)
        q = jnp.max(cap_bt, axis=-1)  # [B]
        q = jnp.where(compat_b, q, 0)
        q = jnp.minimum(q, cap_g)  # per-bin topology cap (waves)
        # spread classes: an owner of class c lands only while the bin's
        # matched count stays within the cap (topologygroup.go:167). A
        # self-selecting owner debits its own take (each pod raises the
        # count the next one sees); an owner whose selector does NOT match
        # its own labels never moves the count, so the cap gates the bin
        # as a whole (all-or-nothing) rather than the take
        # (topology.py:200 'if self_selecting')
        rem_cls = sown_g[None, :] - state["bscnt"]  # [B,C]
        rem_eff = jnp.where(
            smatch_g[None, :], rem_cls, jnp.where(rem_cls > 0, UNCAPPED, 0)
        )
        q_cls = jnp.min(
            jnp.where(owned[None, :], rem_eff, UNCAPPED), axis=-1
        )  # [B]
        q = jnp.minimum(q, jnp.maximum(q_cls, 0))
        if max_minv > 0:
            # minValues floor (types.go:165-199 compiled onto the device):
            # a take of t keeps >= minv instance types alive iff at least
            # minv types have capacity >= t, i.e. t <= the minv-th largest
            # per-type capacity — compiled out entirely when no template
            # carries minValues (max_minv is a static trace arg)
            minv_b = jnp.take(m_minv, state["btmpl"])  # [B]
            k_eff = min(max_minv, T)
            top = jax.lax.top_k(cap_bt, k_eff)[0]  # [B,k_eff] desc
            idx = jnp.clip(minv_b - 1, 0, k_eff - 1)
            kth = jnp.take_along_axis(top, idx[:, None], axis=1)[:, 0]
            kth = jnp.where(minv_b > T, 0, kth)  # fewer types than required
            q = jnp.where(minv_b > 0, jnp.minimum(q, jnp.maximum(kth, 0)), q)

        take = _level_fill(q, state["npods"], n, level_bits)
        # single-bin group: everything lands on the single highest-capacity
        # bin (any bin with matches works — the whole group commits at once)
        b_star = jnp.argmax(q)
        take_single = (
            jnp.zeros_like(take).at[b_star].set(jnp.minimum(jnp.max(q), n))
        )
        take = jnp.where(single, take_single, take)
        take = jnp.where(has_pods, take, 0)
        assigned = jnp.sum(take)
        spill = n - assigned

        # ---- new bins from the best template ----
        fresh_avail = t_alloc - m_overhead[t_tmpl]  # [T,R]
        fr = jnp.where(d[None, :] > 0, fresh_avail / jnp.maximum(d[None, :], _EPS), jnp.inf)
        fresh_cap = jnp.floor(jnp.min(fr, axis=-1) + _EPS).astype(jnp.int32)  # [T]
        limit_ok = jnp.all(t_cap <= state["rem"][t_tmpl] + _EPS, axis=-1)  # [T]
        new_ok = Fg & limit_ok & jnp.take(tfull, t_tmpl) & (fresh_cap > 0) & ovh_ok  # [T]
        per_node_m = jnp.max(
            jnp.where(new_ok[:, None] & t_is_m, fresh_cap[:, None], 0), axis=0
        )  # [M]
        if max_minv > 0:
            # a fresh claim must also open with >= minv viable types: cap
            # its fill at the minv-th largest per-type fresh capacity
            fc = jnp.where(new_ok[:, None] & t_is_m, fresh_cap[:, None], 0)  # [T,M]
            k_eff = min(max_minv, T)
            topm = jax.lax.top_k(fc.T, k_eff)[0]  # [M,k_eff]
            idx_m = jnp.clip(m_minv - 1, 0, k_eff - 1)
            kth_m = jnp.take_along_axis(topm, idx_m[:, None], axis=1)[:, 0]
            kth_m = jnp.where(m_minv > T, 0, kth_m)
            per_node_m = jnp.where(
                m_minv > 0, jnp.minimum(per_node_m, jnp.maximum(kth_m, 0)),
                per_node_m,
            )
        feasible_m = per_node_m > 0
        # templates are pre-sorted by weight: first feasible wins
        m_star = jnp.argmax(feasible_m)
        any_m = jnp.any(feasible_m)
        # fresh bins start at class count 0, so the owned cap bounds
        # per_node — only for self-selecting owners (non-self-selecting
        # pods never raise the count they are checked against)
        cap_own = jnp.min(jnp.where(owned & smatch_g, sown_g, UNCAPPED))
        per_node = jnp.maximum(
            jnp.minimum(jnp.take(per_node_m, m_star), jnp.minimum(cap_g, cap_own)), 1
        )

        # worst-case capacity of a new bin (for limit accounting, below)
        worst = jnp.max(
            jnp.where((new_ok & (t_tmpl == m_star))[:, None], t_cap, 0.0), axis=0
        )  # [R]
        # cap bin openings by the nodepool's remaining limits so one group
        # cannot breach them mid-step (host parity: scheduler.go:271-292
        # re-filters after every claim)
        limit_ratio = jnp.where(worst > 0, state["rem"][m_star] / worst, jnp.inf)
        max_new_by_limit = jnp.clip(
            jnp.floor(jnp.min(limit_ratio) + _EPS), 0, 2**30
        ).astype(jnp.int32)

        want_new = jnp.where(any_m & (spill > 0), (spill + per_node - 1) // per_node, 0)
        # single-bin group: one new bin, and only if nothing placed on an
        # existing bin (followers join the first pod's claim or fail —
        # topology.py:207 bootstrap)
        want_new = jnp.where(
            single, jnp.where((assigned == 0) & any_m & (spill > 0), 1, 0), want_new
        )
        # affinity owners may open a fresh bin only to BOOTSTRAP: every
        # owned class must have zero matches anywhere AND be self-matched
        # (host: matches elsewhere force joining them; a non-self-matching
        # owner with no matches cannot schedule at all), and the bootstrap
        # opens exactly ONE bin — the host's sequential pods must join the
        # first pod's fresh domain (topology.py:211-221)
        gc = jnp.sum(state["baff"], axis=0)  # [A] global matched counts
        if with_existing:
            gc = gc + jnp.sum(state["eaff"], axis=0)
        boot_ok = jnp.all(~aneed_g | (amatch_g & (gc == 0)))
        want_new = jnp.where(any_aneed & ~boot_ok, 0, want_new)
        want_new = jnp.where(any_aneed, jnp.minimum(want_new, 1), want_new)
        want_new = jnp.minimum(want_new, max_new_by_limit)
        free = ~state["used"]
        rank = jnp.cumsum(free.astype(jnp.int32)) - 1
        sel = free & (rank < want_new)
        pods_new = jnp.clip(spill - rank * per_node, 0, per_node) * sel.astype(jnp.int32)

        # ---- commit: existing bins ----
        upd = take > 0
        npods2 = state["npods"] + take
        load2 = state["load"] + take[:, None].astype(jnp.float32) * d[None, :]
        # a surviving type still fits iff its capacity covered the take
        # (d=0 dims are unchanged and held before), so cap_bt is reused
        # instead of a second [B,T,R] reduction
        fits_new = cap_bt >= take[:, None]  # [B,T]
        types2 = jnp.where(upd[:, None], state["types"] & Fg[None, :] & fits_new, state["types"])
        cm, ch = _combine_masks(state["bmask"], state["bhas"], gm[None, :, :], gh[None, :])
        bmask2 = jnp.where(upd[:, None, None], cm, state["bmask"])
        bhas2 = jnp.where(upd[:, None], ch, state["bhas"])

        # ---- commit: new bins ----
        new_load = m_overhead[m_star][None, :] + pods_new[:, None].astype(jnp.float32) * d[None, :]
        # fresh_cap >= pods_new is the d>0 fit; ovh_ok (folded into new_ok)
        # covers overhead-exceeds-alloc on undemanded dims — no [B,T,R] op
        new_types = (
            (t_tmpl[None, :] == m_star)
            & new_ok[None, :]
            & (fresh_cap[None, :] >= pods_new[:, None])
        )
        # new bin requirements = template ∧ group (claim starts from template)
        nm, nh = _combine_masks(m_mask[m_star], m_has[m_star], gm, gh)
        used3 = state["used"] | sel
        npods3 = jnp.where(sel, pods_new, npods2)
        load3 = jnp.where(sel[:, None], new_load, load2)
        types3 = jnp.where(sel[:, None], new_types, types2)
        bmask3 = jnp.where(sel[:, None, None], nm[None, :, :], bmask2)
        bhas3 = jnp.where(sel[:, None], nh[None, :], bhas2)
        btmpl3 = jnp.where(sel, m_star, state["btmpl"])
        btier3 = jnp.where(sel, tier_g, state["btier"])

        # ---- nodepool limits: subtract worst-case capacity per new bin ----
        n_opened = jnp.sum(sel.astype(jnp.float32))
        rem3 = state["rem"].at[m_star].add(-worst * n_opened)

        # ---- conflict-class commit: any bin that received pods of this
        # group now carries its declared/matched classes ----
        landed = (upd | (sel & (pods_new > 0)))[:, None]
        bdecl3 = jnp.where(landed, state["bdecl"] | decl_g[None, :], state["bdecl"])
        bmatch3 = jnp.where(landed, state["bmatch"] | match_g[None, :], state["bmatch"])
        # spread-class counts grow by the bin's total take for every class
        # whose selector matches this group
        total_take = take + pods_new  # [B] (pods_new already masked by sel)
        bscnt3 = state["bscnt"] + total_take[:, None] * smatch_g[None, :].astype(
            jnp.int32
        )
        baff3 = state["baff"] + total_take[:, None] * amatch_g[None, :].astype(
            jnp.int32
        )

        new_state = dict(
            used=used3,
            npods=npods3,
            load=load3,
            types=types3,
            bmask=bmask3,
            bhas=bhas3,
            btmpl=btmpl3,
            rem=rem3,
            bdecl=bdecl3,
            bmatch=bmatch3,
            bscnt=bscnt3,
            baff=baff3,
            btier=btier3,
        )
        if with_existing:
            new_state.update(
                eload=eload2, enpods=enpods2, escnt=escnt2,
                edecl=edecl2, ematch=ematch2, eaff=eaff2,
            )
        return new_state, (take + pods_new, take_e)

    xs = (g_demand, g_count, g_mask, g_has, F, tmpl_full, g_bin_cap, g_single,
          g_decl, g_match, g_sown, g_smatch, g_aneed, g_amatch, g_tier, ge_ok)
    state, (assign, assign_e) = jax.lax.scan(step, state, xs)
    return dict(
        assign=assign,  # [G,B] (scan stacks per-step [B] outputs)
        assign_e=assign_e,  # [G,E] pods landed on existing nodes
        used=state["used"],
        npods=state["npods"],
        types=state["types"],
        tmpl=state["btmpl"],
        tier=state["btier"],  # [B] tier of the bin's opening group
    )


def pallas_enabled() -> bool:
    """Opt-in (KARPENTER_PALLAS=1) AND a TPU backend: Mosaic only compiles
    for TPU — every other platform (cpu, gpu, metal, future plugins) takes
    the jnp path. The image's plugin platform reports as "axon"/"tpu"."""
    from karpenter_tpu.utils.envknobs import env_str

    # graftlint: disable=GL103 -- the freeze-at-trace hazard is the
    # documented contract: callers that cache jitted wrappers resolve this
    # HOST-side and key their cache on it (models/solver.py _kernel);
    # solve_step only falls back here on the eager path
    if env_str("KARPENTER_PALLAS") != "1":
        return False
    backend = jax.default_backend()
    return "axon" in backend or "tpu" in backend


def solve_step(args: dict, max_bins: int, with_existing: bool | None = None,
               use_pallas: bool | None = None,
               level_bits: int = _LEVEL_SEARCH_ITERS,
               max_minv: int | None = None) -> dict:
    """The full single-call solve: feasibility + pack over one snapshot's
    arg dict (the canonical invocation shared by the solver, the sharded
    path, and the graft entry)."""
    # the static minValues width must resolve HOST-side (it shapes the
    # trace); jitted callers pass it explicitly
    if max_minv is None:
        import numpy as _np

        mv = args.get("m_minv")
        # graftlint: disable=GL101 -- eager-only guard branch: every jitted
        # caller (solver/mesh/consolidate) passes max_minv explicitly, so
        # this host pull never sees a tracer
        max_minv = int(_np.asarray(mv).max()) if mv is not None else 0
    # device arrays throughout: the scan body indexes these with traced
    # values, which numpy inputs cannot satisfy when called outside jit
    args = {k: jnp.asarray(v) for k, v in args.items()}
    G = args["g_count"].shape[0]
    if "g_bin_cap" not in args:
        args["g_bin_cap"] = jnp.full(G, 1 << 30, dtype=jnp.int32)
    if "g_single" not in args:
        args["g_single"] = jnp.zeros(G, dtype=bool)
    if "g_decl" not in args:
        CW = args["g_match"].shape[1] if "g_match" in args else 1
        args["g_decl"] = jnp.zeros((G, CW), dtype=jnp.uint32)
    if "g_match" not in args:
        args["g_match"] = jnp.zeros((G, args["g_decl"].shape[1]), dtype=jnp.uint32)
    # g_sown/g_smatch (and g_decl/g_match, g_aneed/g_amatch) are
    # width-paired: default each from its partner's shape so a caller
    # supplying only one cannot produce mismatched class axes
    if "g_sown" not in args:
        C = args["g_smatch"].shape[1] if "g_smatch" in args else 1
        args["g_sown"] = jnp.full((G, C), UNCAPPED, dtype=jnp.int32)
    if "g_smatch" not in args:
        args["g_smatch"] = jnp.zeros((G, args["g_sown"].shape[1]), dtype=bool)
    if "g_aneed" not in args:
        A = args["g_amatch"].shape[1] if "g_amatch" in args else 1
        args["g_aneed"] = jnp.zeros((G, A), dtype=bool)
    if "g_amatch" not in args:
        args["g_amatch"] = jnp.zeros((G, args["g_aneed"].shape[1]), dtype=bool)
    if "g_tier" not in args:
        args["g_tier"] = jnp.zeros(G, dtype=jnp.int32)
    # existing-node tensors default to one inert node (zero capacity);
    # when the caller supplied none, phase A is compiled out entirely
    C = args["g_sown"].shape[1]
    CW = args["g_decl"].shape[1]
    if with_existing is None:
        with_existing = "e_avail" in args
    if "e_avail" not in args:
        R = args["g_demand"].shape[1]
        args["e_avail"] = jnp.zeros((1, R), dtype=jnp.float32)
    E = args["e_avail"].shape[0]
    if "ge_ok" not in args:
        args["ge_ok"] = jnp.zeros((G, E), dtype=bool)
    if "e_npods" not in args:
        args["e_npods"] = jnp.zeros(E, dtype=jnp.int32)
    if "e_scnt" not in args:
        args["e_scnt"] = jnp.zeros((E, C), dtype=jnp.int32)
    if "e_decl" not in args:
        args["e_decl"] = jnp.zeros((E, CW), dtype=jnp.uint32)
    if "e_match" not in args:
        args["e_match"] = jnp.zeros((E, CW), dtype=jnp.uint32)
    if "e_aff" not in args:
        args["e_aff"] = jnp.zeros((E, args["g_aneed"].shape[1]), dtype=jnp.int32)
    if "m_minv" not in args:
        args["m_minv"] = jnp.zeros(args["m_overhead"].shape[0], dtype=jnp.int32)
    if use_pallas is None:
        # NOTE callers that cache jitted wrappers must resolve the flag
        # HOST-side and key their cache on it (models/solver.py does) or
        # the first trace freezes the choice — vmapped/sharded callers
        # pass False explicitly
        use_pallas = pallas_enabled()
    F, price, tmpl_full = feasibility(
        args["g_mask"], args["g_has"], args["g_demand"],
        args["t_mask"], args["t_has"], args["t_alloc"],
        args["g_zone_allowed"], args["g_ct_allowed"],
        args["off_zone"], args["off_ct"], args["off_avail"], args["off_price"],
        args["g_tmpl_ok"], args["m_mask"], args["m_has"],
        g_tol=args.get("g_tol"), t_tol=args.get("t_tol"),
        m_tol=args.get("m_tol"),
        use_pallas=use_pallas,
    )
    out = pack(
        args["g_demand"], args["g_count"], args["g_mask"], args["g_has"], F, tmpl_full,
        args["g_bin_cap"], args["g_single"], args["g_decl"], args["g_match"],
        args["g_sown"], args["g_smatch"], args["g_aneed"], args["g_amatch"],
        args["g_tier"],
        args["ge_ok"], args["e_avail"], args["e_npods"], args["e_scnt"],
        args["e_decl"], args["e_match"], args["e_aff"],
        args["t_alloc"], args["t_cap"], args["t_tmpl"], args["m_mask"], args["m_has"],
        args["m_overhead"], args["m_limits"], args["m_minv"], max_bins=max_bins,
        with_existing=with_existing, level_bits=level_bits, max_minv=max_minv,
    )
    out["F"] = F
    out["price"] = price
    return out
