from karpenter_tpu.ops.tensorize import DeviceSnapshot, tensorize  # noqa: F401

__all__ = ["DeviceSnapshot", "tensorize"]
