"""Snapshot compiler: pods + catalog → dense device tensors.

This is the TPU-native reformulation of the reference's constraint checking
(SURVEY.md §2.2): label requirements become bitmasks over interned per-key
value vocabularies, resource fits become dense demand/allocatable matrices,
and taint/offering checks fold into per-group/per-type boolean tensors. The
pack kernel (ops/kernels.py) then consumes this snapshot.

Design notes:
- Pods are deduplicated into GROUPS by scheduling signature. Real bursts are
  dominated by a few deployment templates, so G << P; the kernel scans groups
  (not pods), which is what makes 50k pods tractable in one device call.
- Complement requirements (NotIn/Exists/Gt/Lt) are materialized against the
  closed type-side vocabulary, which is sound because overlap is only ever
  evaluated against type/template values, all of which are interned.
- The one-way Compatible rule (custom labels undefined on the claim are
  denied — requirements.go:174) is per (group, template) and becomes the
  g_tmpl_ok tensor.

Existing-node delta contract
----------------------------

``tensorize_existing`` compiles the WHOLE fleet from scratch: O(E×G) for
admission plus an O(E) Python loop over node state. Steady-state disruption
rounds mutate only a handful of nodes between generations, so
:class:`ExistingSnapshot` maintains itself by deltas instead
(``apply_delta``), fed from the structured journal ``state/cluster.py``
emits alongside every ``consolidation_state`` generation bump:

* **What patches.** Node-scoped changes only. A *dirty* node (pod
  bind/unbind/delete on it, label/taint/capacity update, claim flap) has
  its row — ``e_avail``/``e_npods``/``e_scnt``/``e_decl``/``e_match``/
  ``e_aff`` and its ``ge_ok`` column — recomputed from live state by
  running ``tensorize_existing`` over just that node and splicing the
  result, so a patched row is bit-identical to a from-scratch build by
  construction. An *added* node appends a row; a *removed* node is MASKED
  in place (``live[row] = False``, zero capacity, admission denied) rather
  than compacted, keeping the E axis — and therefore the pow-2 padded
  shape the kernels compile against — stable as the fleet shrinks.
* **What invalidates.** Anything that changes the GROUP or TYPE side of
  the snapshot the rows are indexed against: nodepool/daemonset events
  (solver inputs), a pod whose scheduling signature matches no existing
  group (new vocabulary/group set), topology-compiled plans (the waves
  domain counts are position-dependent), nodepool limits (usage drifts
  with every node change), and any opaque journal entry. Consumers
  (ops/consolidate.py ``DisruptionSnapshot.advance``) fall back to a full
  rebuild in every such case — the delta layer is an optimization, never
  the only correct path.
* **Accounting.** ``STATS`` tracks tensorize/delta wall clock and the
  ``karpenter_tensorize_negative_avail_total`` counter records every
  negative availability the build clamps to zero (a node whose bound pods
  exceed its allocatable is a capacity-accounting bug that must surface,
  not vanish into ``max(v, 0.0)``). Every build/delta additionally opens
  a ``cache``-kind span on the reconcile flight recorder
  (:mod:`karpenter_tpu.obs`), and a negative-avail clamp marks the
  current round anomalous — its full span tree dumps as Chrome trace
  JSON, so the round that tensorized the bad state is on disk, not just
  counted. The pow-2 shape ladder these tensors feed is itself accounted
  downstream: every dispatch records its padding waste and compile-ledger
  family on the device-plane telemetry
  (:mod:`karpenter_tpu.obs.devplane`; metric semantics in
  deploy/README.md, "Device-plane & SLO telemetry").

Group-row cache contract
------------------------

``tensorize`` additionally caches each group's packed requirement rows
(``g_mask``/``g_has``/``g_tol``/``g_tmpl_ok``/``g_zone_allowed``/
``g_ct_allowed``) keyed on **(pod scheduling signature, waves
extra-requirement fingerprint)** — the provisioning-side analog of the
existing-node delta layer: most pod signatures recur between batcher
ticks, so steady-state rounds (and the doubled re-runs within one solve)
skip the per-group mask/template build entirely.

* **Where it lives.** Inside the type-side cache entry (``_TYPE_CACHE``),
  whose key already fingerprints templates (requirements, weights,
  taints), catalog identity AND mutable offering state, the group
  requirement-value universe, and the resource axis. Any change on those
  axes resolves to a DIFFERENT type-side entry whose row cache starts
  empty — rows can never be served across a vocabulary change; that is
  the entire invalidation contract, enforced by
  tests/test_tensorize_cache.py.
* **What keys a row.** The raw-spec signature (:func:`pod_signature`,
  which covers selectors, affinity, resources, tolerations, labels and
  topology fields) plus the compiled plan's per-group extra requirements
  (zone pins / IN-sets), so the same deployment template landing in
  different zone subgroups keys different rows.
* **Safety.** Cached rows are COPIES both ways (stored from and assigned
  into the snapshot arrays), so mutating a snapshot never corrupts the
  cache; the cache is bounded (``_ROW_CACHE_MAX``) with FIFO eviction.
* **Accounting.** ``STATS["group_row_hits"/"group_row_misses"]``, echoed
  per solve in ``TPUSolver.last_device_stats`` and per grid row by the
  perf harness.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from karpenter_tpu.api import labels as wk
from karpenter_tpu.scheduling import (
    NOT_IN,
    DOES_NOT_EXIST,
    Requirements,
    Taints,
    pod_requirements,
)
from karpenter_tpu import obs
from karpenter_tpu.utils import resources as resutil

WORD = 32

# spread-class cap sentinel: caps at or above OWNED_MIN mean "this group does
# not own the class" (waves writes UNCAPPED; the kernels test >= OWNED_MIN so
# padding/rounding can never turn an uncapped row into a cap).
# native/kernel.cpp mirrors these values — keep them in sync.
UNCAPPED = 1 << 30
SPREAD_OWNED_MIN = 1 << 29

# process-wide tensorize accounting, read by the perf harness (`python -m
# perf --json 4`) — a plain dict instead of the metrics registry because
# tensorize runs below the layers that carry one (the negative-avail count
# ALSO lands on a registry counter for the scrape; see tensorize_existing)
STATS = {
    "existing_calls": 0,
    "existing_ms": 0.0,
    "delta_applies": 0,
    "delta_rows": 0,
    "negative_avail_total": 0,
    # signature-keyed group-row cache (see tensorize): packed requirement
    # rows reused across provisioning rounds/batches
    "group_row_hits": 0,
    "group_row_misses": 0,
    # decoder merged-mask re-checks skipped because the bin's requirement
    # set was provably decomposable (models/solver.py _compat_entry —
    # single-group disjoint-template bins AND the partitioned-shard
    # multi-group extension)
    "decode_exact_skips": 0,
}

# the scrape-plane family name lives in operator/metrics.py
# (TENSORIZE_NEGATIVE_AVAIL); resolved lazily at the increment site so this
# low-level module never imports the operator package at import time


def _bits_for(n_values: int) -> int:
    return max(1, (n_values + WORD - 1) // WORD)


def bucket(n: int, lo: int = 16) -> int:
    """Next shape bucket (>= lo) so XLA compiles one executable per shape
    family — shared by the solver and the batched consolidation probe so
    their compile caches agree. Above 256 the ladder adds 3·2^k steps
    (384, 768, 1536, 3072, …): the pack scan's wall clock is proportional
    to the padded group/bin axes, and pure powers of two waste up to 2× on
    them (grid-5000's 2723 groups padded to 4096; with the intermediate
    step, 3072 — 25% less scan) at the cost of at most one extra compile
    per size family."""
    import math

    n = max(n, 1)
    p = 1 << math.ceil(math.log2(n))
    if n > 256:
        three = 3 << max(math.ceil(math.log2(n / 3)), 0)
        if three >= n:
            p = min(p, three)
    return max(lo, p)


def pad_to(a: np.ndarray, shape: tuple, fill=0) -> np.ndarray:
    """Zero- (or fill-) pad `a` up to `shape` (prefix slices preserved)."""
    out = np.full(shape, fill, dtype=a.dtype) if fill else np.zeros(shape, dtype=a.dtype)
    out[tuple(slice(0, s) for s in a.shape)] = a
    return out


# every solve-arg tensor that rides the group (G) axis on its leading dim —
# the slicing vocabulary for the partitioned mesh solve's per-shard bundle
# views (parallel/mesh.py) and the one list a new group-axis tensor family
# must join to reach the shards. ge_ok is [G,E]: group-axis leading, so it
# slices here too (the partitioned path never sees it — existing nodes are
# a partition blocker — but the view helper stays total).
GROUP_AXIS_KEYS = frozenset({
    "g_mask", "g_has", "g_tol", "g_demand", "g_count", "g_zone_allowed",
    "g_ct_allowed", "g_tmpl_ok", "g_bin_cap", "g_single", "g_decl",
    "g_match", "g_sown", "g_smatch", "g_aneed", "g_amatch", "g_tier",
    "ge_ok",
})


def shard_view(args: dict, lo: int, hi: int, g_pad: int) -> dict:
    """Per-shard bundle view of a solve arg dict: group-axis tensors are
    sliced to [lo:hi) and zero-padded to ``g_pad`` rows; type/template
    tensors pass through BY REFERENCE (they are shard-invariant, so the
    host pays no copy per shard — placement happens at device_put time).
    Zero padding is inert by the kernels' padded-row contract: count 0
    rows never take, a zero g_sown row only gates itself."""
    out = {}
    for k, v in args.items():
        if k in GROUP_AXIS_KEYS:
            a = np.asarray(v)[lo:hi]
            if a.shape[0] != g_pad:
                a = pad_to(a, (g_pad,) + a.shape[1:])
            out[k] = a
        else:
            out[k] = v
    return out


def splice_rows(dst: np.ndarray, rows, vals) -> np.ndarray:
    """Row-splice ``vals`` into ``dst`` at ``rows`` along the leading axis —
    the delta-maintenance primitive :meth:`ExistingSnapshot.apply_delta`
    uses for dirty existing-node rows, exported so the solver service's
    per-tenant bundle patching (service/session.py) applies the SAME
    in-place row semantics to a cached tensor snapshot. Trailing shapes
    must match; a mismatch raises rather than broadcasting silently."""
    rows = np.atleast_1d(np.asarray(rows, dtype=np.intp))
    vals = np.asarray(vals, dtype=dst.dtype)
    if vals.shape[1:] != dst.shape[1:]:
        raise ValueError(
            f"splice_rows: trailing shape {vals.shape[1:]} != {dst.shape[1:]}"
        )
    if vals.ndim == 0 or vals.shape[0] != rows.shape[0]:
        # a (1,...) vals against k rows would broadcast-replicate one row
        # into every slot with no error — the silent-corruption class this
        # primitive's checks exist to reject
        raise ValueError(
            f"splice_rows: {rows.shape[0]} rows != "
            f"{vals.shape[0] if vals.ndim else 'scalar'} replacement rows"
        )
    dst[rows] = vals
    return dst


@dataclass
class DeviceSnapshot:
    # vocabularies
    keys: list  # requirement keys (K)
    key_index: dict
    vocab: dict  # key -> {value: bit index}
    resources: list  # resource names (R)
    W: int

    # groups (G)
    groups: list  # list[list[Pod]] in FFD order
    group_reqs: list  # list[Requirements]
    group_demand: list  # list[ResourceList] per-pod demand in float64
    g_demand: np.ndarray  # [G,R] f32
    g_count: np.ndarray  # [G] i32
    g_mask: np.ndarray  # [G,K,W] u32
    g_has: np.ndarray  # [G,K] bool
    g_tol: np.ndarray  # [G,K] bool operator NotIn/DoesNotExist (an empty
    # meet with another such requirement is tolerated, requirements.py:249)
    g_tmpl_ok: np.ndarray  # [G,M] bool
    g_bin_cap: np.ndarray  # [G] i32 max pods of the group per bin (waves)
    g_single: np.ndarray  # [G] bool whole group confined to one bin (waves)
    g_decl: np.ndarray  # [G,CW] u32 hostname-anti classes the group declares
    g_match: np.ndarray  # [G,CW] u32 hostname-anti classes matching the group
    g_sown: np.ndarray  # [G,C] i32 per-bin cap where the group owns the
    # hostname-spread class, else UNCAPPED (waves spread classes)
    g_smatch: np.ndarray  # [G,C] bool the class counts this group's pods
    g_aneed: np.ndarray  # [G,A] bool hostname-affinity classes the group
    # owns: it may only land on bins whose matched count is positive
    g_amatch: np.ndarray  # [G,A] bool the class selector matches this group

    # flattened (template, type) axis (T)
    type_refs: list  # [(template_idx, InstanceType)]
    t_mask: np.ndarray  # [T,K,W] u32
    t_has: np.ndarray  # [T,K] bool
    t_tol: np.ndarray  # [T,K] bool (operator NotIn/DoesNotExist: an empty
    # meet with another such requirement is tolerated, requirements.py:249)
    t_alloc: np.ndarray  # [T,R] f32
    t_cap: np.ndarray  # [T,R] f32
    t_tmpl: np.ndarray  # [T] i32

    # offerings (O per type)
    off_zone: np.ndarray  # [T,O] i32 (bit index into zone vocab; -1 = none)
    off_ct: np.ndarray  # [T,O] i32
    off_avail: np.ndarray  # [T,O] bool
    off_price: np.ndarray  # [T,O] f32 risk-discounted EFFECTIVE price:
    # nominal × (1 + λ·risk) per cloudprovider/types.effective_price — the
    # ONE vector that makes every price consumer (kernel scoring, probe
    # prefilters, _prefix_criterion's same-type ladder) risk-aware with no
    # new dispatch path; bit-identical to nominal at λ=0
    g_zone_allowed: np.ndarray  # [G,Vz] bool
    g_ct_allowed: np.ndarray  # [G,Vc] bool

    # templates (M)
    templates: list
    m_mask: np.ndarray  # [M,K,W] u32
    m_has: np.ndarray  # [M,K] bool
    m_tol: np.ndarray  # [M,K] bool (NotIn/DoesNotExist operators)
    m_overhead: np.ndarray  # [M,R] f32
    m_limits: np.ndarray  # [M,R] f32 (inf where unconstrained)
    m_minv: np.ndarray  # [M] i32 required distinct instance types (minValues)

    ineligible_pods: list = field(default_factory=list)
    # [T,O] f32 RESOLVED interruption-risk signal (unknown → the
    # KARPENTER_SPOT_RISK_DEFAULT prior at build time): NOT a kernel arg —
    # the kernel only ever sees the effective off_price above. The sidecar
    # exists so the λ-discount is auditable from the snapshot alone:
    # price × (1 + λ·off_risk) always reproduces off_price (the parity
    # suite rides this; /introspect-style diagnostics read the signal
    # without re-walking the catalog)
    off_risk: np.ndarray | None = None
    # priority-tier axis (fused cluster round, deploy/README.md "Fused
    # cluster round"): per-group tier rank in the scan's fencing order —
    # HIGHER tier packs FIRST, so lower tiers only ever see residual
    # capacity, replacing the admission plane's re-tensorize-per-tier
    # cascade with one dispatch. None/1 means single-tier (every solve
    # before the fused round, and every consolidation probe).
    g_tier: np.ndarray | None = None  # [G] i32
    n_tiers: int = 1

    @property
    def G(self):
        return len(self.groups)

    @property
    def T(self):
        return len(self.type_refs)

    def mask_set(self, reqs) -> tuple:
        """(mask [K,W], has [K], tol [K]) for an arbitrary merged
        Requirements over this snapshot's interned vocabulary — the host-side
        analog of the group/type mask build, used by the decoder's vectorized
        joint-compatibility check. `tol` mirrors Intersects' tolerance rule
        (requirements.py:249): an empty meet is allowed iff BOTH operators
        are NotIn/DoesNotExist — NOT the complement flag (Gt/Lt/Exists are
        complements but operator Exists, and DoesNotExist is not)."""
        K = len(self.keys)
        mask = np.zeros((K, self.W), dtype=np.uint32)
        has = np.zeros(K, dtype=bool)
        tol = np.zeros(K, dtype=bool)
        for r in reqs.values():
            if r.key == wk.HOSTNAME_LABEL or r.key not in self.key_index:
                continue
            k = self.key_index[r.key]
            has[k] = True
            tol[k] = r.operator in (NOT_IN, DOES_NOT_EXIST)
            mask[k] = _materialize_mask(r, self.vocab[r.key], self.W)
        return mask, has, tol

    def alloc64(self) -> np.ndarray:
        """[T,R] float64 allocatable from the source dicts (memoized) — the
        f32 device tensors are too coarse at memory-byte scale for the
        decoder's exact host-side checks."""
        a = getattr(self, "_alloc64", None)
        if a is None:
            a = np.array(
                [
                    [it.allocatable().get(r, 0.0) for r in self.resources]
                    for _, it in self.type_refs
                ],
                dtype=np.float64,
            ).reshape(len(self.type_refs), len(self.resources))
            self._alloc64 = a
        return a

    def cap64(self) -> np.ndarray:
        """[T,R] float64 capacity from the source dicts (memoized)."""
        c = getattr(self, "_cap64", None)
        if c is None:
            c = np.array(
                [
                    [it.capacity.get(r, 0.0) for r in self.resources]
                    for _, it in self.type_refs
                ],
                dtype=np.float64,
            ).reshape(len(self.type_refs), len(self.resources))
            self._cap64 = c
        return c


@dataclass
class ExistingSnapshot:
    """Existing/in-flight nodes as pre-loaded kernel bins
    (existingnode.go:40-120 compiled to tensors): fixed available capacity,
    per-group admission (taints + STRICT label compatibility — a node's
    labels are concrete, so a pod key the node doesn't define fails, unlike
    the claim-side well-known allowance), and topology class state seeded
    from the nodes' current pods."""

    nodes: list  # ExistingNode, index-aligned with the E axis
    e_avail: np.ndarray  # [E,R] f32 available minus remaining daemon reserve
    ge_ok: np.ndarray  # [G,E] bool group may land on node
    e_npods: np.ndarray  # [E] i32 current pod count (fill priority)
    e_scnt: np.ndarray  # [E,C] i32 spread-class counts from current pods
    e_decl: np.ndarray  # [E,CW] u32 anti classes declared by current pods
    e_match: np.ndarray  # [E,CW] u32 anti classes matching current pods
    e_aff: np.ndarray  # [E,A] i32 affinity-class matched-pod counts
    # delta-maintenance bookkeeping (module docstring "Existing-node delta
    # contract"): provider id -> row, and which rows still represent live
    # nodes (removed nodes are masked in place, never compacted, so the E
    # axis — and the pow-2 pad family over it — is stable as E shrinks)
    row_of: dict = field(default_factory=dict)
    live: np.ndarray | None = None

    def __post_init__(self):
        if self.live is None:
            self.live = np.ones(len(self.nodes), dtype=bool)
        if not self.row_of and self.nodes:
            self.row_of = {
                n.state_node.provider_id: i for i, n in enumerate(self.nodes)
            }

    @property
    def E(self):
        return len(self.nodes)

    def apply_delta(self, snap, dirty=(), removed=(), added=(),
                    device_plan=None, registry=None):
        """Patch this snapshot in place instead of re-tensorizing the fleet.

        ``dirty``: ExistingNodes (already present) whose rows are rebuilt
        from live state; ``removed``: provider ids whose rows are masked;
        ``added``: ExistingNodes appended as new rows. Dirty and added rows
        are computed by running :func:`tensorize_existing` over exactly
        those nodes and splicing the result, so a patched row is
        bit-identical to a from-scratch build by construction. Raises
        KeyError when a dirty node was never tensorized — the caller
        (ops/consolidate.py advance) must route such nodes through
        ``added`` or rebuild."""
        dirty = list(dirty)
        removed = list(removed)
        added = list(added)
        with obs.span("tensorize.delta", kind="cache", dirty=len(dirty),
                      removed=len(removed), added=len(added)):
            return self._apply_delta(snap, dirty, removed, added,
                                     device_plan, registry)

    def _apply_delta(self, snap, dirty, removed, added, device_plan,
                     registry):
        if dirty or added:
            mini = tensorize_existing(snap, dirty + added, device_plan,
                                      registry=registry)
        if dirty:
            rows = np.empty(len(dirty), dtype=np.intp)
            for j, node in enumerate(dirty):
                r = self.row_of[node.state_node.provider_id]
                rows[j] = r
                self.nodes[r] = node
            nd = len(dirty)
            splice_rows(self.e_avail, rows, mini.e_avail[:nd])
            splice_rows(self.e_npods, rows, mini.e_npods[:nd])
            splice_rows(self.e_scnt, rows, mini.e_scnt[:nd])
            splice_rows(self.e_decl, rows, mini.e_decl[:nd])
            splice_rows(self.e_match, rows, mini.e_match[:nd])
            splice_rows(self.e_aff, rows, mini.e_aff[:nd])
            self.ge_ok[:, rows] = mini.ge_ok[:, :nd]
            self.live[rows] = True
        for pid in removed:
            r = self.row_of.get(pid)
            if r is None or not self.live[r]:
                continue
            self.live[r] = False
            self.e_avail[r] = 0.0
            self.ge_ok[:, r] = False
            self.e_npods[r] = 0
            self.e_scnt[r] = 0
            self.e_decl[r] = 0
            self.e_match[r] = 0
            self.e_aff[r] = 0
        if added:
            k = len(dirty)
            E0 = len(self.nodes)
            self.e_avail = np.concatenate([self.e_avail, mini.e_avail[k:]])
            self.ge_ok = np.concatenate([self.ge_ok, mini.ge_ok[:, k:]], axis=1)
            self.e_npods = np.concatenate([self.e_npods, mini.e_npods[k:]])
            self.e_scnt = np.concatenate([self.e_scnt, mini.e_scnt[k:]])
            self.e_decl = np.concatenate([self.e_decl, mini.e_decl[k:]])
            self.e_match = np.concatenate([self.e_match, mini.e_match[k:]])
            self.e_aff = np.concatenate([self.e_aff, mini.e_aff[k:]])
            self.live = np.concatenate(
                [self.live, np.ones(len(added), dtype=bool)])
            for j, node in enumerate(added):
                self.nodes.append(node)
                self.row_of[node.state_node.provider_id] = E0 + j
        STATS["delta_applies"] += 1
        STATS["delta_rows"] += len(dirty) + len(removed) + len(added)


def tensorize_existing(snap: DeviceSnapshot, existing_nodes, device_plan=None,
                       registry=None):
    """Compile ExistingNode capacity into the kernel's pre-loaded-bin
    tensors. `snap` supplies the interned vocabulary/resource axes;
    `device_plan` (waves) supplies the conflict/spread class indices whose
    per-node counts come from each TopologyGroup's hostname domain map.
    `registry` (optional, defaults to the process registry) receives the
    negative-availability counter."""
    with obs.span("tensorize.existing", kind="cache",
                  nodes=len(existing_nodes)):
        return _tensorize_existing(snap, existing_nodes, device_plan,
                                   registry)


def _tensorize_existing(snap, existing_nodes, device_plan, registry):
    import time

    from karpenter_tpu.api import labels as wk
    from karpenter_tpu.scheduling import Taints as TaintSet

    t_start = time.perf_counter()
    E = len(existing_nodes)
    G = snap.G
    R = len(snap.resources)
    K = len(snap.keys)
    CW = snap.g_decl.shape[1]
    C = snap.g_sown.shape[1]
    A = snap.g_aneed.shape[1]

    e_avail = np.zeros((E, R), dtype=np.float32)
    ge_ok = np.zeros((G, E), dtype=bool)
    e_npods = np.zeros(E, dtype=np.int32)
    e_scnt = np.zeros((E, C), dtype=np.int32)
    e_decl = np.zeros((E, CW), dtype=np.uint32)
    e_match = np.zeros((E, CW), dtype=np.uint32)
    e_aff = np.zeros((E, A), dtype=np.int32)

    e_mask = np.zeros((E, K, snap.W), dtype=np.uint32)
    e_has = np.zeros((E, K), dtype=bool)
    negative = 0
    neg_example = None
    for e, node in enumerate(existing_nodes):
        avail = resutil.subtract(node.cached_available, node.requests)
        for r, v in avail.items():
            if r in snap.resources:
                if v < 0.0:
                    # a bound-pod total exceeding allocatable is a capacity-
                    # accounting bug upstream — clamping keeps the kernel
                    # sound (a full node just admits nothing) but the clamp
                    # must be VISIBLE, not a silent max()
                    negative += 1
                    if neg_example is None:
                        neg_example = (node.state_node.name, r, v)
                e_avail[e, snap.resources.index(r)] = max(v, 0.0)
        e_mask[e], e_has[e], _ = snap.mask_set(node.requirements)
        e_npods[e] = len(node.state_node.pods)
        hostname = node.state_node.hostname
        if device_plan is not None:
            for c, pair in enumerate(device_plan.anti_tgs_by_class):
                direct, inverse = pair
                if direct.domains.get(hostname, 0) > 0:
                    e_match[e, c // WORD] |= np.uint32(1 << (c % WORD))
                if inverse is not None and inverse.domains.get(hostname, 0) > 0:
                    e_decl[e, c // WORD] |= np.uint32(1 << (c % WORD))
            for c, tg in enumerate(device_plan.spread_tgs_by_class):
                e_scnt[e, c] = tg.domains.get(hostname, 0)
            for c, tg in enumerate(device_plan.aff_tgs_by_class):
                e_aff[e, c] = tg.domains.get(hostname, 0)

    # strict requirement compatibility over the interned masks: every key
    # the group requires must be defined on the node AND overlap. Values a
    # node carries outside the vocabulary mask to zero, which is exact for
    # IN (the pod's interned values genuinely differ) and conservative for
    # complement operators (routes to the host loop).
    for g in range(G):
        gm, gh = snap.g_mask[g], snap.g_has[g]
        # a key overlaps if ANY word overlaps; required keys must be defined
        ov = ((e_mask & gm[None]) != 0).any(axis=2)  # [E,K]
        ge_ok[g] = (~gh[None, :] | (e_has & ov)).all(axis=1)

    # taints + hostname checks: nodes share a handful of distinct taint
    # profiles, so toleration is evaluated once per (profile, group), not
    # per (node, group) — the E×G Python loop collapses to
    # O(distinct-profiles × G) (a fleet of 1000 nodes typically has <5)
    hreqs = [
        snap.group_reqs[g].get_req(wk.HOSTNAME_LABEL)
        if wk.HOSTNAME_LABEL in snap.group_reqs[g]
        else None
        for g in range(G)
    ]
    tol_cache: dict = {}  # taint fingerprint -> [G] bool tolerates
    for e, node in enumerate(existing_nodes):
        taints = node.state_node.taints()
        fp = tuple((t.key, t.value, t.effect) for t in taints)
        tol = tol_cache.get(fp)
        if tol is None:
            ts = TaintSet(taints)
            tol = np.array(
                [ts.tolerates(snap.groups[g][0]) is None for g in range(G)],
                dtype=bool,
            )
            tol_cache[fp] = tol
        ge_ok[:, e] &= tol
        for g in range(G):
            if hreqs[g] is not None and ge_ok[g, e]:
                if not hreqs[g].has(node.state_node.hostname):
                    ge_ok[g, e] = False

    if negative:
        import logging

        from karpenter_tpu.operator import metrics as _m

        STATS["negative_avail_total"] += negative
        if registry is None:
            registry = _m.REGISTRY
        registry.counter(
            _m.TENSORIZE_NEGATIVE_AVAIL,
            "negative node availabilities clamped to zero during "
            "tensorization (capacity-accounting bug upstream)",
        ).inc(negative)
        name, res, v = neg_example
        # anomaly trigger: a clamp means capacity accounting went wrong
        # UPSTREAM of this build — the flight recorder keeps the round
        # that tensorized the bad state (obs module contract)
        obs.anomaly("negative-avail", registry=registry, count=negative,
                    node=name, resource=res)
        logging.getLogger(__name__).warning(
            "tensorize_existing clamped %d negative availabilities this "
            "round (first: node %s %s=%s)", negative, name, res, v)
    STATS["existing_calls"] += 1
    STATS["existing_ms"] += (time.perf_counter() - t_start) * 1000.0
    return ExistingSnapshot(
        nodes=list(existing_nodes),
        e_avail=e_avail,
        ge_ok=ge_ok,
        e_npods=e_npods,
        e_scnt=e_scnt,
        e_decl=e_decl,
        e_match=e_match,
        e_aff=e_aff,
    )


def kernel_args(snap: DeviceSnapshot, esnap: "ExistingSnapshot | None" = None,
                Gp: int | None = None, Tp: int | None = None,
                Ep: int | None = None, include_counts: bool = True) -> dict:
    """Padded solve_step argument dict — the ONE assembly point shared by
    the full solve (models/solver.py) and the batched consolidation probes
    (ops/consolidate.py). Before this helper each caller assembled its own
    dict and they drifted (g_tol/t_tol/m_tol were once dropped from the
    probe and tainted pools read as intolerable); the lockstep guard in
    tests/test_batched_consolidation.py pins the family list.

    ``include_counts=False`` omits ``g_count``/``e_avail`` — the probes
    carry those on the vmapped batch axis instead of the shared snapshot.

    Padded types are infeasible by construction: zero allocatable fails
    every fit (pods >= 1) and their offerings carry the -1 "no domain"
    sentinel. Padded group rows have count 0, so their sown=0 cap is inert.
    """
    K = snap.g_mask.shape[1]
    W = snap.W
    R = len(snap.resources)
    M = len(snap.templates)
    if Gp is None:
        Gp = bucket(snap.G)
    if Tp is None:
        Tp = bucket(snap.T)
    pad = pad_to
    args = dict(
        g_mask=pad(snap.g_mask, (Gp, K, W)),
        g_has=pad(snap.g_has, (Gp, K)),
        g_tol=pad(snap.g_tol, (Gp, K)),
        g_demand=pad(snap.g_demand, (Gp, R)),
        g_zone_allowed=pad(snap.g_zone_allowed, (Gp, snap.g_zone_allowed.shape[1])),
        g_ct_allowed=pad(snap.g_ct_allowed, (Gp, snap.g_ct_allowed.shape[1])),
        g_tmpl_ok=pad(snap.g_tmpl_ok, (Gp, M)),
        g_bin_cap=pad(snap.g_bin_cap, (Gp,)),
        g_single=pad(snap.g_single, (Gp,)),
        g_decl=pad(snap.g_decl, (Gp, snap.g_decl.shape[1])),
        g_match=pad(snap.g_match, (Gp, snap.g_match.shape[1])),
        g_sown=pad(snap.g_sown, (Gp, snap.g_sown.shape[1])),
        g_smatch=pad(snap.g_smatch, (Gp, snap.g_smatch.shape[1])),
        g_aneed=pad(snap.g_aneed, (Gp, snap.g_aneed.shape[1])),
        g_amatch=pad(snap.g_amatch, (Gp, snap.g_amatch.shape[1])),
        g_tier=pad(
            snap.g_tier if snap.g_tier is not None
            else np.zeros(snap.G, dtype=np.int32),
            (Gp,),
        ),
        t_mask=pad(snap.t_mask, (Tp, K, W)),
        t_has=pad(snap.t_has, (Tp, K)),
        t_tol=pad(snap.t_tol, (Tp, K)),
        t_alloc=pad(snap.t_alloc, (Tp, R)),
        t_cap=pad(snap.t_cap, (Tp, R)),
        t_tmpl=pad(snap.t_tmpl, (Tp,)),
        off_zone=pad(snap.off_zone, (Tp, snap.off_zone.shape[1]), fill=-1),
        off_ct=pad(snap.off_ct, (Tp, snap.off_ct.shape[1]), fill=-1),
        off_avail=pad(snap.off_avail, (Tp, snap.off_avail.shape[1])),
        off_price=pad(snap.off_price, (Tp, snap.off_price.shape[1])),
        m_mask=snap.m_mask,
        m_has=snap.m_has,
        m_tol=snap.m_tol,
        m_overhead=snap.m_overhead,
        m_limits=snap.m_limits,
        m_minv=snap.m_minv,
    )
    if include_counts:
        args["g_count"] = pad(snap.g_count, (Gp,))
    if esnap is not None:
        if Ep is None:
            Ep = bucket(max(esnap.E, 1), lo=8)
        args.update(
            ge_ok=pad(esnap.ge_ok, (Gp, Ep)),
            e_npods=pad(esnap.e_npods, (Ep,)),
            e_scnt=pad(esnap.e_scnt, (Ep, esnap.e_scnt.shape[1])),
            e_decl=pad(esnap.e_decl, (Ep, esnap.e_decl.shape[1])),
            e_match=pad(esnap.e_match, (Ep, esnap.e_match.shape[1])),
            e_aff=pad(esnap.e_aff, (Ep, esnap.e_aff.shape[1])),
        )
        if include_counts:
            args["e_avail"] = pad(esnap.e_avail, (Ep, R))
    return args


def pod_signature(pod) -> tuple:
    """Scheduling-equivalence key for pod deduplication.

    Derived from the RAW spec fields, not the canonical Requirements — two
    pods with identical specs always produce identical tensors, so grouping
    on spec tuples is sound, and it skips building 50k Requirements objects
    on the burst path (spec-equivalent-but-differently-written pods merely
    split into separate groups, which costs a few rows, not correctness).
    """
    ns = tuple(sorted(pod.node_selector.items()))
    res = tuple(sorted(pod.requests.items()))
    cont = tuple(
        tuple(sorted((c.get("requests") or {}).items())) for c in pod.containers or ()
    )
    init = tuple(
        tuple(sorted((c.get("requests") or {}).items()))
        for c in pod.init_containers or ()
    )
    ovh = tuple(sorted(pod.overhead.items()))
    aff, tol_sig, lbl, spread, pa = _signature_tail(pod)
    return (ns, aff, res, cont, init, ovh, tol_sig, lbl, spread, pa)


# the tail of a pod with no affinity/tolerations/labels/spread — the shape
# that dominates deployment bursts. One shared constant instead of five
# fresh empty tuples per pod: at 500k first-sight pods the empty-component
# tuple builds were the bulk of the remaining per-pod signature cost.
_EMPTY_TAIL = ((), (), (), (), ())


def _signature_tail(pod) -> tuple:
    """The signature components ``Pod.clone`` deep-copies (so identity
    memos can never share them): (aff, tol_sig, lbl, spread, pa). Shared
    by :func:`pod_signature` and the batch path so both assemble the exact
    same tuple shape."""
    if (pod.affinity is None and not pod.tolerations
            and not pod.metadata.labels
            and not pod.topology_spread_constraints):
        return _EMPTY_TAIL
    aff = ()
    if pod.affinity is not None and pod.affinity.node_affinity is not None:
        aff = tuple(
            tuple(
                (e.key, e.operator, tuple(e.values), e.min_values)
                for e in term.match_expressions
            )
            for term in pod.affinity.node_affinity.required
        )
    tol_sig = tuple(sorted((t.key, t.operator, t.value, t.effect) for t in pod.tolerations))
    # labels: topology selectors match on them, so the waves compiler needs
    # label-homogeneous groups to reason per-representative
    lbl = tuple(sorted(pod.metadata.labels.items()))
    # topology fields: pods with distinct spread/affinity constraints must
    # not share a group — the waves compiler plans topology PER GROUP
    spread = tuple(
        (
            c.topology_key,
            c.max_skew,
            c.when_unsatisfiable,
            c.min_domains,
            _selector_sig(c.label_selector),
        )
        for c in pod.topology_spread_constraints or ()
    )
    pa = ()
    if pod.affinity is not None:
        for kind, block in (
            ("aff", pod.affinity.pod_affinity),
            ("anti", pod.affinity.pod_anti_affinity),
        ):
            if block is None:
                continue
            pa += tuple(
                (kind, t.topology_key, _selector_sig(t.label_selector),
                 tuple(sorted(t.namespaces)), req)
                for req, terms in (("req", block.required),)
                for t in terms
            )
            pa += tuple(
                (kind, w.pod_affinity_term.topology_key,
                 _selector_sig(w.pod_affinity_term.label_selector),
                 tuple(sorted(w.pod_affinity_term.namespaces)), "pref")
                for w in block.preferred
            )
    return (aff, tol_sig, lbl, spread, pa)


def _selector_sig(sel):
    if sel is None:
        return None
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple((e.key, e.operator, tuple(sorted(e.values))) for e in sel.match_expressions),
    )


# process-wide signature intern pool: equal signatures collapse to ONE
# canonical tuple, so every downstream dict keyed on signatures
# (sig_to_group, the group-row cache, group_by_signature itself) compares
# by identity first instead of walking two deep nested tuples. Bounded:
# a signature-vocabulary blowup (adversarial label churn) clears the pool
# rather than growing without limit — interning is an optimization, never
# a correctness dependency.
_SIG_INTERN: dict = {}
_SIG_INTERN_MAX = 8192


def intern_signature(sig: tuple) -> tuple:
    """The canonical instance of an equal signature tuple."""
    canon = _SIG_INTERN.get(sig)
    if canon is None:
        if len(_SIG_INTERN) >= _SIG_INTERN_MAX:
            _SIG_INTERN.clear()
        _SIG_INTERN[sig] = canon = sig
    return canon


def interned_signature(pod) -> tuple:
    """``pod_signature`` with the ``_sig_cache`` memo and the intern pool
    applied — the per-pod entry point every consumer outside the batch path
    should use (ops/consolidate.py's sig_to_group registrations do)."""
    d = pod.__dict__
    sig = d.get("_sig_cache")
    if sig is None:
        sig = d["_sig_cache"] = intern_signature(pod_signature(pod))
    return sig


def batch_signatures(pods) -> list:
    """Signatures for one tensorize batch at once (ROADMAP's ~35 µs/pod
    first-sight interning burn-down): replica stamps share their spec
    sub-objects by reference (a Deployment stamps every replica from one
    template; ``Pod.clone`` keeps ``requests``/``node_selector``/
    ``containers`` shared), so per-CALL identity memos skip re-tupling
    those components per pod, and the finished tuple lands in the
    process-wide intern pool so later rounds hash one canonical object per
    distinct shape. Components clones deep-copy (affinity, tolerations,
    labels, spread) are recomputed per pod — they are empty on the burst
    shapes that dominate, and correctness never depends on sharing."""
    out = [None] * len(pods)
    ns_m: dict = {}
    res_m: dict = {}
    cont_m: dict = {}
    init_m: dict = {}
    ovh_m: dict = {}
    # whole-signature identity memo for tail-free pods: replica stamps
    # share every signature-bearing sub-object by reference (requests /
    # node_selector / containers ride Pod.clone untouched), so a burst of
    # N pods over S shapes pays S tuple builds + S intern hashes, not N —
    # the per-pod-hash burn-down the 500k first round needs. Pods with a
    # non-empty tail (affinity/tolerations/labels/spread) never enter:
    # clone deep-copies those, so identity can't vouch for them.
    whole_m: dict = {}
    for i, pod in enumerate(pods):
        d = pod.__dict__
        sig = d.get("_sig_cache")
        if sig is not None:
            out[i] = sig
            continue
        tail_free = (pod.affinity is None and not pod.tolerations
                     and not pod.metadata.labels
                     and not pod.topology_spread_constraints)
        wkey = None
        if tail_free:
            wkey = (id(pod.node_selector) if pod.node_selector else 0,
                    id(pod.requests) if pod.requests else 0,
                    id(pod.containers) if pod.containers else 0,
                    id(pod.init_containers) if pod.init_containers else 0,
                    id(pod.overhead) if pod.overhead else 0)
            sig = whole_m.get(wkey)
            if sig is not None:
                out[i] = d["_sig_cache"] = sig
                continue
        # empty components skip the memo outright: per-pod default
        # containers (a fresh empty list each) would miss on every id and
        # pay the bookkeeping for nothing
        sel = pod.node_selector
        if not sel:
            ns = ()
        else:
            ns = ns_m.get(id(sel))
            if ns is None:
                ns = ns_m[id(sel)] = tuple(sorted(sel.items()))
        req = pod.requests
        if not req:
            res = ()
        else:
            res = res_m.get(id(req))
            if res is None:
                res = res_m[id(req)] = tuple(sorted(req.items()))
        if not pod.containers:
            cont = ()
        else:
            cont = cont_m.get(id(pod.containers))
            if cont is None:
                cont = cont_m[id(pod.containers)] = tuple(
                    tuple(sorted((c.get("requests") or {}).items()))
                    for c in pod.containers
                )
        if not pod.init_containers:
            init = ()
        else:
            init = init_m.get(id(pod.init_containers))
            if init is None:
                init = init_m[id(pod.init_containers)] = tuple(
                    tuple(sorted((c.get("requests") or {}).items()))
                    for c in pod.init_containers
                )
        if not pod.overhead:
            ovh = ()
        else:
            ovh = ovh_m.get(id(pod.overhead))
            if ovh is None:
                ovh = ovh_m[id(pod.overhead)] = tuple(
                    sorted(pod.overhead.items()))
        # the remaining components are pod-owned copies (clone deep-copies
        # them): one shared tail builder keeps both paths assembling the
        # exact same tuple shape
        aff, tol_sig, lbl, spread, pa = _signature_tail(pod)
        sig = intern_signature(
            (ns, aff, res, cont, init, ovh, tol_sig, lbl, spread, pa))
        out[i] = d["_sig_cache"] = sig
        if wkey is not None:
            whole_m[wkey] = sig
    return out


def group_by_signature(pods) -> list:
    """list[list[Pod]] grouped by scheduling signature (unsorted)."""
    by_sig: dict = {}
    get_group = by_sig.get
    sigs = batch_signatures(pods)
    for pod, sig in zip(pods, sigs):
        grp = get_group(sig)
        if grp is None:
            by_sig[sig] = [pod]
        else:
            grp.append(pod)
    return list(by_sig.values())


def device_basic_eligible(pod) -> bool:
    """Spec features the device path can express at all; topology-constraint
    support is decided per GROUP by the waves compiler (ops/waves.py).
    Preferred terms need the relaxation ladder, which is host-side."""
    if pod.affinity is not None:
        a = pod.affinity
        if a.pod_affinity and a.pod_affinity.preferred:
            return False
        if a.pod_anti_affinity and a.pod_anti_affinity.preferred:
            return False
        if a.node_affinity and (a.node_affinity.preferred or len(a.node_affinity.required) > 1):
            return False
    if getattr(pod, "host_ports", None) or getattr(pod, "volumes", None):
        return False
    if any(c.get("ports") for c in pod.containers or []):
        return False
    return True


def device_eligible(pod) -> bool:
    """Pods the topology-free device path handles without a waves plan."""
    if pod.affinity and (pod.affinity.pod_affinity or pod.affinity.pod_anti_affinity):
        return False
    if pod.topology_spread_constraints:
        return False
    return device_basic_eligible(pod)


def _materialize_mask(req, vocab_k: dict, W: int) -> np.ndarray:
    mask = np.zeros(W, dtype=np.uint32)
    for value, bit in vocab_k.items():
        if req.has(value):
            mask[bit // WORD] |= np.uint32(1 << (bit % WORD))
    return mask


def _req_fingerprint(reqs: Requirements) -> tuple:
    return tuple(
        sorted(
            (r.key, r.complement, tuple(sorted(r.values)), r.greater_than,
             r.less_than, r.min_values)
            for r in reqs.values()
        )
    )


def _template_fingerprint(tpl) -> tuple:
    return (
        tpl.nodepool_name,
        tpl.weight,
        _req_fingerprint(tpl.requirements),
        tuple(sorted((t.key, t.value, t.effect) for t in tpl.taints)),
    )


# type-side tensors are a pure function of (templates, catalog, the group
# requirement universe, the resource axis) — all static between solves in
# steady state, so they are memoized across calls. Entries hold strong refs
# to the catalog objects, keeping the id()-based fingerprint stable.
_TYPE_CACHE: dict = {}
_TYPE_CACHE_MAX = 8
# per-type-side-entry group-row cache bound (signatures, not bytes: each
# row tuple is a few hundred bytes)
_ROW_CACHE_MAX = 8192
# per-type-side-entry decoder compat-entry bound (models/solver.py
# _compat_entry): distinct (template, group-signature-set) bins
_COMPAT_CACHE_MAX = 8192


def _build_type_side(templates, instance_types_by_pool, group_reqs, resources):
    from karpenter_tpu.cloudprovider.types import (
        default_risk,
        effective_price as _effective_price,
        risk_lambda,
    )

    # the risk-discount weight AND the unknown-risk prior are part of the
    # type-side identity: a λ or prior flip (perf legs, operator reconfig)
    # must re-price the cached tensors, not serve stale effective prices
    lam = risk_lambda()
    prior = default_risk()
    key = (
        tuple(_template_fingerprint(t) for t in templates),
        tuple(
            (
                t.nodepool_name,
                # identity + mutable offering state: flipping an offering's
                # available/price/risk in place (the standard ICE-handling
                # pattern) must miss the cache, not serve stale tensors
                tuple(
                    (id(it), tuple((o.available, o.price,
                                    o.interruption_risk)
                                   for o in it.offerings))
                    for it in instance_types_by_pool.get(t.nodepool_name, ())
                ),
            )
            for t in templates
        ),
        (lam, prior),
        frozenset(
            (r.key, r.complement, tuple(sorted(r.values)), r.greater_than, r.less_than)
            for reqs in group_reqs
            for r in reqs.values()
        ),
        tuple(resources),
    )
    cached = _TYPE_CACHE.get(key)
    if cached is not None:
        return cached

    r_index = {r: i for i, r in enumerate(resources)}

    # ---- key/value vocabularies ----
    # collect from type requirements, template requirements, group concrete values
    def iter_reqs():
        for tpl in templates:
            for r in tpl.requirements.values():
                yield r
            for it in instance_types_by_pool.get(tpl.nodepool_name, []):
                for r in it.requirements.values():
                    yield r
                for o in it.offerings:
                    for r in o.requirements.values():
                        yield r
        for reqs in group_reqs:
            for r in reqs.values():
                yield r

    vocab: dict = {}
    for r in iter_reqs():
        if r.key == wk.HOSTNAME_LABEL:
            continue
        vocab.setdefault(r.key, {})
        # concrete and complement (NotIn) values both intern — a NotIn value
        # only matters when it also appears on the type side, and Gt/Lt are
        # resolved through req.has() at mask materialization
        for v in r.values:
            vocab[r.key].setdefault(v, len(vocab[r.key]))
    keys = sorted(vocab.keys())
    key_index = {k: i for i, k in enumerate(keys)}
    K = len(keys)
    W = _bits_for(max((len(v) for v in vocab.values()), default=1))
    M = len(templates)

    def build_mask_set(reqs: Requirements):
        mask = np.zeros((K, W), dtype=np.uint32)
        has = np.zeros(K, dtype=bool)
        for r in reqs.values():
            if r.key == wk.HOSTNAME_LABEL or r.key not in key_index:
                continue
            k = key_index[r.key]
            has[k] = True
            mask[k] = _materialize_mask(r, vocab[r.key], W)
        return mask, has

    # ---- templates ----
    m_mask = np.zeros((M, K, W), dtype=np.uint32)
    m_has = np.zeros((M, K), dtype=bool)
    m_tol = np.zeros((M, K), dtype=bool)
    # kernel-enforced minValues floor: required distinct instance types per
    # claim (cloudprovider/types.go:165-199). Only the instance-type key is
    # modeled on device — minValues on other keys stays a decode-time exact
    # check that kicks violating bins to the host loop.
    m_minv = np.zeros(M, dtype=np.int32)
    for m, tpl in enumerate(templates):
        m_mask[m], m_has[m] = build_mask_set(tpl.requirements)
        for r in tpl.requirements.values():
            if r.key in key_index:
                m_tol[m, key_index[r.key]] = r.operator in (NOT_IN, DOES_NOT_EXIST)
            if r.key == wk.INSTANCE_TYPE_LABEL and r.min_values:
                m_minv[m] = int(r.min_values)

    # ---- flattened (template, type) axis; pre-filter type vs template ----
    type_refs = []
    for m, tpl in enumerate(templates):
        for it in instance_types_by_pool.get(tpl.nodepool_name, []):
            if it.requirements.intersects(tpl.requirements) is not None:
                continue
            if not it.offerings.available().has_compatible(tpl.requirements):
                continue
            type_refs.append((m, it))
    T = len(type_refs)
    O = max((len(it.offerings) for _, it in type_refs), default=1)

    t_mask = np.zeros((T, K, W), dtype=np.uint32)
    t_has = np.zeros((T, K), dtype=bool)
    t_tol = np.zeros((T, K), dtype=bool)
    t_alloc = np.zeros((T, len(resources)), dtype=np.float32)
    t_cap = np.zeros((T, len(resources)), dtype=np.float32)
    t_tmpl = np.zeros(T, dtype=np.int32)
    off_zone = np.full((T, O), -1, dtype=np.int32)
    off_ct = np.full((T, O), -1, dtype=np.int32)
    off_avail = np.zeros((T, O), dtype=bool)
    off_price = np.full((T, O), np.inf, dtype=np.float32)
    off_risk = np.zeros((T, O), dtype=np.float32)

    zone_vocab = vocab.get(wk.TOPOLOGY_ZONE_LABEL, {})
    ct_vocab = vocab.get(wk.CAPACITY_TYPE_LABEL, {})

    for t, (m, it) in enumerate(type_refs):
        t_tmpl[t] = m
        t_mask[t], t_has[t] = build_mask_set(it.requirements)
        for r in it.requirements.values():
            if r.key in key_index:
                t_tol[t, key_index[r.key]] = r.operator in (NOT_IN, DOES_NOT_EXIST)
        alloc = it.allocatable()
        for r, v in alloc.items():
            if r in r_index:
                t_alloc[t, r_index[r]] = max(v, 0.0)
        for r, v in it.capacity.items():
            if r in r_index:
                t_cap[t, r_index[r]] = v
        for o, off in enumerate(it.offerings):
            off_zone[t, o] = zone_vocab.get(off.zone, -1)
            off_ct[t, o] = ct_vocab.get(off.capacity_type, -1)
            off_avail[t, o] = off.available
            # the risk-discounted EFFECTIVE price (identity at λ=0):
            # provisioning, the probe ladders, and filterByPrice all read
            # this tensor, so one number makes the whole plane risk-aware
            off_price[t, o] = _effective_price(off, lam)
            # the sidecar stores the RESOLVED risk (unknown → the prior),
            # so recomputing price × (1 + λ·off_risk) always reproduces
            # off_price — the audit contract the parity suite rides
            off_risk[t, o] = (off.interruption_risk
                              if off.interruption_risk is not None
                              else prior)

    cached = dict(
        vocab=vocab, keys=keys, key_index=key_index, W=W,
        build_mask_set=build_mask_set,
        m_mask=m_mask, m_has=m_has, m_tol=m_tol, m_minv=m_minv,
        type_refs=type_refs, t_mask=t_mask, t_has=t_has, t_tol=t_tol,
        t_alloc=t_alloc, t_cap=t_cap, t_tmpl=t_tmpl,
        off_zone=off_zone, off_ct=off_ct, off_avail=off_avail,
        off_price=off_price, off_risk=off_risk,
        zone_vocab=zone_vocab, ct_vocab=ct_vocab,
        # strong refs to EVERY catalog object (template-filtered ones too):
        # the id()-based cache key is only stable while nothing in the
        # fingerprinted pool can be garbage-collected and its address reused
        _refs=[list(instance_types_by_pool.get(t.nodepool_name, ())) for t in templates],
    )
    if len(_TYPE_CACHE) >= _TYPE_CACHE_MAX:
        _TYPE_CACHE.pop(next(iter(_TYPE_CACHE)))
    _TYPE_CACHE[key] = cached
    return cached


def tensorize(
    pods,
    templates,
    instance_types_by_pool,
    daemon_overhead=None,
    limits=None,
    device_plan=None,
    tier_of=None,
):
    """Compile a scheduling snapshot to tensors.

    pods: eligible pods (caller pre-filters with device_eligible); ignored
        when device_plan is given
    templates: [ClaimTemplate] in weight order
    instance_types_by_pool: nodepool name -> [InstanceType]
    daemon_overhead: nodepool name -> ResourceList
    limits: nodepool name -> ResourceList (remaining resources; absent = inf)
    device_plan: pre-compiled waves.WavesPlan (topology-compiled subgroups
        with extra requirements / bin caps / conflict classes), groups
        already in the order the scan should process them
    tier_of: pod uid -> priority-tier rank (higher = packs first). Splits
        signature groups per tier and orders the scan tier-major so the
        fused admission round fences tiers on device (deploy/README.md
        "Fused cluster round"). Ignored when device_plan is given — the
        topology path keeps the host cascade.
    """
    with obs.span("tensorize.build", kind="cache",
                  plan=device_plan is not None):
        return _tensorize(pods, templates, instance_types_by_pool,
                          daemon_overhead, limits, device_plan, tier_of)


def _tensorize(pods, templates, instance_types_by_pool, daemon_overhead,
               limits, device_plan, tier_of=None):
    daemon_overhead = daemon_overhead or {}
    limits = limits or {}

    if device_plan is not None:
        device_groups = device_plan.device_groups
        groups = [dg.pods for dg in device_groups]
        group_reqs = []
        row_keys = []
        for dg in device_groups:
            rep = dg.pods[0]
            reqs = pod_requirements(rep)
            if dg.extra_reqs:
                reqs = reqs.copy()
                reqs.add(*dg.extra_reqs)
            group_reqs.append(reqs)
            sig = interned_signature(rep)
            # waves extra reqs (zone pins/IN-sets) key the row alongside
            # the spec signature: the same deployment template lands in
            # different zone subgroups with different packed rows
            extras_fp = tuple(
                (r.key, r.complement, tuple(sorted(r.values)),
                 r.greater_than, r.less_than, r.min_values)
                for r in dg.extra_reqs
            )
            row_keys.append((sig, extras_fp))
        g_bin_cap_list = [dg.bin_cap for dg in device_groups]
        g_single_list = [dg.single_bin for dg in device_groups]
        g_decl, g_match = device_plan.class_masks()
        g_sown, g_smatch = device_plan.spread_tensors()
        g_aneed, g_amatch = device_plan.aff_tensors()
        g_tier_list = [0] * len(groups)
    else:
        # ---- group pods by signature, FFD order ----
        # the signature is cached on the pod object: the provisioner
        # re-solves the same (immutable-spec) Pod instances round after
        # round; clones (which relaxation/injection mutate) are fresh
        # objects without the cached attribute
        base_groups = group_by_signature(pods)
        if tier_of:
            # sub-split each signature group by priority tier: the scan IS
            # the fence (tier-major order below), so pods of one spec but
            # different tiers must occupy distinct rows to pack in their
            # tier's turn. Same-signature rows share a row_key — the row
            # cache content is tier-independent, so sharing stays sound.
            n_base = len(base_groups)
            split = []
            for g in base_groups:
                by_tier: dict = {}
                for p in g:
                    by_tier.setdefault(tier_of.get(p.uid, 0), []).append(p)
                split.extend(by_tier.values())
            base_groups = split
            # tier-axis pad-waste site: group rows that exist ONLY for
            # tier fencing (the split's inflation) are the axis's extra
            # scan cost — recorded so a fused-round row-count blowup is
            # attributed to the tier axis, not read as organic G growth
            from karpenter_tpu.obs import devplane as _devplane

            _devplane.record_padding("solve.tiers", n_base, len(split))

        def _tier(g):
            return tier_of.get(g[0].uid, 0) if tier_of else 0

        groups = sorted(
            base_groups,
            key=lambda g: (
                -_tier(g),
                -g[0].effective_requests().get(resutil.CPU, 0.0),
                -g[0].effective_requests().get(resutil.MEMORY, 0.0),
            ),
        )
        g_tier_list = [_tier(g) for g in groups]
        group_reqs = [pod_requirements(g[0]) for g in groups]
        # group_by_signature cached the signature on every rep
        row_keys = [(g[0].__dict__["_sig_cache"], ()) for g in groups]
        g_bin_cap_list = [1 << 30] * len(groups)
        g_single_list = [False] * len(groups)
        g_decl = np.zeros((len(groups), 1), dtype=np.uint32)
        g_match = np.zeros((len(groups), 1), dtype=np.uint32)
        g_sown = np.full((len(groups), 1), UNCAPPED, dtype=np.int32)
        g_smatch = np.zeros((len(groups), 1), dtype=bool)
        g_aneed = np.zeros((len(groups), 1), dtype=bool)
        g_amatch = np.zeros((len(groups), 1), dtype=bool)
    group_demand = [g[0].effective_requests() for g in groups]

    # ---- resource dimension union ----
    res_names = {resutil.CPU, resutil.MEMORY, resutil.PODS}
    for d in group_demand:
        res_names.update(d.keys())
    resources = sorted(res_names)
    r_index = {r: i for i, r in enumerate(resources)}

    ts = _build_type_side(templates, instance_types_by_pool, group_reqs, resources)
    vocab, keys, key_index, W = ts["vocab"], ts["keys"], ts["key_index"], ts["W"]
    build_mask_set = ts["build_mask_set"]
    type_refs = ts["type_refs"]
    zone_vocab, ct_vocab = ts["zone_vocab"], ts["ct_vocab"]
    K = len(keys)
    M = len(templates)
    G = len(groups)

    # ---- per-solve template tensors (overhead/limits change per round) ----
    m_mask, m_has, m_tol = ts["m_mask"], ts["m_has"], ts["m_tol"]
    m_minv = ts["m_minv"]
    m_overhead = np.zeros((M, len(resources)), dtype=np.float32)
    m_limits = np.full((M, len(resources)), np.inf, dtype=np.float32)
    for m, tpl in enumerate(templates):
        for r, v in daemon_overhead.get(tpl.nodepool_name, {}).items():
            if r in r_index:
                m_overhead[m, r_index[r]] = v
        for r, v in limits.get(tpl.nodepool_name, {}).items():
            if r in r_index:
                m_limits[m, r_index[r]] = v
    t_mask, t_has, t_tol = ts["t_mask"], ts["t_has"], ts["t_tol"]
    t_alloc, t_cap, t_tmpl = ts["t_alloc"], ts["t_cap"], ts["t_tmpl"]
    off_zone, off_ct = ts["off_zone"], ts["off_ct"]
    off_avail, off_price = ts["off_avail"], ts["off_price"]

    # ---- groups ----
    R = len(resources)
    g_demand = np.zeros((G, R), dtype=np.float32)
    g_count = np.zeros(G, dtype=np.int32)
    g_mask = np.zeros((G, K, W), dtype=np.uint32)
    g_has = np.zeros((G, K), dtype=bool)
    g_tol = np.zeros((G, K), dtype=bool)
    g_tmpl_ok = np.zeros((G, M), dtype=bool)
    g_zone_allowed = np.ones((G, max(len(zone_vocab), 1)), dtype=bool)
    g_ct_allowed = np.ones((G, max(len(ct_vocab), 1)), dtype=bool)
    g_bin_cap = np.asarray(g_bin_cap_list, dtype=np.int32).reshape(G)
    g_single = np.asarray(g_single_list, dtype=bool).reshape(G)
    g_tier = np.asarray(g_tier_list, dtype=np.int32).reshape(G)
    n_tiers = int(g_tier.max()) + 1 if G else 1

    # signature-keyed row cache: the packed requirement rows are a pure
    # function of (pod signature, waves extra reqs) GIVEN this type-side
    # entry — vocabulary, templates, catalog, and the resource axis are all
    # pinned by the ts cache key, so any change there lands in a fresh ts
    # dict with an empty row cache (the invalidation contract; see the
    # module docstring). Most pod signatures recur between batcher ticks,
    # so steady-state rounds skip the whole per-group mask/template build.
    row_cache = ts.setdefault("row_cache", {})
    for g, (pods_g, reqs) in enumerate(zip(groups, group_reqs)):
        for r, v in group_demand[g].items():
            g_demand[g, r_index[r]] = v
        g_count[g] = len(pods_g)
        rk = row_keys[g]
        cached_row = row_cache.get(rk)
        if cached_row is not None:
            (g_mask[g], g_has[g], g_tol[g], g_tmpl_ok[g],
             g_zone_allowed[g], g_ct_allowed[g]) = cached_row
            STATS["group_row_hits"] += 1
            continue
        g_mask[g], g_has[g] = build_mask_set(reqs)
        for r in reqs.values():
            if r.key in key_index:
                g_tol[g, key_index[r.key]] = r.operator in (NOT_IN, DOES_NOT_EXIST)
        pod0 = pods_g[0]
        for m, tpl in enumerate(templates):
            ok = Taints(tpl.taints).tolerates(pod0) is None
            if ok:
                # one-way Compatible: custom labels undefined on the template
                # are denied unless NotIn/DoesNotExist (requirements.go:174)
                for r in reqs.values():
                    if r.key in wk.WELL_KNOWN_LABELS or r.key == wk.HOSTNAME_LABEL:
                        continue
                    if r.key in tpl.requirements:
                        continue
                    if r.operator in (NOT_IN, DOES_NOT_EXIST):
                        continue
                    ok = False
                    break
            g_tmpl_ok[g, m] = ok
        if wk.TOPOLOGY_ZONE_LABEL in reqs:
            zr = reqs.get_req(wk.TOPOLOGY_ZONE_LABEL)
            for v, bit in zone_vocab.items():
                g_zone_allowed[g, bit] = zr.has(v)
        if wk.CAPACITY_TYPE_LABEL in reqs:
            cr = reqs.get_req(wk.CAPACITY_TYPE_LABEL)
            for v, bit in ct_vocab.items():
                g_ct_allowed[g, bit] = cr.has(v)
        STATS["group_row_misses"] += 1
        if len(row_cache) >= _ROW_CACHE_MAX:
            row_cache.pop(next(iter(row_cache)))
        row_cache[rk] = (
            g_mask[g].copy(), g_has[g].copy(), g_tol[g].copy(),
            g_tmpl_ok[g].copy(), g_zone_allowed[g].copy(),
            g_ct_allowed[g].copy(),
        )

    snap = DeviceSnapshot(
        keys=keys,
        key_index=key_index,
        vocab=vocab,
        resources=resources,
        W=W,
        groups=groups,
        group_reqs=group_reqs,
        group_demand=group_demand,
        g_demand=g_demand,
        g_count=g_count,
        g_mask=g_mask,
        g_has=g_has,
        g_tol=g_tol,
        g_tmpl_ok=g_tmpl_ok,
        type_refs=type_refs,
        t_mask=t_mask,
        t_has=t_has,
        t_tol=t_tol,
        t_alloc=t_alloc,
        t_cap=t_cap,
        t_tmpl=t_tmpl,
        off_zone=off_zone,
        off_ct=off_ct,
        off_avail=off_avail,
        off_price=off_price,
        g_zone_allowed=g_zone_allowed,
        g_ct_allowed=g_ct_allowed,
        g_bin_cap=g_bin_cap,
        g_single=g_single,
        g_decl=g_decl,
        g_match=g_match,
        g_sown=g_sown,
        g_smatch=g_smatch,
        g_aneed=g_aneed,
        g_amatch=g_amatch,
        templates=list(templates),
        m_mask=m_mask,
        m_has=m_has,
        m_tol=m_tol,
        m_minv=m_minv,
        m_overhead=m_overhead,
        m_limits=m_limits,
        off_risk=ts["off_risk"],
        g_tier=g_tier,
        n_tiers=n_tiers,
    )
    # decoder fast-path state: per-group signature keys plus the type-side
    # entry's persistent compat cache. Entries are pure functions of
    # (template index, group signature set) GIVEN this ts entry — the same
    # invalidation contract as the group-row cache above — so the decoder
    # can reuse a bin's candidate-type set across solves and rounds.
    snap.row_keys = row_keys
    snap.compat_cache = ts.setdefault("compat_cache", {})
    return snap
