from karpenter_tpu.api import labels  # noqa: F401
from karpenter_tpu.api.objects import (  # noqa: F401
    Node,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec, NodeClaimStatus  # noqa: F401
from karpenter_tpu.api.nodepool import (  # noqa: F401
    Budget,
    Disruption,
    NodePool,
    NodePoolSpec,
    NodePoolStatus,
)

__all__ = [
    "labels",
    "Node", "ObjectMeta", "Pod", "PodDisruptionBudget", "Taint",
    "Toleration", "TopologySpreadConstraint",
    "NodeClaim", "NodeClaimSpec", "NodeClaimStatus",
    "Budget", "Disruption", "NodePool", "NodePoolSpec", "NodePoolStatus",
]
