"""NodePool: the user-facing pool template.

Field semantics from the reference's pkg/apis/v1beta1/nodepool.go:
NodePoolSpec :40, Disruption :64 (consolidationPolicy :139-144), Budget
:102-136 (count/percent nodes, cron schedule + duration, per-reason),
GetAllowedDisruptions :271, Budget.IsActive :318, Limits.ExceededBy
(nodepool_status.go).
"""

from __future__ import annotations

import hashlib
import json
import math
import time
from dataclasses import dataclass, field

from karpenter_tpu.api.conditions import ConditionedObject
from karpenter_tpu.api.objects import ObjectMeta
from karpenter_tpu.utils import resources as resutil
from karpenter_tpu.utils.cron import parse_schedule

CONSOLIDATION_WHEN_EMPTY = "WhenEmpty"
CONSOLIDATION_WHEN_UNDERUTILIZED = "WhenUnderutilized"

# disruption reasons (v1beta1 uses one budget list for all reasons unless set)
REASON_UNDERUTILIZED = "Underutilized"
REASON_EMPTY = "Empty"
REASON_DRIFTED = "Drifted"
REASON_EXPIRED = "Expired"
ALL_REASONS = (REASON_UNDERUTILIZED, REASON_EMPTY, REASON_DRIFTED, REASON_EXPIRED)
# spot interruption is INVOLUNTARY disruption: the provider reclaims the
# capacity whether or not a budget window is open, so the proactive drain
# is not budget-gated and the reason stays OUT of ALL_REASONS (budgets
# bound voluntary disruption only — the reference's interruption
# controller takes the same stance)
REASON_INTERRUPTED = "Interrupted"


@dataclass
class Budget:
    """Active-window cap on concurrent disruptions (nodepool.go:102-136)."""

    nodes: str = "10%"  # absolute count ("5") or percentage ("10%")
    schedule: str | None = None  # cron, UTC; None = always active
    duration: float | None = None  # seconds the window stays open
    reasons: list | None = None  # None = applies to all reasons

    def is_active(self, now: float | None = None) -> bool:
        """True when the budget window is open (Budget.IsActive nodepool.go:318)."""
        if self.schedule is None and self.duration is None:
            return True
        try:
            sched = parse_schedule(self.schedule or "* * * * *")
        except ValueError:
            # invalid schedules are rejected at admission by the validation
            # controller; at runtime an unparseable budget is inert
            return False
        if self.duration is None:
            # schedule without duration: the window never closes, so the
            # budget is simply always active (CEL validation in the
            # reference requires the pair to be set together)
            return True
        now = time.time() if now is None else now
        # Active iff a firing occurred within the last `duration`; bounding
        # the lookback keeps sparse schedules (@yearly) off the hot path.
        lookback = int(self.duration // 60) + 2
        last = sched.prev(now, lookback_minutes=lookback)
        return last is not None and last <= now < last + self.duration

    def allowed(self, total_nodes: int, now: float | None = None) -> int:
        if not self.is_active(now):
            return total_nodes  # inactive budget imposes no cap
        s = str(self.nodes).strip()
        if s.endswith("%"):
            # percentages round UP (intstr.GetScaledValueFromIntOrPercent
            # with roundUp=true in GetAllowedDisruptions): a 10% budget on a
            # 1-node pool still allows one disruption
            return int(math.ceil(total_nodes * float(s[:-1]) / 100.0))
        return int(s)


@dataclass
class Disruption:
    consolidation_policy: str = CONSOLIDATION_WHEN_UNDERUTILIZED
    consolidate_after: float | None = None  # seconds; None = immediate for WhenUnderutilized
    expire_after: float | None = None  # seconds; None = Never
    budgets: list = field(default_factory=lambda: [Budget(nodes="10%")])


@dataclass
class NodeClaimTemplate:
    """spec.template: metadata + claim spec stamped onto every NodeClaim."""

    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    # NodeClaimSpec fields (pkg/apis/v1beta1/nodeclaim.go:26)
    taints: list = field(default_factory=list)
    startup_taints: list = field(default_factory=list)
    requirements: list = field(default_factory=list)  # [NodeSelectorRequirement]
    resource_requests: dict = field(default_factory=dict)
    kubelet: dict = field(default_factory=dict)
    node_class_ref: dict = field(default_factory=dict)  # {"kind","name","apiVersion"}


@dataclass
class NodePoolSpec:
    template: NodeClaimTemplate = field(default_factory=NodeClaimTemplate)
    disruption: Disruption = field(default_factory=Disruption)
    limits: dict = field(default_factory=dict)  # resource name -> quantity
    weight: int = 0


@dataclass
class NodePoolStatus:
    resources: dict = field(default_factory=dict)  # aggregated owned-node resources
    conditions: list = field(default_factory=list)


@dataclass
class NodePool(ConditionedObject):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodePoolSpec = field(default_factory=NodePoolSpec)
    status: NodePoolStatus = field(default_factory=NodePoolStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def allowed_disruptions(self, reason: str, total_nodes: int, now: float | None = None) -> int:
        """min over active budgets applying to reason (GetAllowedDisruptions
        nodepool.go:271)."""
        allowed = total_nodes
        for b in self.spec.disruption.budgets:
            if b.reasons is not None and reason not in b.reasons:
                continue
            allowed = min(allowed, b.allowed(total_nodes, now))
        return max(allowed, 0)

    def limits_exceeded(self, usage: dict) -> list:
        """Resources for which usage exceeds spec.limits (Limits.ExceededBy)."""
        return resutil.exceeds(usage, self.spec.limits)

    def static_hash(self) -> str:
        """Hash of drift-relevant static fields (basis of the nodepool-hash
        annotation, nodepool/hash/controller.go:49)."""
        t = self.spec.template
        payload = {
            "labels": t.labels,
            "annotations": t.annotations,
            "taints": [(x.key, x.value, x.effect) for x in t.taints],
            "startup_taints": [(x.key, x.value, x.effect) for x in t.startup_taints],
            "kubelet": t.kubelet,
            "node_class_ref": t.node_class_ref,
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]
