"""NodeClaim: one requested/owned machine.

Field semantics from the reference's pkg/apis/v1beta1/nodeclaim.go
(NodeClaimSpec :26, NodeSelectorRequirementWithMinValues :60) and
nodeclaim_status.go (providerID, capacity/allocatable, conditions
Launched/Registered/Initialized plus disruption conditions
Drifted/Empty/Expired set by pkg/controllers/nodeclaim/disruption).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from karpenter_tpu.api.objects import ObjectMeta

# condition types
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_DRIFTED = "Drifted"
COND_EMPTY = "Empty"
COND_EXPIRED = "Expired"
COND_CONSISTENT = "ConsistentStateFound"
COND_TERMINATING = "Terminating"


@dataclass
class Condition:
    type: str
    status: str = "True"  # True | False | Unknown
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class NodeClaimSpec:
    taints: list = field(default_factory=list)  # [Taint]
    startup_taints: list = field(default_factory=list)
    requirements: list = field(default_factory=list)  # [NodeSelectorRequirement]
    resource_requests: dict = field(default_factory=dict)
    kubelet: dict = field(default_factory=dict)
    node_class_ref: dict = field(default_factory=dict)
    terminate_after: float | None = None


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    image_id: str = ""
    node_name: str = ""
    capacity: dict = field(default_factory=dict)
    allocatable: dict = field(default_factory=dict)
    conditions: list = field(default_factory=list)  # [Condition]


@dataclass
class NodeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    def get_condition(self, cond_type: str) -> Condition | None:
        for c in self.status.conditions:
            if c.type == cond_type:
                return c
        return None

    def set_condition(self, cond_type: str, status: str = "True", reason: str = "", message: str = "", now: float | None = None):
        existing = self.get_condition(cond_type)
        if existing is not None:
            if existing.status != status:
                existing.status = status
                existing.last_transition_time = time.time() if now is None else now
            existing.reason = reason
            existing.message = message
            return existing
        c = Condition(type=cond_type, status=status, reason=reason, message=message,
                      last_transition_time=time.time() if now is None else now)
        self.status.conditions.append(c)
        return c

    def clear_condition(self, cond_type: str):
        self.status.conditions = [c for c in self.status.conditions if c.type != cond_type]

    def is_true(self, cond_type: str) -> bool:
        c = self.get_condition(cond_type)
        return c is not None and c.status == "True"

    @property
    def launched(self) -> bool:
        return self.is_true(COND_LAUNCHED)

    @property
    def registered(self) -> bool:
        return self.is_true(COND_REGISTERED)

    @property
    def initialized(self) -> bool:
        return self.is_true(COND_INITIALIZED)
