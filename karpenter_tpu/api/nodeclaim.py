"""NodeClaim: one requested/owned machine.

Field semantics from the reference's pkg/apis/v1beta1/nodeclaim.go
(NodeClaimSpec :26, NodeSelectorRequirementWithMinValues :60) and
nodeclaim_status.go (providerID, capacity/allocatable, conditions
Launched/Registered/Initialized plus disruption conditions
Drifted/Empty/Expired set by pkg/controllers/nodeclaim/disruption).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.api.conditions import Condition, ConditionedObject
from karpenter_tpu.api.objects import ObjectMeta

__all__ = [
    "Condition",
    "NodeClaim",
    "NodeClaimSpec",
    "NodeClaimStatus",
]

# condition types
COND_LAUNCHED = "Launched"
COND_REGISTERED = "Registered"
COND_INITIALIZED = "Initialized"
COND_DRIFTED = "Drifted"
COND_EMPTY = "Empty"
COND_EXPIRED = "Expired"
COND_CONSISTENT = "ConsistentStateFound"
COND_TERMINATING = "Terminating"


@dataclass
class NodeClaimSpec:
    taints: list = field(default_factory=list)  # [Taint]
    startup_taints: list = field(default_factory=list)
    requirements: list = field(default_factory=list)  # [NodeSelectorRequirement]
    resource_requests: dict = field(default_factory=dict)
    kubelet: dict = field(default_factory=dict)
    node_class_ref: dict = field(default_factory=dict)
    terminate_after: float | None = None


@dataclass
class NodeClaimStatus:
    provider_id: str = ""
    image_id: str = ""
    node_name: str = ""
    capacity: dict = field(default_factory=dict)
    allocatable: dict = field(default_factory=dict)
    conditions: list = field(default_factory=list)  # [Condition]


@dataclass
class NodeClaim(ConditionedObject):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def launched(self) -> bool:
        return self.is_true(COND_LAUNCHED)

    @property
    def registered(self) -> bool:
        return self.is_true(COND_REGISTERED)

    @property
    def initialized(self) -> bool:
        return self.is_true(COND_INITIALIZED)
