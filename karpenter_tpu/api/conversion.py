"""Dual API version support: karpenter.sh/v1beta1 ↔ v1 wire conversion.

Mirror of the reference's staged-version machinery (pkg/apis/apis.go:33-43:
v1beta1 active, v1 staged next; conversion via webhooks,
pkg/webhooks/webhooks.go:82-125). Our storage (hub) objects are the
dataclasses in api/nodepool.py / api/nodeclaim.py — v1beta1-flavored, like
the reference snapshot's storage version — and this module converts wire
documents of EITHER version to and from them, so a client speaking v1 and a
client speaking v1beta1 read/write the same stored object.

The modeled v1 changes (the real karpenter v1 migration):
- `consolidationPolicy: WhenUnderutilized` (v1beta1) is renamed
  `WhenEmptyOrUnderutilized` (v1)
- `spec.disruption.expireAfter` (v1beta1) moves to
  `spec.template.spec.expireAfter` (v1), per-NodeClaim
- `spec.template.spec.kubelet` (v1beta1) leaves the NodePool in v1 (it
  moved to the NodeClass); a v1 encode stashes it in the
  compatibility.karpenter.sh/v1beta1-kubelet-conversion annotation the way
  the real migration did, so nothing is lost crossing versions
- durations are wire strings ("720h", "1h30m", "Never") ↔ hub float seconds
"""

from __future__ import annotations

import json
import re

from karpenter_tpu.api.conditions import Condition
from karpenter_tpu.api.nodeclaim import NodeClaim, NodeClaimSpec, NodeClaimStatus
from karpenter_tpu.api.nodepool import (
    Budget,
    Disruption,
    NodeClaimTemplate,
    NodePool,
    NodePoolSpec,
)
from karpenter_tpu.api.objects import NodeSelectorRequirement, ObjectMeta, Taint

GROUP = "karpenter.sh"
V1BETA1 = f"{GROUP}/v1beta1"
V1 = f"{GROUP}/v1"
VERSIONS = (V1BETA1, V1)

KUBELET_COMPAT_ANNOTATION = "compatibility.karpenter.sh/v1beta1-kubelet-conversion"

_POLICY_TO_V1 = {"WhenUnderutilized": "WhenEmptyOrUnderutilized"}
_POLICY_FROM_V1 = {v: k for k, v in _POLICY_TO_V1.items()}

# "ms" must precede "m" in the alternation or the regex engine commits to
# the minutes unit and strands the trailing "s" ("500ms" read as "500m"+"s")
_DUR = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")
_UNIT_SECONDS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}


class ConversionError(Exception):
    pass


def parse_duration(s) -> float | None:
    """Go-style duration string → seconds; "Never"/None → None."""
    if s is None or s == "Never":
        return None
    if isinstance(s, (int, float)):
        return float(s)
    pos, total = 0, 0.0
    for m in _DUR.finditer(s):
        if m.start() != pos:
            raise ConversionError(f"invalid duration {s!r}")
        total += float(m.group(1)) * _UNIT_SECONDS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ConversionError(f"invalid duration {s!r}")
    return total


def format_duration(seconds: float | None) -> str:
    """Seconds → canonical wire string; None → "Never". Negative inputs
    clamp to "0s": the grammar has no sign, so an unclamped encode would
    emit a wire string ("-1h58m30s") that parse_duration rejects — encode
    must never produce an unparseable document."""
    if seconds is None:
        return "Never"
    # round to the wire resolution FIRST so the residual carries into the
    # coarser units ("1000ms" must canonicalize to "1s", and a
    # sub-half-millisecond residual must vanish rather than render "0ms",
    # which the parse grammar rejects as "0m" + a dangling "s")
    total_ms = int(round(max(float(seconds), 0.0) * 1000))
    s, ms = divmod(total_ms, 1000)
    out = []
    for unit, width in (("h", 3600), ("m", 60), ("s", 1)):
        n, s = divmod(s, width)
        if n:
            out.append(f"{n}{unit}")
    if ms:
        out.append(f"{ms}ms")
    return "".join(out) or "0s"


# ---- shared fragments ---------------------------------------------------

def _conditions_from(items) -> list:
    return [Condition.from_wire(c) for c in items or ()]


def _conditions_to(conds) -> list:
    out = []
    for c in conds:
        if isinstance(c, dict):
            out.append(dict(c))
            continue
        d = {"type": c.type, "status": c.status}
        if c.reason:
            d["reason"] = c.reason
        if c.message:
            d["message"] = c.message
        if c.last_transition_time:
            d["lastTransitionTime"] = c.last_transition_time
        out.append(d)
    return out


def _meta_from(doc: dict) -> ObjectMeta:
    m = doc.get("metadata", {})
    return ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", "default"),
        labels=dict(m.get("labels", {})),
        annotations=dict(m.get("annotations", {})),
    )


def _meta_to(meta: ObjectMeta) -> dict:
    out = {"name": meta.name}
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    return out


def _taints_from(items) -> list:
    return [
        Taint(key=t["key"], value=t.get("value", ""),
              effect=t.get("effect", "NoSchedule"))
        for t in items or ()
    ]


def _taints_to(taints) -> list:
    return [
        {"key": t.key, **({"value": t.value} if t.value else {}),
         "effect": t.effect}
        for t in taints
    ]


def _reqs_from(items) -> list:
    return [
        NodeSelectorRequirement(
            key=r["key"], operator=r.get("operator", "In"),
            values=list(r.get("values", [])), min_values=r.get("minValues"),
        )
        for r in items or ()
    ]


def _reqs_to(reqs) -> list:
    out = []
    for r in reqs:
        d = {"key": r.key, "operator": r.operator}
        if r.values:
            d["values"] = list(r.values)
        if r.min_values is not None:
            d["minValues"] = r.min_values
        out.append(d)
    return out


# ---- NodePool -----------------------------------------------------------

def _nodepool_from(doc: dict, version: str) -> NodePool:
    spec = doc.get("spec", {})
    tpl = spec.get("template", {})
    tpl_meta = tpl.get("metadata", {})
    tpl_spec = tpl.get("spec", {})
    dis = spec.get("disruption", {})

    policy = dis.get("consolidationPolicy", "WhenUnderutilized")
    if version == V1:
        policy = _POLICY_FROM_V1.get(policy, policy)
        expire = parse_duration(tpl_spec.get("expireAfter"))
    else:
        expire = parse_duration(dis.get("expireAfter"))

    kubelet = dict(tpl_spec.get("kubelet", {}))
    meta = _meta_from(doc)
    if version == V1 and not kubelet:
        stash = meta.annotations.get(KUBELET_COMPAT_ANNOTATION)
        if stash:
            kubelet = json.loads(stash)
    # the stash is an encode-time artifact, not hub state: leaving it on the
    # hub object would resurrect a later-cleared kubelet on the next encode
    meta.annotations.pop(KUBELET_COMPAT_ANNOTATION, None)

    status = doc.get("status", {})
    np_ = NodePool(
        metadata=meta,
        spec=NodePoolSpec(
            template=NodeClaimTemplate(
                labels=dict(tpl_meta.get("labels", {})),
                annotations=dict(tpl_meta.get("annotations", {})),
                taints=_taints_from(tpl_spec.get("taints")),
                startup_taints=_taints_from(tpl_spec.get("startupTaints")),
                requirements=_reqs_from(tpl_spec.get("requirements")),
                kubelet=kubelet,
                node_class_ref=dict(tpl_spec.get("nodeClassRef", {})),
            ),
            disruption=Disruption(
                consolidation_policy=policy,
                consolidate_after=parse_duration(dis.get("consolidateAfter")),
                expire_after=expire,
                budgets=[
                    Budget(
                        nodes=b.get("nodes", "10%"),
                        schedule=b.get("schedule"),
                        duration=parse_duration(b.get("duration")),
                        reasons=b.get("reasons"),
                    )
                    for b in dis.get("budgets", [{"nodes": "10%"}])
                ],
            ),
            limits=dict(spec.get("limits", {})),
            weight=spec.get("weight", 0),
        ),
    )
    np_.status.resources = dict(status.get("resources", {}))
    np_.status.conditions = _conditions_from(status.get("conditions"))
    return np_


def _nodepool_to(np: NodePool, version: str) -> dict:
    t = np.spec.template
    d = np.spec.disruption
    meta = _meta_to(np.metadata)

    tpl_spec: dict = {}
    if t.taints:
        tpl_spec["taints"] = _taints_to(t.taints)
    if t.startup_taints:
        tpl_spec["startupTaints"] = _taints_to(t.startup_taints)
    if t.requirements:
        tpl_spec["requirements"] = _reqs_to(t.requirements)
    if t.node_class_ref:
        tpl_spec["nodeClassRef"] = dict(t.node_class_ref)

    policy = d.consolidation_policy
    dis: dict = {}
    if version == V1:
        dis["consolidationPolicy"] = _POLICY_TO_V1.get(policy, policy)
        tpl_spec["expireAfter"] = format_duration(d.expire_after)
        if t.kubelet:
            # the kubelet block left the NodePool in v1; the compatibility
            # annotation preserves it across the version boundary
            meta.setdefault("annotations", {})[KUBELET_COMPAT_ANNOTATION] = (
                json.dumps(t.kubelet, sort_keys=True)
            )
    else:
        dis["consolidationPolicy"] = policy
        dis["expireAfter"] = format_duration(d.expire_after)
        if t.kubelet:
            tpl_spec["kubelet"] = dict(t.kubelet)
    if d.consolidate_after is not None:
        dis["consolidateAfter"] = format_duration(d.consolidate_after)
    dis["budgets"] = [
        {
            "nodes": b.nodes,
            **({"schedule": b.schedule} if b.schedule else {}),
            **({"duration": format_duration(b.duration)}
               if b.duration is not None else {}),
            **({"reasons": list(b.reasons)} if b.reasons is not None else {}),
        }
        for b in d.budgets
    ]

    tpl: dict = {"spec": tpl_spec}
    if t.labels or t.annotations:
        tpl["metadata"] = {
            **({"labels": dict(t.labels)} if t.labels else {}),
            **({"annotations": dict(t.annotations)} if t.annotations else {}),
        }
    spec: dict = {"template": tpl, "disruption": dis}
    if np.spec.limits:
        spec["limits"] = dict(np.spec.limits)
    if np.spec.weight:
        spec["weight"] = np.spec.weight
    out = {
        "apiVersion": version,
        "kind": "NodePool",
        "metadata": meta,
        "spec": spec,
    }
    status: dict = {}
    if np.status.resources:
        status["resources"] = dict(np.status.resources)
    if np.status.conditions:
        status["conditions"] = _conditions_to(np.status.conditions)
    if status:
        out["status"] = status
    return out


# ---- NodeClaim ----------------------------------------------------------

def _nodeclaim_from(doc: dict, version: str) -> NodeClaim:
    spec = doc.get("spec", {})
    if version == V1:
        expire = parse_duration(spec.get("expireAfter"))
    else:
        expire = parse_duration(spec.get("terminateAfter") or spec.get("expireAfter"))
    status = doc.get("status", {})
    meta = _meta_from(doc)
    kubelet = dict(spec.get("kubelet", {}))
    if version == V1 and not kubelet:
        stash = meta.annotations.get(KUBELET_COMPAT_ANNOTATION)
        if stash:
            kubelet = json.loads(stash)
    meta.annotations.pop(KUBELET_COMPAT_ANNOTATION, None)
    return NodeClaim(
        metadata=meta,
        spec=NodeClaimSpec(
            taints=_taints_from(spec.get("taints")),
            startup_taints=_taints_from(spec.get("startupTaints")),
            requirements=_reqs_from(spec.get("requirements")),
            resource_requests=dict(spec.get("resources", {}).get("requests", {})),
            kubelet=kubelet,
            node_class_ref=dict(spec.get("nodeClassRef", {})),
            terminate_after=expire,
        ),
        status=NodeClaimStatus(
            provider_id=status.get("providerID", ""),
            image_id=status.get("imageID", ""),
            node_name=status.get("nodeName", ""),
            capacity=dict(status.get("capacity", {})),
            allocatable=dict(status.get("allocatable", {})),
            conditions=_conditions_from(status.get("conditions")),
        ),
    )


def _nodeclaim_to(nc: NodeClaim, version: str) -> dict:
    meta = _meta_to(nc.metadata)
    spec: dict = {}
    if nc.spec.taints:
        spec["taints"] = _taints_to(nc.spec.taints)
    if nc.spec.startup_taints:
        spec["startupTaints"] = _taints_to(nc.spec.startup_taints)
    if nc.spec.requirements:
        spec["requirements"] = _reqs_to(nc.spec.requirements)
    if nc.spec.resource_requests:
        spec["resources"] = {"requests": dict(nc.spec.resource_requests)}
    if nc.spec.node_class_ref:
        spec["nodeClassRef"] = dict(nc.spec.node_class_ref)
    if version == V1:
        spec["expireAfter"] = format_duration(nc.spec.terminate_after)
        if nc.spec.kubelet:
            # same compatibility stash as the NodePool path: kubelet left
            # the v1 NodeClaim spec but must survive the round trip
            meta.setdefault("annotations", {})[KUBELET_COMPAT_ANNOTATION] = (
                json.dumps(nc.spec.kubelet, sort_keys=True)
            )
    else:
        if nc.spec.kubelet:
            spec["kubelet"] = dict(nc.spec.kubelet)
        if nc.spec.terminate_after is not None:
            spec["terminateAfter"] = format_duration(nc.spec.terminate_after)
    status: dict = {}
    if nc.status.provider_id:
        status["providerID"] = nc.status.provider_id
    if nc.status.image_id:
        status["imageID"] = nc.status.image_id
    if nc.status.node_name:
        status["nodeName"] = nc.status.node_name
    if nc.status.capacity:
        status["capacity"] = dict(nc.status.capacity)
    if nc.status.allocatable:
        status["allocatable"] = dict(nc.status.allocatable)
    if nc.status.conditions:
        status["conditions"] = _conditions_to(nc.status.conditions)
    out = {
        "apiVersion": version,
        "kind": "NodeClaim",
        "metadata": meta,
        "spec": spec,
    }
    if status:
        out["status"] = status
    return out


# ---- public surface -----------------------------------------------------

_DECODERS = {"NodePool": _nodepool_from, "NodeClaim": _nodeclaim_from}
_ENCODERS = {NodePool: _nodepool_to, NodeClaim: _nodeclaim_to}


def decode(doc: dict):
    """Wire document (either version) → hub object. The conversion-webhook
    analog on the read/write path (webhooks.go:82-125)."""
    version = doc.get("apiVersion", "")
    if version not in VERSIONS:
        raise ConversionError(f"unsupported apiVersion {version!r}")
    kind = doc.get("kind", "")
    dec = _DECODERS.get(kind)
    if dec is None:
        raise ConversionError(f"unsupported kind {kind!r}")
    return dec(doc, version)


def encode(obj, version: str) -> dict:
    """Hub object → wire document of the requested version."""
    if version not in VERSIONS:
        raise ConversionError(f"unsupported apiVersion {version!r}")
    enc = _ENCODERS.get(type(obj))
    if enc is None:
        raise ConversionError(f"unsupported object {type(obj).__name__}")
    return enc(obj, version)
