"""Admission-layer spec validation — the webhook/CEL analog.

The reference enforces spec legality twice: CEL markers compiled into the
CRDs (hack/validation/*.sh writing kubebuilder rules into
pkg/apis/v1/nodepool.go) and the conversion/validation webhooks
(pkg/webhooks/webhooks.go:82-125). In this hermetic build the apiserver is
the in-memory store, so the same rules run as an admission hook the store
invokes on create/update of NodePools — an invalid spec is REJECTED at
write time (AdmissionError), not merely marked unready later
(controllers/nodepool/validation.py keeps the runtime re-check that folds
into readiness, mirroring the reference's dual layers).
"""

from __future__ import annotations

import re

from karpenter_tpu.api import labels as wk

VALID_OPERATORS = {"In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"}
VALID_TAINT_EFFECTS = {"NoSchedule", "PreferNoSchedule", "NoExecute"}
VALID_CONSOLIDATION_POLICIES = {"WhenEmpty", "WhenEmptyOrUnderutilized",
                                "WhenUnderutilized"}
# kubebuilder markers: qualified name, 63-char segments
_LABEL_KEY_RE = re.compile(
    r"^([a-z0-9]([-a-z0-9.]*[a-z0-9])?/)?[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$"
)
_LABEL_VALUE_RE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")


class AdmissionError(ValueError):
    """Spec rejected at admission (webhooks.go denial analog)."""


def _validate_requirement(r, where: str) -> list[str]:
    errs = []
    if not r.key or len(r.key) > 316 or not _LABEL_KEY_RE.match(r.key):
        errs.append(f"{where}: invalid label key {r.key!r}")
    op = getattr(r, "operator", "In")
    if op not in VALID_OPERATORS:
        errs.append(f"{where}: invalid operator {op!r}")
    values = list(getattr(r, "values", ()) or ())
    if op == "In" and not values:
        errs.append(f"{where}: operator In requires values")
    if op in ("Exists", "DoesNotExist") and values:
        errs.append(f"{where}: operator {op} must not carry values")
    if op in ("Gt", "Lt"):
        if len(values) != 1 or not re.fullmatch(r"-?\d+", str(values[0])):
            errs.append(f"{where}: operator {op} requires one integer value")
        elif int(values[0]) < 0:
            errs.append(f"{where}: operator {op} value must be >= 0")
    mv = getattr(r, "min_values", None)
    if mv is not None and not (1 <= mv <= 50):
        errs.append(f"{where}: minValues must be in [1,50]")
    for v in values:
        if len(str(v)) > 63 or not _LABEL_VALUE_RE.match(str(v)):
            errs.append(f"{where}: invalid label value {v!r}")
    return errs


def validate_nodepool_admission(np) -> list[str]:
    """CEL/webhook-layer rules; empty list = admitted."""
    errs = []
    spec = np.spec
    # weight is optional (kubebuilder Minimum=1 Maximum=100); 0 means unset
    if spec.weight and not (1 <= spec.weight <= 100):
        errs.append(f"spec.weight: {spec.weight} outside [1,100]")
    for i, r in enumerate(spec.template.requirements):
        errs.extend(_validate_requirement(r, f"spec.template.requirements[{i}]"))
    for key, value in (spec.template.labels or {}).items():
        # format only: RESTRICTED-label rejection is the runtime validation
        # controller's job (controllers/nodepool/validation.py), mirroring
        # the reference's split — CEL checks shape, the controller checks
        # domain policy and folds it into readiness
        if not _LABEL_KEY_RE.match(key or ""):
            errs.append(f"spec.template.labels: invalid key {key!r}")
        if value is not None and not _LABEL_VALUE_RE.match(str(value)):
            errs.append(f"spec.template.labels[{key}]: invalid value {value!r}")
    for i, t in enumerate(spec.template.taints or ()):
        if t.effect not in VALID_TAINT_EFFECTS:
            errs.append(f"spec.template.taints[{i}]: invalid effect {t.effect!r}")
        if not t.key or not _LABEL_KEY_RE.match(t.key):
            errs.append(f"spec.template.taints[{i}]: invalid key {t.key!r}")
    d = spec.disruption
    if d.consolidation_policy and d.consolidation_policy not in VALID_CONSOLIDATION_POLICIES:
        errs.append(
            f"spec.disruption.consolidationPolicy: {d.consolidation_policy!r}"
        )
    if d.consolidate_after is not None and d.consolidate_after < 0:
        errs.append("spec.disruption.consolidateAfter: must be >= 0")
    expire = getattr(d, "expire_after", None)
    if expire is not None and expire < 0:
        errs.append("spec.disruption.expireAfter: must be >= 0")
    for r, v in (spec.limits or {}).items():
        try:
            from karpenter_tpu.utils.resources import parse_quantity

            if parse_quantity(v) < 0:
                errs.append(f"spec.limits[{r}]: negative")
        except Exception:
            errs.append(f"spec.limits[{r}]: unparseable {v!r}")
    return errs


# PriorityClass admission (the scheduling.k8s.io validation rules): user
# classes live in [-HIGHEST_USER_DEFINABLE, HIGHEST_USER_DEFINABLE]; only
# system- prefixed classes may sit in the positive system-reserved band,
# and the NEGATIVE mirror of that band is reserved-and-unusable for
# everyone (admission/priority.py resolves through the same constants).
HIGHEST_USER_DEFINABLE_PRIORITY = 1_000_000_000
SYSTEM_CLASS_PREFIX = "system-"
VALID_PREEMPTION_POLICIES = {"", "PreemptLowerPriority", "Never"}


def validate_priority_class_admission(pc) -> list[str]:
    errs = []
    value = getattr(pc, "value", 0)
    if not isinstance(value, int) or isinstance(value, bool):
        errs.append(f"value: {value!r} is not an integer")
        return errs
    name = pc.metadata.name or ""
    if value < -HIGHEST_USER_DEFINABLE_PRIORITY:
        # the negative system-reserved range: no class — system or user —
        # may claim it (there is nothing below user priorities to reserve)
        errs.append(
            f"value: {value} is below -{HIGHEST_USER_DEFINABLE_PRIORITY} "
            "(negative system-reserved range)"
        )
    elif value > HIGHEST_USER_DEFINABLE_PRIORITY and not name.startswith(
        SYSTEM_CLASS_PREFIX
    ):
        errs.append(
            f"value: {value} exceeds {HIGHEST_USER_DEFINABLE_PRIORITY} "
            f"(system-reserved; only {SYSTEM_CLASS_PREFIX}* classes may use it)"
        )
    policy = getattr(pc, "preemption_policy", "")
    if policy not in VALID_PREEMPTION_POLICIES:
        errs.append(f"preemptionPolicy: invalid {policy!r}")
    return errs


def admit(kind: str, obj):
    """Store admission hook: raise AdmissionError on an illegal spec."""
    if kind == "nodepools":
        errs = validate_nodepool_admission(obj)
        if errs:
            raise AdmissionError("; ".join(errs))
    elif kind == "priorityclasses":
        errs = validate_priority_class_admission(obj)
        if errs:
            raise AdmissionError("; ".join(errs))
