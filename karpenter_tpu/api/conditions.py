"""Shared status-condition helpers for API objects.

The reference gets these from knative/apis condition sets; here one mixin
serves NodeClaim and NodePool (both keep conditions in status.conditions).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Condition:
    type: str
    status: str = "True"  # True | False | Unknown
    reason: str = ""
    message: str = ""
    last_transition_time: float = field(default_factory=time.time)

    @classmethod
    def from_wire(cls, c: dict) -> "Condition":
        """One normalizer for dict-shaped conditions (wire docs, test
        fixtures) — every entry point must share it or the shapes drift.
        A missing transition time reads as NOW: age-gated consumers
        (emptiness consolidate_after, drift ordering) must restart their
        waits rather than treat the condition as epoch-old."""
        return cls(
            type=c["type"], status=c.get("status", "Unknown"),
            reason=c.get("reason", ""), message=c.get("message", ""),
            last_transition_time=c.get("lastTransitionTime") or time.time(),
        )


class ConditionedObject:
    """Mixin for objects with status.conditions: get/set/clear/is_true.

    is_true returns False for a missing condition — callers that want
    "unreconciled means ready" (e.g. the provisioner's nodepool gate) must
    check get_condition() is None explicitly.
    """

    def get_condition(self, cond_type: str):
        for i, c in enumerate(self.status.conditions):
            if isinstance(c, dict):
                if c.get("type") != cond_type:
                    continue
                # normalize dict-shaped conditions in place so set_condition
                # and clear_condition can rely on attribute access
                c = Condition.from_wire(c)
                self.status.conditions[i] = c
                return c
            if c.type == cond_type:
                return c
        return None

    def set_condition(self, cond_type: str, status: str = "True", reason: str = "",
                      message: str = "", now: float | None = None):
        existing = self.get_condition(cond_type)
        if existing is not None:
            if existing.status != status:
                existing.status = status
                existing.last_transition_time = time.time() if now is None else now
            existing.reason = reason
            existing.message = message
            return existing
        c = Condition(type=cond_type, status=status, reason=reason, message=message,
                      last_transition_time=time.time() if now is None else now)
        self.status.conditions.append(c)
        return c

    def clear_condition(self, cond_type: str):
        self.status.conditions = [
            c for c in self.status.conditions
            if (c.get("type") if isinstance(c, dict) else c.type) != cond_type
        ]

    def is_true(self, cond_type: str) -> bool:
        c = self.get_condition(cond_type)
        return c is not None and c.status == "True"
