"""Core Kubernetes-shaped object model.

The reference consumes real k8s API types via client-go; our framework is
self-hosted, so this module defines the minimal-but-faithful pod/node model
that the constraint algebra (scheduling/), the tensorizer (ops/tensorize.py),
and the in-memory apiserver (kube/) all share. Field semantics follow
k8s core/v1 as used by the reference (e.g. Toleration.ToleratesTaint,
TopologySpreadConstraint fields consumed in
pkg/controllers/provisioning/scheduling/topologygroup.go).
"""

from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field, replace

from karpenter_tpu.utils import resources as resutil

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid())
    labels: dict = field(default_factory=dict)
    annotations: dict = field(default_factory=dict)
    finalizers: list = field(default_factory=list)
    owner_references: list = field(default_factory=list)  # [{kind, name, uid, controller}]
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    resource_version: int = 0
    generation: int = 0


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute

    def matches(self, other: "Taint") -> bool:
        # v1.Taint.MatchTaint: key and effect equality
        return self.key == other.key and self.effect == other.effect


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: int | None = None

    def tolerates(self, taint: Taint) -> bool:
        """v1.Toleration.ToleratesTaint semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: list = field(default_factory=list)
    min_values: int | None = None  # NodeSelectorRequirementWithMinValues (nodeclaim.go:60)


@dataclass
class NodeSelectorTerm:
    match_expressions: list = field(default_factory=list)  # [NodeSelectorRequirement]


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class LabelSelector:
    """metav1.LabelSelector: matchLabels AND matchExpressions."""

    match_labels: dict = field(default_factory=dict)
    match_expressions: list = field(default_factory=list)  # [NodeSelectorRequirement]

    def matches(self, labels: dict) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for expr in self.match_expressions:
            val = labels.get(expr.key)
            if expr.operator == "In":
                if val is None or val not in expr.values:
                    return False
            elif expr.operator == "NotIn":
                if val is not None and val in expr.values:
                    return False
            elif expr.operator == "Exists":
                if val is None:
                    return False
            elif expr.operator == "DoesNotExist":
                if val is not None:
                    return False
        return True


@dataclass
class PodAffinityTerm:
    topology_key: str
    label_selector: LabelSelector | None = None
    namespaces: list = field(default_factory=list)
    namespace_selector: LabelSelector | None = None


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm = None


@dataclass
class NodeAffinity:
    required: list = field(default_factory=list)  # [NodeSelectorTerm] (ORed)
    preferred: list = field(default_factory=list)  # [PreferredSchedulingTerm]


@dataclass
class PodAffinity:
    required: list = field(default_factory=list)  # [PodAffinityTerm]
    preferred: list = field(default_factory=list)  # [WeightedPodAffinityTerm]


@dataclass
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity: PodAffinity | None = None
    pod_anti_affinity: PodAffinity | None = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: LabelSelector | None = None
    min_domains: int | None = None
    node_affinity_policy: str = "Honor"  # Honor | Ignore
    node_taints_policy: str = "Ignore"  # Honor | Ignore


@dataclass
class PersistentVolumeClaimRef:
    claim_name: str
    read_only: bool = False


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    volume_name: str = ""  # set once bound to a PV
    requests: dict = field(default_factory=dict)  # {"storage": bytes}
    phase: str = "Pending"  # Pending | Bound


@dataclass
class PersistentVolume:
    """Only the scheduling-relevant shape: required node affinity
    (zone pinning) and the local/hostPath marker that voids hostname
    affinity on reschedule (volumetopology.go:128-152)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    node_affinity_required: list = field(default_factory=list)  # [NodeSelectorTerm] (ORed)
    local: bool = False  # Local or HostPath volume source
    csi_driver: str = ""
    capacity: dict = field(default_factory=dict)


@dataclass
class VolumeAttachment:
    """storage.k8s.io VolumeAttachment: a CSI volume attached to a node.
    The termination controller awaits these draining away before releasing
    a node's finalizer (node/termination awaits volume detachment so
    stateful workloads never lose data to an early instance delete).
    Existence of the object is what blocks — the attach/detach controller
    deletes it once the volume is unmounted."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    attacher: str = ""  # CSI driver name
    node_name: str = ""
    pv_name: str = ""  # spec.source.persistentVolumeName


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""
    # [{key: str, values: [str]}] — first term's matchLabelExpressions
    # (storageclass AllowedTopologies, volumetopology.go:112-125)
    allowed_topologies: list = field(default_factory=list)
    volume_binding_mode: str = "WaitForFirstConsumer"


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    # scheduling inputs
    node_name: str = ""
    node_selector: dict = field(default_factory=dict)
    affinity: Affinity | None = None
    tolerations: list = field(default_factory=list)  # [Toleration]
    topology_spread_constraints: list = field(default_factory=list)
    requests: dict = field(default_factory=dict)  # direct resource requests
    containers: list = field(default_factory=list)  # [{"requests": {...}, "ports": [...]}]
    init_containers: list = field(default_factory=list)
    overhead: dict = field(default_factory=dict)
    host_ports: list = field(default_factory=list)  # [(ip, port, protocol)]
    volumes: list = field(default_factory=list)  # [PersistentVolumeClaimRef | str]
    priority: int | None = None
    priority_class_name: str = ""
    preemption_policy: str = ""
    scheduler_name: str = "default-scheduler"
    # status
    phase: str = "Pending"
    conditions: list = field(default_factory=list)  # [{"type","status","reason"}]
    nominated_node_name: str = ""
    terminating: bool = False

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def effective_requests(self) -> dict:
        return resutil.pod_requests(self)

    def owned_by_daemonset(self) -> bool:
        return any(o.get("kind") == "DaemonSet" for o in self.metadata.owner_references)

    def owner_key(self):
        for o in self.metadata.owner_references:
            if o.get("controller"):
                return (o.get("kind"), self.metadata.namespace, o.get("name"))
        return None

    def clone(self) -> "Pod":
        # affinity/spread/tolerations must be independent: the relaxation
        # ladder (models/preferences.py) mutates them in place
        return replace(
            self,
            metadata=replace(
                self.metadata,
                labels=dict(self.metadata.labels),
                annotations=dict(self.metadata.annotations),
            ),
            affinity=copy.deepcopy(self.affinity),
            tolerations=list(self.tolerations),
            topology_spread_constraints=copy.deepcopy(self.topology_spread_constraints),
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    provider_id: str = ""
    taints: list = field(default_factory=list)  # [Taint]
    startup_taints: list = field(default_factory=list)
    unschedulable: bool = False
    capacity: dict = field(default_factory=dict)
    allocatable: dict = field(default_factory=dict)
    ready: bool = True
    phase: str = "Running"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def labels(self) -> dict:
        return self.metadata.labels


@dataclass
class DaemonSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: "Pod" = None  # pod template stamped per node


@dataclass
class Deployment:
    """Replica workload: the hermetic runtime's replicaset analog — evicted
    pods are recreated so drains actually displace work."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    template: "Pod" = None


@dataclass
class Lease:
    """coordination.k8s.io Lease: kubelet heartbeats in kube-node-lease.
    Only the ownership shape matters here — leasegarbagecollection
    (leasegarbagecollection/controller.go:48) deletes leases whose owning
    Node is gone."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""


@dataclass
class NodeClass:
    """Provider-specific node configuration object (the KWOKNodeClass
    analog, kwok/apis/v1alpha1). NodePools reference one via
    spec.template.node_class_ref; nodepool.readiness
    (readiness/controller.go:52) mirrors its Ready condition."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    kind: str = "KWOKNodeClass"
    conditions: list = field(default_factory=list)  # [{"type","status"}]

    @property
    def name(self) -> str:
        return self.metadata.name

    def ready(self) -> bool:
        for c in self.conditions:
            ctype = c.type if hasattr(c, "type") else c.get("type")
            status = c.status if hasattr(c, "status") else c.get("status")
            if ctype == "Ready":
                return status == "True"
        return True  # no explicit condition = ready (kwok nodeclass is static)


@dataclass
class PriorityClass:
    """scheduling.k8s.io PriorityClass: the value a pod's
    ``priority_class_name`` resolves to (admission/priority.py owns the
    resolution matrix). ``global_default`` marks the class applied to pods
    that name no class; ``preemption_policy`` ("" = PreemptLowerPriority)
    rides onto pods resolved through the class."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = ""  # "" (PreemptLowerPriority) | "Never"
    description: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PodDisruptionBudget:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    min_available: int | str | None = None
    max_unavailable: int | str | None = None
    disruptions_allowed: int = 0


def sort_terms_by_weight(terms: list) -> list:
    return sorted(terms, key=lambda t: -t.weight)
