"""Well-known labels, annotations, taints, and restriction rules.

Semantics from the reference's pkg/apis/v1beta1/labels.go:30-115 and
pkg/apis/v1beta1/taints.go. These strings are the shared vocabulary between
NodePools, pods, and instance-type catalogs; the tensorizer (ops/tensorize.py)
interns them into integer ids.
"""

from __future__ import annotations

GROUP = "karpenter.sh"

# k8s core labels
HOSTNAME_LABEL = "kubernetes.io/hostname"
TOPOLOGY_ZONE_LABEL = "topology.kubernetes.io/zone"
TOPOLOGY_REGION_LABEL = "topology.kubernetes.io/region"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
WINDOWS_BUILD_LABEL = "node.kubernetes.io/windows-build"

# architectures / capacity types
ARCHITECTURE_AMD64 = "amd64"
ARCHITECTURE_ARM64 = "arm64"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_ON_DEMAND = "on-demand"

# karpenter labels
NODEPOOL_LABEL = f"{GROUP}/nodepool"
NODE_INITIALIZED_LABEL = f"{GROUP}/initialized"
NODE_REGISTERED_LABEL = f"{GROUP}/registered"
CAPACITY_TYPE_LABEL = f"{GROUP}/capacity-type"

# karpenter annotations
DO_NOT_DISRUPT_ANNOTATION = f"{GROUP}/do-not-disrupt"
NODEPOOL_HASH_ANNOTATION = f"{GROUP}/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION = f"{GROUP}/nodepool-hash-version"
NODEPOOL_HASH_VERSION = "v2"  # current static-hash protocol version
MANAGED_BY_ANNOTATION = f"{GROUP}/managed-by"

# gang (all-or-nothing pod-group) admission — karpenter_tpu/admission/gangs.py
POD_GROUP_ANNOTATION = f"{GROUP}/pod-group"
POD_GROUP_MIN_ANNOTATION = f"{GROUP}/pod-group-min-member"
POD_GROUP_TOPOLOGY_ANNOTATION = f"{GROUP}/pod-group-topology"
# solve-internal label stamped on gang CLONES so the injected co-location
# pod-affinity term has a selector to match (never written to the store)
POD_GROUP_LABEL = f"{GROUP}/pod-group"

# finalizers
TERMINATION_FINALIZER = f"{GROUP}/termination"

# taints (pkg/apis/v1beta1/taints.go)
DISRUPTION_TAINT_KEY = f"{GROUP}/disruption"
DISRUPTION_TAINT_VALUE = "disrupting"
UNREGISTERED_TAINT_KEY = f"{GROUP}/unregistered"

RESTRICTED_LABEL_DOMAINS = frozenset({"kubernetes.io", "k8s.io", GROUP})

LABEL_DOMAIN_EXCEPTIONS = frozenset(
    {"kops.k8s.io", "node.kubernetes.io", "node-restriction.kubernetes.io"}
)

WELL_KNOWN_LABELS = frozenset(
    {
        NODEPOOL_LABEL,
        TOPOLOGY_ZONE_LABEL,
        TOPOLOGY_REGION_LABEL,
        INSTANCE_TYPE_LABEL,
        ARCH_LABEL,
        OS_LABEL,
        CAPACITY_TYPE_LABEL,
        WINDOWS_BUILD_LABEL,
    }
)

# labels that interfere with provisioning logic (labels.go RestrictedLabels)
RESTRICTED_LABELS = frozenset({HOSTNAME_LABEL})

# aliased concepts normalized to the canonical label (labels.go NormalizedLabels)
NORMALIZED_LABELS = {
    "failure-domain.beta.kubernetes.io/zone": TOPOLOGY_ZONE_LABEL,
    "failure-domain.beta.kubernetes.io/region": TOPOLOGY_REGION_LABEL,
    "beta.kubernetes.io/arch": ARCH_LABEL,
    "beta.kubernetes.io/os": OS_LABEL,
    "beta.kubernetes.io/instance-type": INSTANCE_TYPE_LABEL,
}


def normalize(key: str) -> str:
    return NORMALIZED_LABELS.get(key, key)


def _domain(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def is_restricted_node_label(key: str) -> bool:
    """True if karpenter must not inject this label onto nodes
    (labels.go IsRestrictedNodeLabel)."""
    if key in WELL_KNOWN_LABELS:
        return False
    dom = _domain(key)
    in_restricted = any(dom == d or dom.endswith("." + d) for d in RESTRICTED_LABEL_DOMAINS)
    in_exception = any(dom == d or dom.endswith("." + d) for d in LABEL_DOMAIN_EXCEPTIONS)
    return (in_restricted and not in_exception) or key in RESTRICTED_LABELS


def is_restricted_label(key: str) -> str | None:
    """Returns an error string if the label may not be used in specs
    (labels.go IsRestrictedLabel)."""
    if key in WELL_KNOWN_LABELS:
        return None
    if is_restricted_node_label(key):
        return (
            f"label {key} is restricted; specify a well known label "
            f"or a custom label that does not use a restricted domain"
        )
    return None
