"""Native host kernel: build-on-first-use C++ solve engine via ctypes.

`load()` compiles kernel.cpp with g++ into a cached shared library next to
the source (or $KARPENTER_NATIVE_CACHE) and returns the bound entry point;
it returns None when no toolchain is available, and callers fall back to
the pure-Python host loop. The library is rebuilt whenever kernel.cpp is
newer than the cached .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from karpenter_tpu.ops.tensorize import UNCAPPED
from karpenter_tpu.utils.envknobs import env_str

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "kernel.cpp")

_lock = threading.Lock()
_lib = None
_load_failed = False

_u32p = np.ctypeslib.ndpointer(dtype=np.uint32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(dtype=np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")


def _so_path() -> str:
    cache = env_str("KARPENTER_NATIVE_CACHE", _HERE)
    return os.path.join(cache, "libkarpenter_kernel.so")


def _build(so: str) -> bool:
    try:
        os.makedirs(os.path.dirname(so), exist_ok=True)
        tmp = so + ".tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=180,
        )
        os.replace(tmp, so)
        return True
    except Exception:
        return False


# shared (non-output) argtype blocks; the solve and probe-batch entries
# differ only in the leading N, the g_count/e_avail axes, and the outputs
_MID_ARGTYPES = (
    [_u32p, _u8p, _u8p, _f32p, _i32p, _u8p, _u8p, _u8p, _i32p,
     _u8p, _u32p, _u32p]                                # group side
    + [ctypes.c_int, _i32p, _u8p]                       # spread classes
    + [ctypes.c_int, _u8p, _u8p]                        # affinity classes
    + [ctypes.c_int, _f32p, _u8p, _i32p, _i32p, _u32p, _u32p, _i32p]  # existing
    + [_u32p, _u8p, _u8p, _f32p, _f32p, _i32p]          # type side
    + [_i32p, _i32p, _u8p]                              # offerings
    + [_u32p, _u8p, _u8p, _f32p, _f32p, _i32p]          # templates
)


def load():
    """Bound karpenter_solve(), or None if the native engine is unusable."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib.karpenter_solve
    if _load_failed:
        return None
    with _lock:
        if _lib is not None:
            return _lib.karpenter_solve
        so = _so_path()
        stale = not os.path.exists(so) or (
            os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(so)
        )
        if stale and not _build(so):
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            _load_failed = True
            return None
        fn = lib.karpenter_solve
        fn.restype = ctypes.c_int
        fn.argtypes = (
            [ctypes.c_int] * 11
            + _MID_ARGTYPES
            + [_i32p, _i32p, _u8p, _i32p, _u8p]                   # outputs
        )
        try:
            bfn = lib.karpenter_solve_probe_batch
            bfn.restype = ctypes.c_int
            bfn.argtypes = (
                [ctypes.c_int] * 12   # N + the 11 dims
                + _MID_ARGTYPES
                + [_i32p, _i32p]      # placed_g [N*G], used [N]
            )
        except AttributeError:
            pass  # stale library without the batch entry: solve_step only
        _lib = lib
        return fn


def load_probe_batch():
    """Bound karpenter_solve_probe_batch(), or None when the library (or
    the symbol, on a stale cached build) is unavailable."""
    if load() is None:
        return None
    try:
        return _lib.karpenter_solve_probe_batch
    except AttributeError:
        return None


def available() -> bool:
    return load() is not None


def _prep(args: dict, max_bins: int, g_count, e_avail):
    """Shared argument marshalling for the solve and probe-batch entries:
    (dims, mid) where dims = [G,T,K,W,R,M,O,B,Vz,Vc,CW] and mid is the
    ctypes argument block between the dims and the outputs. ``g_count`` and
    ``e_avail`` are passed explicitly — the batch entry feeds [N,...] rows
    through the same positions."""
    g_mask = np.ascontiguousarray(args["g_mask"], dtype=np.uint32)
    G, K, W = g_mask.shape
    t_mask = np.ascontiguousarray(args["t_mask"], dtype=np.uint32)
    T = t_mask.shape[0]
    m_mask = np.ascontiguousarray(args["m_mask"], dtype=np.uint32)
    M = m_mask.shape[0]
    off_zone = np.ascontiguousarray(args["off_zone"], dtype=np.int32)
    O = off_zone.shape[1]
    g_demand = np.ascontiguousarray(args["g_demand"], dtype=np.float32)
    R = g_demand.shape[1]
    gza = np.ascontiguousarray(args["g_zone_allowed"], dtype=np.uint8)
    gca = np.ascontiguousarray(args["g_ct_allowed"], dtype=np.uint8)
    # width-paired arrays default from their partner so a caller supplying
    # only one cannot feed the kernel mismatched class axes
    CW = np.asarray(
        args.get("g_decl", args.get("g_match", np.zeros((G, 1))))
    ).shape[1]
    g_decl = np.ascontiguousarray(
        args.get("g_decl", np.zeros((G, CW), dtype=np.uint32)), dtype=np.uint32
    )
    g_match = np.ascontiguousarray(
        args.get("g_match", np.zeros((G, CW), dtype=np.uint32)), dtype=np.uint32
    )
    if g_match.shape != g_decl.shape:
        raise ValueError(f"g_decl/g_match shape mismatch: {g_decl.shape} vs {g_match.shape}")
    C = np.asarray(args.get("g_sown", args.get("g_smatch", np.zeros((G, 1))))).shape[1]
    g_sown = np.ascontiguousarray(
        args.get("g_sown", np.full((G, C), UNCAPPED, dtype=np.int32)), dtype=np.int32
    )
    g_smatch = np.ascontiguousarray(
        args.get("g_smatch", np.zeros((G, C), dtype=np.uint8)), dtype=np.uint8
    )
    if g_smatch.shape != g_sown.shape:
        raise ValueError(f"g_sown/g_smatch shape mismatch: {g_sown.shape} vs {g_smatch.shape}")
    A = np.asarray(args.get("g_aneed", args.get("g_amatch", np.zeros((G, 1))))).shape[1]
    g_aneed = np.ascontiguousarray(
        args.get("g_aneed", np.zeros((G, A), dtype=np.uint8)), dtype=np.uint8
    )
    g_amatch = np.ascontiguousarray(
        args.get("g_amatch", np.zeros((G, A), dtype=np.uint8)), dtype=np.uint8
    )
    if g_amatch.shape != g_aneed.shape:
        raise ValueError(f"g_aneed/g_amatch shape mismatch: {g_aneed.shape} vs {g_amatch.shape}")
    B = int(max_bins)
    # existing-node tensors (default: one inert zero-capacity node); the
    # probe batch passes [N,E,R] rows, so E comes from the TRAILING axes
    e_avail = np.ascontiguousarray(e_avail, dtype=np.float32)
    E = e_avail.shape[-2]
    ge_ok = np.ascontiguousarray(
        args.get("ge_ok", np.zeros((G, E), dtype=np.uint8)), dtype=np.uint8
    )
    e_npods = np.ascontiguousarray(
        args.get("e_npods", np.zeros(E, dtype=np.int32)), dtype=np.int32
    )
    e_scnt = np.ascontiguousarray(
        args.get("e_scnt", np.zeros((E, C), dtype=np.int32)), dtype=np.int32
    )
    e_decl = np.ascontiguousarray(
        args.get("e_decl", np.zeros((E, CW), dtype=np.uint32)), dtype=np.uint32
    )
    e_match = np.ascontiguousarray(
        args.get("e_match", np.zeros((E, CW), dtype=np.uint32)), dtype=np.uint32
    )
    e_aff = np.ascontiguousarray(
        args.get("e_aff", np.zeros((E, A), dtype=np.int32)), dtype=np.int32
    )
    if e_aff.shape != (E, A):
        raise ValueError(f"e_aff shape mismatch: {e_aff.shape} vs {(E, A)}")

    dims = [G, T, K, W, R, M, O, B, gza.shape[1], gca.shape[1], CW]
    mid = [
        g_mask,
        np.ascontiguousarray(args["g_has"], dtype=np.uint8),
        np.ascontiguousarray(
            args.get("g_tol", np.zeros((G, K), dtype=np.uint8)), dtype=np.uint8
        ),
        g_demand,
        np.ascontiguousarray(g_count, dtype=np.int32),
        gza, gca,
        np.ascontiguousarray(args["g_tmpl_ok"], dtype=np.uint8),
        np.ascontiguousarray(
            args.get("g_bin_cap", np.full(G, 1 << 30, dtype=np.int32)), dtype=np.int32
        ),
        np.ascontiguousarray(
            args.get("g_single", np.zeros(G, dtype=np.uint8)), dtype=np.uint8
        ),
        g_decl, g_match,
        C, g_sown, g_smatch,
        A, g_aneed, g_amatch,
        E, e_avail, ge_ok, e_npods, e_scnt, e_decl, e_match, e_aff,
        t_mask,
        np.ascontiguousarray(args["t_has"], dtype=np.uint8),
        np.ascontiguousarray(
            args.get("t_tol", np.zeros((T, K), dtype=np.uint8)), dtype=np.uint8
        ),
        np.ascontiguousarray(args["t_alloc"], dtype=np.float32),
        np.ascontiguousarray(args["t_cap"], dtype=np.float32),
        np.ascontiguousarray(args["t_tmpl"], dtype=np.int32),
        off_zone,
        np.ascontiguousarray(args["off_ct"], dtype=np.int32),
        np.ascontiguousarray(args["off_avail"], dtype=np.uint8),
        m_mask,
        np.ascontiguousarray(args["m_has"], dtype=np.uint8),
        np.ascontiguousarray(
            args.get("m_tol", np.zeros((M, K), dtype=np.uint8)), dtype=np.uint8
        ),
        np.ascontiguousarray(args["m_overhead"], dtype=np.float32),
        np.ascontiguousarray(args["m_limits"], dtype=np.float32),
        np.ascontiguousarray(
            args.get("m_minv", np.zeros(M, dtype=np.int32)), dtype=np.int32
        ),
    ]
    return dims, mid


def solve_step(args: dict, max_bins: int) -> dict:
    """Drop-in for ops.kernels.solve_step on the host: same snapshot arg
    dict, same output dict (assign/used/tmpl/F), numpy throughout."""
    fn = load()
    if fn is None:
        raise RuntimeError("native kernel unavailable (no g++?)")
    R = np.asarray(args["g_demand"]).shape[1]
    e_avail = args.get("e_avail")
    if e_avail is None:
        e_avail = np.zeros((1, R), dtype=np.float32)
    dims, mid = _prep(args, max_bins, args["g_count"], e_avail)
    G, T, B = dims[0], dims[1], dims[7]
    E = np.asarray(e_avail).shape[0]

    assign = np.zeros((G, B), dtype=np.int32)
    assign_e = np.zeros((G, E), dtype=np.int32)
    used = np.zeros(B, dtype=np.uint8)
    tmpl = np.zeros(B, dtype=np.int32)
    F = np.zeros((G, T), dtype=np.uint8)

    rc = fn(*dims, *mid, assign, assign_e, used, tmpl, F)
    if rc != 0:
        raise RuntimeError(f"native kernel failed: rc={rc}")
    return {
        "assign": assign,
        "assign_e": assign_e,
        "used": used.astype(bool),
        "tmpl": tmpl,
        "F": F.astype(bool),
    }


def solve_probe_batch(args: dict, g_count_rows, e_avail_rows, max_bins: int):
    """Batched consolidation probe: N counterfactual rows over ONE shared
    snapshot in a single native call. ``args`` is the kernel_args dict
    WITHOUT g_count/e_avail (ops/tensorize.kernel_args include_counts=False);
    ``g_count_rows`` is [N, G] i32, ``e_avail_rows`` [N, E, R] f32. The
    engine builds feasibility once and packs per row, returning the probe
    reductions (placed_g [N, G], used [N]) — the per-row assign/F tensors
    never materialize host-side."""
    fn = load_probe_batch()
    if fn is None:
        raise RuntimeError(
            "native probe-batch entry unavailable (stale library or no g++)")
    g_count_rows = np.ascontiguousarray(g_count_rows, dtype=np.int32)
    e_avail_rows = np.ascontiguousarray(e_avail_rows, dtype=np.float32)
    N, G = g_count_rows.shape
    if e_avail_rows.shape[0] != N:
        raise ValueError(
            f"row-count mismatch: g_count {N} vs e_avail {e_avail_rows.shape[0]}")
    dims, mid = _prep(args, max_bins, g_count_rows, e_avail_rows)
    if dims[0] != G:
        raise ValueError(f"g_count_rows axis {G} != snapshot G {dims[0]}")

    placed_g = np.zeros((N, G), dtype=np.int32)
    used = np.zeros(N, dtype=np.int32)
    rc = fn(N, *dims, *mid, placed_g, used)
    if rc != 0:
        raise RuntimeError(f"native probe batch failed: rc={rc}")
    return placed_g, used
