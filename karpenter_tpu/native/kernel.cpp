// Native (host) implementation of the grouped feasibility + pack kernel.
//
// Mirrors karpenter_tpu/ops/kernels.py::solve_step over the exact same
// tensorized snapshot layout (see ops/tensorize.py): requirement sets as
// packed uint32 bitmasks over interned vocabularies, groups in FFD order,
// bins accumulating the intersection of surviving instance types. This is
// the fallback engine when no accelerator is available — the TPU-native
// reformulation of the reference's Go scheduling loop (scheduler.go:195-296)
// compiled for the host instead of for XLA.
//
// Differences from the device kernel, none observable in results:
// - emptiest-first filling is done directly with a priority scan instead of
//   the batched level-search (same fixpoint as scheduler.go:258's ascending
//   pod-count ordering);
// - per-bin candidate types are kept as shrinking index lists instead of a
//   dense [B,T] mask.
//
// C ABI for ctypes; all arrays are C-contiguous, caller-allocated.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>
#include <algorithm>
#include <limits>

namespace {

constexpr float EPS = 1e-6f;

struct Bin {
    int npods = 0;
    int tmpl = 0;
    std::vector<float> load;          // [R]
    std::vector<int> types;           // surviving candidate type ids
    std::vector<uint32_t> mask;       // [K*W] accumulated requirement mask
    std::vector<uint8_t> has;         // [K]
    std::vector<uint32_t> decl;       // [CW] hostname-anti classes declared
    std::vector<uint32_t> match;      // [CW] hostname-anti classes matched
    std::vector<int32_t> scnt;        // [C] spread-class matched-pod counts
    std::vector<int32_t> aff;         // [A] affinity-class matched-pod counts
};

// hostname pod-affinity classes (mirrors ops/kernels.py baff): a group
// OWNING class a may only land on bins whose matched count is already
// positive (nextDomainAffinity options, topology.py:209)
inline bool aff_ok(const Bin& bin, const uint8_t* aneed_g, int A) {
    for (int a = 0; a < A; ++a)
        if (aneed_g[a] && bin.aff[a] <= 0) return false;
    return true;
}

// hostname anti-affinity conflict classes (mirrors ops/kernels.py:199-203):
// a bin hosting pods MATCHED by class c excludes groups DECLARING c and
// vice versa (the direct/inverse TopologyGroup pair, topology.go:225)
inline bool anti_ok(const Bin& bin, const uint32_t* decl_g,
                    const uint32_t* match_g, int CW) {
    for (int w = 0; w < CW; ++w) {
        if ((bin.match[w] & decl_g[w]) || (bin.decl[w] & match_g[w]))
            return false;
    }
    return true;
}

// keep in sync with ops/tensorize.py SPREAD_OWNED_MIN / UNCAPPED
constexpr int32_t SPREAD_UNCAPPED = 1 << 29;

// hostname spread classes (mirrors ops/kernels.py bscnt): counts by
// selector match, cap enforced for owner groups (topologygroup.go:167).
// A self-selecting owner debits its own take; a non-self-selecting owner
// never raises the count it is checked against, so the cap gates the bin
// all-or-nothing (topology.py:200 'if self_selecting').
inline int spread_cap(const Bin& bin, const int32_t* sown_g,
                      const uint8_t* smatch_g, int C) {
    int cap = 1 << 30;
    for (int c = 0; c < C; ++c) {
        if (sown_g[c] >= SPREAD_UNCAPPED) continue;
        int rem = sown_g[c] - bin.scnt[c];
        if (!smatch_g[c]) rem = rem > 0 ? (1 << 30) : 0;
        cap = std::min(cap, rem > 0 ? rem : 0);
    }
    return cap;
}

inline bool masks_compatible(const uint32_t* a_mask, const uint8_t* a_has,
                             const uint32_t* b_mask, const uint8_t* b_has,
                             int K, int W,
                             // empty meet tolerated iff BOTH operators are
                             // NotIn/DoesNotExist (requirements.py:249);
                             // null = no tolerance (bin-accumulated masks)
                             const uint8_t* a_tol = nullptr,
                             const uint8_t* b_tol = nullptr) {
    for (int k = 0; k < K; ++k) {
        if (!a_has[k] || !b_has[k]) continue;
        if (a_tol && b_tol && a_tol[k] && b_tol[k]) continue;
        const uint32_t* aw = a_mask + (size_t)k * W;
        const uint32_t* bw = b_mask + (size_t)k * W;
        bool overlap = false;
        for (int w = 0; w < W; ++w) {
            if (aw[w] & bw[w]) { overlap = true; break; }
        }
        if (!overlap) return false;
    }
    return true;
}

inline void combine_masks(std::vector<uint32_t>& mask, std::vector<uint8_t>& has,
                          const uint32_t* gm, const uint8_t* gh, int K, int W) {
    for (int k = 0; k < K; ++k) {
        uint32_t* mw = mask.data() + (size_t)k * W;
        const uint32_t* gw = gm + (size_t)k * W;
        if (has[k] && gh[k]) {
            for (int w = 0; w < W; ++w) mw[w] &= gw[w];
        } else if (gh[k]) {
            for (int w = 0; w < W; ++w) mw[w] = gw[w];
        }
        has[k] = has[k] || gh[k];
    }
}

// minValues floor (cloudprovider/types.go:165-199): a take of t keeps
// >= minv instance types alive iff at least minv candidate capacities are
// >= t, i.e. t <= the minv-th largest capacity. caps is clobbered.
inline int minv_cap(std::vector<int>& caps, int minv) {
    if ((int)caps.size() < minv) return 0;
    std::nth_element(caps.begin(), caps.begin() + (minv - 1), caps.end(),
                     std::greater<int>());
    return caps[minv - 1];
}

// pods of demand d that fit into remaining space (alloc - load)
inline int cap_for(const float* alloc, const float* load, const float* d, int R) {
    float cap = std::numeric_limits<float>::infinity();
    for (int r = 0; r < R; ++r) {
        if (d[r] <= 0.0f) continue;
        float avail = alloc[r] - (load ? load[r] : 0.0f);
        float c = avail / std::max(d[r], EPS);
        cap = std::min(cap, c);
    }
    if (std::isinf(cap)) return 1 << 30;
    float f = std::floor(cap + EPS);
    return f <= 0.0f ? 0 : (int)f;
}

// every array the pack consults EXCEPT g_count/e_avail — the probe batch
// entry varies those two per counterfactual row over one shared snapshot
struct SolveShared {
    int G, T, K, W, R, M, O, B, Vz, Vc, CW, C, A, E;
    const uint32_t* g_mask; const uint8_t* g_has; const uint8_t* g_tol;
    const float* g_demand;
    const uint8_t* g_zone_allowed; const uint8_t* g_ct_allowed;
    const uint8_t* g_tmpl_ok;
    const int32_t* g_bin_cap; const uint8_t* g_single;
    const uint32_t* g_decl; const uint32_t* g_match;
    const int32_t* g_sown; const uint8_t* g_smatch;
    const uint8_t* g_aneed; const uint8_t* g_amatch;
    const uint8_t* ge_ok;
    const int32_t* e_npods; const int32_t* e_scnt;
    const uint32_t* e_decl; const uint32_t* e_match; const int32_t* e_aff;
    const uint32_t* t_mask; const uint8_t* t_has; const uint8_t* t_tol;
    const float* t_alloc; const float* t_cap; const int32_t* t_tmpl;
    const int32_t* off_zone; const int32_t* off_ct; const uint8_t* off_avail;
    const uint32_t* m_mask; const uint8_t* m_has; const uint8_t* m_tol;
    const float* m_overhead; const float* m_limits; const int32_t* m_minv;
};

// ---- feasibility: F[g,t] = requirement ∧ fit-one ∧ offering ----
static void build_feasibility(const SolveShared& s, std::vector<uint8_t>& F) {
    const int G = s.G, T = s.T, K = s.K, W = s.W, R = s.R, O = s.O;
    const int Vz = s.Vz, Vc = s.Vc;
    for (int g = 0; g < G; ++g) {
        const uint32_t* gm = s.g_mask + (size_t)g * K * W;
        const uint8_t* gh = s.g_has + (size_t)g * K;
        const float* d = s.g_demand + (size_t)g * R;
        const uint8_t* gt = s.g_tol + (size_t)g * K;
        for (int t = 0; t < T; ++t) {
            if (!masks_compatible(gm, gh, s.t_mask + (size_t)t * K * W,
                                  s.t_has + (size_t)t * K, K, W,
                                  gt, s.t_tol + (size_t)t * K))
                continue;
            if (cap_for(s.t_alloc + (size_t)t * R, nullptr, d, R) < 1) continue;
            bool off_ok = false;
            for (int o = 0; o < O; ++o) {
                size_t i = (size_t)t * O + o;
                if (!s.off_avail[i]) continue;
                int z = s.off_zone[i], c = s.off_ct[i];
                if (z >= 0 && !s.g_zone_allowed[(size_t)g * Vz + z]) continue;
                if (c >= 0 && !s.g_ct_allowed[(size_t)g * Vc + c]) continue;
                off_ok = true;
                break;
            }
            if (off_ok) F[(size_t)g * T + t] = 1;
        }
    }
}

// ---- template-level overlap for new-bin placement ----
static void build_tmpl_full(const SolveShared& s, std::vector<uint8_t>& tmpl_full) {
    const int G = s.G, K = s.K, W = s.W, M = s.M;
    for (int g = 0; g < G; ++g) {
        const uint32_t* gm = s.g_mask + (size_t)g * K * W;
        const uint8_t* gh = s.g_has + (size_t)g * K;
        for (int m = 0; m < M; ++m) {
            if (!s.g_tmpl_ok[(size_t)g * M + m]) continue;
            if (masks_compatible(gm, gh, s.m_mask + (size_t)m * K * W,
                                 s.m_has + (size_t)m * K, K, W,
                                 s.g_tol + (size_t)g * K, s.m_tol + (size_t)m * K))
                tmpl_full[(size_t)g * M + m] = 1;
        }
    }
}

// ---- grouped greedy pack (the body of the original karpenter_solve) ----
static void pack_bins(const SolveShared& s, const std::vector<uint8_t>& F,
                      const std::vector<uint8_t>& tmpl_full,
                      const int32_t* g_count, const float* e_avail,
                      int32_t* assign, int32_t* assign_e, uint8_t* used,
                      int32_t* tmpl_out) {
    const int G = s.G, T = s.T, K = s.K, W = s.W, R = s.R, M = s.M;
    const int B = s.B, CW = s.CW, C = s.C, A = s.A, E = s.E;
    const uint32_t* g_mask = s.g_mask; const uint8_t* g_has = s.g_has;
    const uint8_t* g_tol = s.g_tol; const float* g_demand = s.g_demand;
    const uint8_t* g_tmpl_ok = s.g_tmpl_ok;
    const int32_t* g_bin_cap = s.g_bin_cap; const uint8_t* g_single = s.g_single;
    const uint32_t* g_decl = s.g_decl; const uint32_t* g_match = s.g_match;
    const int32_t* g_sown = s.g_sown; const uint8_t* g_smatch = s.g_smatch;
    const uint8_t* g_aneed = s.g_aneed; const uint8_t* g_amatch = s.g_amatch;
    const uint8_t* ge_ok = s.ge_ok;
    const int32_t* e_npods = s.e_npods; const int32_t* e_scnt = s.e_scnt;
    const uint32_t* e_decl = s.e_decl; const uint32_t* e_match = s.e_match;
    const int32_t* e_aff = s.e_aff;
    const float* t_alloc = s.t_alloc; const float* t_cap = s.t_cap;
    const int32_t* t_tmpl = s.t_tmpl;
    const uint32_t* m_mask = s.m_mask; const uint8_t* m_has = s.m_has;
    const float* m_overhead = s.m_overhead; const float* m_limits = s.m_limits;
    const int32_t* m_minv = s.m_minv;
    (void)g_tol;
    std::vector<Bin> bins;
    bins.reserve(256);
    std::vector<float> rem((size_t)M * R);
    std::memcpy(rem.data(), m_limits, sizeof(float) * M * R);
    std::memset(assign, 0, sizeof(int32_t) * (size_t)G * B);
    std::memset(used, 0, (size_t)B);
    std::memset(tmpl_out, 0, sizeof(int32_t) * (size_t)B);

    // existing-node state (mirrors ops/kernels.py phase A): fixed capacity,
    // evolving load + topology class state
    std::vector<float> eload((size_t)E * R, 0.0f);
    std::vector<int32_t> enp(e_npods, e_npods + E);
    std::vector<int32_t> escnt(e_scnt, e_scnt + (size_t)E * C);
    std::vector<uint32_t> edecl(e_decl, e_decl + (size_t)E * CW);
    std::vector<uint32_t> ematch(e_match, e_match + (size_t)E * CW);
    std::vector<int32_t> eaff(e_aff, e_aff + (size_t)E * A);
    std::memset(assign_e, 0, sizeof(int32_t) * (size_t)G * E);

    std::vector<int> order;  // bin indices sorted by npods (emptiest first)
    for (int g = 0; g < G; ++g) {
        int n = g_count[g];
        if (n <= 0) continue;
        const uint32_t* gm = g_mask + (size_t)g * K * W;
        const uint8_t* gh = g_has + (size_t)g * K;
        const float* d = g_demand + (size_t)g * R;
        const uint8_t* Fg = F.data() + (size_t)g * T;
        const int cap_g = g_bin_cap[g] > 0 ? g_bin_cap[g] : (1 << 30);
        const bool single = g_single[g] != 0;
        const uint32_t* decl_g = g_decl + (size_t)g * CW;
        const uint32_t* match_g = g_match + (size_t)g * CW;
        const int32_t* sown_g = g_sown + (size_t)g * C;
        const uint8_t* smatch_g = g_smatch + (size_t)g * C;
        const uint8_t* aneed_g = g_aneed + (size_t)g * A;
        const uint8_t* amatch_g = g_amatch + (size_t)g * A;
        bool any_aneed = false;
        for (int a = 0; a < A; ++a) any_aneed = any_aneed || aneed_g[a];
        int cap_own = 1 << 30;  // fresh-bin cap from owned spread classes
        for (int c = 0; c < C; ++c)
            if (sown_g[c] < SPREAD_UNCAPPED && smatch_g[c])
                cap_own = std::min(cap_own, (int)sown_g[c]);

        // phase A: existing nodes first (scheduler.go:250), emptiest-first;
        // single-bin groups bootstrap fresh claims (device parity)
        if (!single && E > 0) {
            std::vector<int> eorder(E);
            for (int i = 0; i < E; ++i) eorder[i] = i;
            std::stable_sort(eorder.begin(), eorder.end(), [&](int a, int b) {
                return enp[a] < enp[b];
            });
            for (int ei : eorder) {
                if (n <= 0) break;
                if (!ge_ok[(size_t)g * E + ei]) continue;
                bool aok = true;
                for (int w = 0; w < CW; ++w)
                    if ((ematch[(size_t)ei * CW + w] & decl_g[w]) ||
                        (edecl[(size_t)ei * CW + w] & match_g[w])) { aok = false; break; }
                for (int a = 0; a < A && aok; ++a)
                    if (aneed_g[a] && eaff[(size_t)ei * A + a] <= 0) aok = false;
                if (!aok) continue;
                int scap = 1 << 30;
                for (int c = 0; c < C; ++c) {
                    if (g_sown[(size_t)g * C + c] >= SPREAD_UNCAPPED) continue;
                    int rem = g_sown[(size_t)g * C + c] - escnt[(size_t)ei * C + c];
                    if (!smatch_g[c]) rem = rem > 0 ? (1 << 30) : 0;
                    scap = std::min(scap, rem > 0 ? rem : 0);
                }
                int q = cap_for(e_avail + (size_t)ei * R, eload.data() + (size_t)ei * R, d, R);
                q = std::min(q, std::min(cap_g, scap));
                if (q <= 0) continue;
                int take = std::min(q, n);
                n -= take;
                assign_e[(size_t)g * E + ei] += take;
                enp[ei] += take;
                for (int r = 0; r < R; ++r) eload[(size_t)ei * R + r] += take * d[r];
                for (int c = 0; c < C; ++c)
                    if (smatch_g[c]) escnt[(size_t)ei * C + c] += take;
                for (int a = 0; a < A; ++a)
                    if (amatch_g[a]) eaff[(size_t)ei * A + a] += take;
                for (int w = 0; w < CW; ++w) {
                    edecl[(size_t)ei * CW + w] |= decl_g[w];
                    ematch[(size_t)ei * CW + w] |= match_g[w];
                }
            }
        }

        // existing bins, emptiest first (scheduler.go:258)
        order.resize(bins.size());
        for (size_t i = 0; i < bins.size(); ++i) order[i] = (int)i;
        std::stable_sort(order.begin(), order.end(), [&](int a, int b) {
            return bins[a].npods < bins[b].npods;
        });
        if (single) {
            // whole group confined to one bin (hostname pod affinity,
            // topologygroup.go:219): pick the single highest-capacity bin
            int best_bi = -1, best_q = 0;
            for (int bi : order) {
                Bin& bin = bins[bi];
                if (!tmpl_full[(size_t)g * M + bin.tmpl]) continue;
                if (!anti_ok(bin, decl_g, match_g, CW)) continue;
                if (!aff_ok(bin, aneed_g, A)) continue;
                if (!masks_compatible(bin.mask.data(), bin.has.data(), gm, gh, K, W))
                    continue;
                int q = 0;
                int minv = m_minv[bin.tmpl];
                std::vector<int> caps;
                for (int t : bin.types) {
                    if (!Fg[t]) continue;
                    int c = cap_for(t_alloc + (size_t)t * R, bin.load.data(), d, R);
                    if (minv > 0) caps.push_back(c);
                    q = std::max(q, c);
                }
                if (minv > 0) q = std::min(q, minv_cap(caps, minv));
                q = std::min(q, spread_cap(bin, sown_g, smatch_g, C));
                if (q > best_q) { best_q = q; best_bi = bi; }
            }
            order.clear();
            if (best_bi >= 0) order.push_back(best_bi);
        }
        for (int bi : order) {
            if (n <= 0) break;
            Bin& bin = bins[bi];
            if (!tmpl_full[(size_t)g * M + bin.tmpl]) continue;
            if (!anti_ok(bin, decl_g, match_g, CW)) continue;
            if (!aff_ok(bin, aneed_g, A)) continue;
            if (!masks_compatible(bin.mask.data(), bin.has.data(), gm, gh, K, W))
                continue;
            // capacity = max over surviving types still feasible for g
            int q = 0;
            int minv = m_minv[bin.tmpl];
            std::vector<int> caps;
            for (int t : bin.types) {
                if (!Fg[t]) continue;
                int c = cap_for(t_alloc + (size_t)t * R, bin.load.data(), d, R);
                if (minv > 0) caps.push_back(c);
                q = std::max(q, c);
            }
            if (minv > 0) q = std::min(q, minv_cap(caps, minv));
            q = std::min(q, cap_g);  // per-bin topology cap (waves)
            q = std::min(q, spread_cap(bin, sown_g, smatch_g, C));
            if (q <= 0) continue;
            int take = std::min(q, n);
            n -= take;
            assign[(size_t)g * B + bi] += take;
            bin.npods += take;
            for (int r = 0; r < R; ++r) bin.load[r] += take * d[r];
            // shrink surviving types: still feasible for g AND still fits load
            std::vector<int> kept;
            kept.reserve(bin.types.size());
            for (int t : bin.types) {
                if (!Fg[t]) continue;
                bool fits = true;
                const float* alloc = t_alloc + (size_t)t * R;
                for (int r = 0; r < R; ++r)
                    if (bin.load[r] > alloc[r] + EPS) { fits = false; break; }
                if (fits) kept.push_back(t);
            }
            bin.types.swap(kept);
            combine_masks(bin.mask, bin.has, gm, gh, K, W);
            // conflict-class commit: the bin now hosts this group's pods
            for (int w = 0; w < CW; ++w) {
                bin.decl[w] |= decl_g[w];
                bin.match[w] |= match_g[w];
            }
            for (int c = 0; c < C; ++c)
                if (smatch_g[c]) bin.scnt[c] += take;
            for (int a = 0; a < A; ++a)
                if (amatch_g[a]) bin.aff[a] += take;
        }

        // new bins from the first (weight-ordered) feasible template.
        // single-bin groups open at most ONE bin, and only when nothing
        // landed on an existing bin (followers join the first pod's claim
        // or fail, topology.py:207 bootstrap)
        bool opened_for_single = false;
        // affinity owners may open a fresh bin only to BOOTSTRAP: every
        // owned class must be self-matched with zero matches anywhere, and
        // the bootstrap opens exactly ONE bin (topology.py:211-221)
        bool aff_new_ok = true;
        if (any_aneed) {
            for (int a = 0; a < A && aff_new_ok; ++a) {
                if (!aneed_g[a]) continue;
                long total = 0;
                for (const Bin& bn : bins) total += bn.aff[a];
                for (int ei = 0; ei < E; ++ei) total += eaff[(size_t)ei * A + a];
                if (total > 0 || !amatch_g[a]) aff_new_ok = false;
            }
        }
        while (n > 0 && (int)bins.size() < B) {
            if (single && (n < g_count[g] || opened_for_single)) break;
            if (any_aneed && (!aff_new_ok || opened_for_single)) break;
            int m_star = -1, per_node = 0;
            for (int m = 0; m < M && m_star < 0; ++m) {
                if (!tmpl_full[(size_t)g * M + m]) continue;
                int best = 0;
                int minv_m = m_minv[m];
                std::vector<int> caps;
                for (int t = 0; t < T; ++t) {
                    if (t_tmpl[t] != m || !Fg[t]) continue;
                    // nodepool limits: worst-case capacity must fit rem
                    bool lim_ok = true;
                    for (int r = 0; r < R; ++r)
                        if (t_cap[(size_t)t * R + r] > rem[(size_t)m * R + r] + EPS) {
                            lim_ok = false; break;
                        }
                    if (!lim_ok) continue;
                    std::vector<float> ovh(m_overhead + (size_t)m * R,
                                           m_overhead + (size_t)m * R + R);
                    int c = cap_for(t_alloc + (size_t)t * R, ovh.data(), d, R);
                    if (minv_m > 0) caps.push_back(c);
                    best = std::max(best, c);
                }
                // a fresh claim must open with >= minv viable types
                if (minv_m > 0) best = std::min(best, minv_cap(caps, minv_m));
                if (best > 0) { m_star = m; per_node = best; }
            }
            if (m_star < 0) break;  // nothing can host this group

            Bin bin;
            bin.tmpl = m_star;
            bin.load.assign(m_overhead + (size_t)m_star * R,
                            m_overhead + (size_t)m_star * R + R);
            bin.mask.assign(m_mask + (size_t)m_star * K * W,
                            m_mask + (size_t)m_star * K * W + (size_t)K * W);
            bin.has.assign(m_has + (size_t)m_star * K, m_has + (size_t)m_star * K + K);
            bin.decl.assign(decl_g, decl_g + CW);
            bin.match.assign(match_g, match_g + CW);
            per_node = std::min(per_node, std::min(cap_g, cap_own));
            int take = std::min(per_node, n);
            bin.npods = take;
            bin.scnt.assign(C, 0);
            for (int c = 0; c < C; ++c)
                if (smatch_g[c]) bin.scnt[c] = take;
            bin.aff.assign(A, 0);
            for (int a = 0; a < A; ++a)
                if (amatch_g[a]) bin.aff[a] = take;
            for (int r = 0; r < R; ++r) bin.load[r] += take * d[r];
            // candidate types: template's, feasible for g, limit-ok, fits load
            std::vector<float> worst(R, 0.0f);
            for (int t = 0; t < T; ++t) {
                if (t_tmpl[t] != m_star || !Fg[t]) continue;
                bool lim_ok = true, fits = true;
                const float* cap = t_cap + (size_t)t * R;
                const float* alloc = t_alloc + (size_t)t * R;
                for (int r = 0; r < R; ++r) {
                    if (cap[r] > rem[(size_t)m_star * R + r] + EPS) lim_ok = false;
                    if (bin.load[r] > alloc[r] + EPS) fits = false;
                }
                if (!lim_ok || !fits) continue;
                bin.types.push_back(t);
                for (int r = 0; r < R; ++r) worst[r] = std::max(worst[r], cap[r]);
            }
            if (bin.types.empty()) break;
            combine_masks(bin.mask, bin.has, gm, gh, K, W);
            // limit accounting: subtract worst-case capacity (subtractMax)
            for (int r = 0; r < R; ++r) rem[(size_t)m_star * R + r] -= worst[r];
            int bi = (int)bins.size();
            bins.push_back(std::move(bin));
            assign[(size_t)g * B + bi] = take;
            n -= take;
            opened_for_single = true;
        }
        // pods still unplaced are implied by count - sum(assign[g]) and
        // re-routed by the decoder, matching the device kernel's contract
    }

    for (size_t i = 0; i < bins.size(); ++i) {
        used[i] = 1;
        tmpl_out[i] = bins[i].tmpl;
    }
}

static SolveShared make_shared_args(
    int G, int T, int K, int W, int R, int M, int O, int B, int Vz, int Vc,
    int CW, int C, int A, int E,
    const uint32_t* g_mask, const uint8_t* g_has, const uint8_t* g_tol,
    const float* g_demand, const uint8_t* g_zone_allowed,
    const uint8_t* g_ct_allowed, const uint8_t* g_tmpl_ok,
    const int32_t* g_bin_cap, const uint8_t* g_single,
    const uint32_t* g_decl, const uint32_t* g_match,
    const int32_t* g_sown, const uint8_t* g_smatch,
    const uint8_t* g_aneed, const uint8_t* g_amatch,
    const uint8_t* ge_ok, const int32_t* e_npods, const int32_t* e_scnt,
    const uint32_t* e_decl, const uint32_t* e_match, const int32_t* e_aff,
    const uint32_t* t_mask, const uint8_t* t_has, const uint8_t* t_tol,
    const float* t_alloc, const float* t_cap, const int32_t* t_tmpl,
    const int32_t* off_zone, const int32_t* off_ct, const uint8_t* off_avail,
    const uint32_t* m_mask, const uint8_t* m_has, const uint8_t* m_tol,
    const float* m_overhead, const float* m_limits, const int32_t* m_minv) {
    SolveShared s;
    s.G = G; s.T = T; s.K = K; s.W = W; s.R = R; s.M = M; s.O = O; s.B = B;
    s.Vz = Vz; s.Vc = Vc; s.CW = CW; s.C = C; s.A = A; s.E = E;
    s.g_mask = g_mask; s.g_has = g_has; s.g_tol = g_tol; s.g_demand = g_demand;
    s.g_zone_allowed = g_zone_allowed; s.g_ct_allowed = g_ct_allowed;
    s.g_tmpl_ok = g_tmpl_ok; s.g_bin_cap = g_bin_cap; s.g_single = g_single;
    s.g_decl = g_decl; s.g_match = g_match; s.g_sown = g_sown;
    s.g_smatch = g_smatch; s.g_aneed = g_aneed; s.g_amatch = g_amatch;
    s.ge_ok = ge_ok; s.e_npods = e_npods; s.e_scnt = e_scnt;
    s.e_decl = e_decl; s.e_match = e_match; s.e_aff = e_aff;
    s.t_mask = t_mask; s.t_has = t_has; s.t_tol = t_tol;
    s.t_alloc = t_alloc; s.t_cap = t_cap; s.t_tmpl = t_tmpl;
    s.off_zone = off_zone; s.off_ct = off_ct; s.off_avail = off_avail;
    s.m_mask = m_mask; s.m_has = m_has; s.m_tol = m_tol;
    s.m_overhead = m_overhead; s.m_limits = m_limits; s.m_minv = m_minv;
    return s;
}

}  // namespace

extern "C" {

// Returns 0 on success. Output arrays: assign [G*B] i32 (zeroed by callee),
// used [B] u8, tmpl_out [B] i32, F_out [G*T] u8.
int karpenter_solve(
    int G, int T, int K, int W, int R, int M, int O, int B, int Vz, int Vc,
    int CW,
    const uint32_t* g_mask, const uint8_t* g_has, const uint8_t* g_tol,
    const float* g_demand,
    const int32_t* g_count, const uint8_t* g_zone_allowed,
    const uint8_t* g_ct_allowed, const uint8_t* g_tmpl_ok,
    const int32_t* g_bin_cap, const uint8_t* g_single,
    const uint32_t* g_decl, const uint32_t* g_match,
    int C, const int32_t* g_sown, const uint8_t* g_smatch,
    int A, const uint8_t* g_aneed, const uint8_t* g_amatch,
    int E, const float* e_avail, const uint8_t* ge_ok,
    const int32_t* e_npods, const int32_t* e_scnt,
    const uint32_t* e_decl, const uint32_t* e_match,
    const int32_t* e_aff,
    const uint32_t* t_mask, const uint8_t* t_has, const uint8_t* t_tol,
    const float* t_alloc,
    const float* t_cap, const int32_t* t_tmpl,
    const int32_t* off_zone, const int32_t* off_ct, const uint8_t* off_avail,
    const uint32_t* m_mask, const uint8_t* m_has, const uint8_t* m_tol,
    const float* m_overhead, const float* m_limits, const int32_t* m_minv,
    int32_t* assign, int32_t* assign_e, uint8_t* used, int32_t* tmpl_out,
    uint8_t* F_out) {
    SolveShared s = make_shared_args(
        G, T, K, W, R, M, O, B, Vz, Vc, CW, C, A, E,
        g_mask, g_has, g_tol, g_demand, g_zone_allowed, g_ct_allowed,
        g_tmpl_ok, g_bin_cap, g_single, g_decl, g_match, g_sown, g_smatch,
        g_aneed, g_amatch, ge_ok, e_npods, e_scnt, e_decl, e_match, e_aff,
        t_mask, t_has, t_tol, t_alloc, t_cap, t_tmpl, off_zone, off_ct,
        off_avail, m_mask, m_has, m_tol, m_overhead, m_limits, m_minv);
    std::vector<uint8_t> F((size_t)G * T, 0);
    build_feasibility(s, F);
    std::memcpy(F_out, F.data(), (size_t)G * T);
    std::vector<uint8_t> tmpl_full((size_t)G * M, 0);
    build_tmpl_full(s, tmpl_full);
    pack_bins(s, F, tmpl_full, g_count, e_avail, assign, assign_e, used,
              tmpl_out);
    return 0;
}

// Batched probe entry (ops/consolidate.py _dispatch_native): N
// counterfactual rows over ONE shared snapshot — feasibility and the
// template overlap build once, then the pack runs per row with that row's
// g_count [N*G] and e_avail [N*E*R]. Outputs are the probe's reductions:
// placed_g [N*G] (fresh-bin + existing placements per group) and
// used_out [N] (fresh claims opened). The per-row full outputs the single
// entry would emit never materialize host-side.
int karpenter_solve_probe_batch(
    int N,
    int G, int T, int K, int W, int R, int M, int O, int B, int Vz, int Vc,
    int CW,
    const uint32_t* g_mask, const uint8_t* g_has, const uint8_t* g_tol,
    const float* g_demand,
    const int32_t* g_count_rows, const uint8_t* g_zone_allowed,
    const uint8_t* g_ct_allowed, const uint8_t* g_tmpl_ok,
    const int32_t* g_bin_cap, const uint8_t* g_single,
    const uint32_t* g_decl, const uint32_t* g_match,
    int C, const int32_t* g_sown, const uint8_t* g_smatch,
    int A, const uint8_t* g_aneed, const uint8_t* g_amatch,
    int E, const float* e_avail_rows, const uint8_t* ge_ok,
    const int32_t* e_npods, const int32_t* e_scnt,
    const uint32_t* e_decl, const uint32_t* e_match,
    const int32_t* e_aff,
    const uint32_t* t_mask, const uint8_t* t_has, const uint8_t* t_tol,
    const float* t_alloc,
    const float* t_cap, const int32_t* t_tmpl,
    const int32_t* off_zone, const int32_t* off_ct, const uint8_t* off_avail,
    const uint32_t* m_mask, const uint8_t* m_has, const uint8_t* m_tol,
    const float* m_overhead, const float* m_limits, const int32_t* m_minv,
    int32_t* placed_g, int32_t* used_out) {
    SolveShared s = make_shared_args(
        G, T, K, W, R, M, O, B, Vz, Vc, CW, C, A, E,
        g_mask, g_has, g_tol, g_demand, g_zone_allowed, g_ct_allowed,
        g_tmpl_ok, g_bin_cap, g_single, g_decl, g_match, g_sown, g_smatch,
        g_aneed, g_amatch, ge_ok, e_npods, e_scnt, e_decl, e_match, e_aff,
        t_mask, t_has, t_tol, t_alloc, t_cap, t_tmpl, off_zone, off_ct,
        off_avail, m_mask, m_has, m_tol, m_overhead, m_limits, m_minv);
    std::vector<uint8_t> F((size_t)G * T, 0);
    build_feasibility(s, F);
    std::vector<uint8_t> tmpl_full((size_t)G * M, 0);
    build_tmpl_full(s, tmpl_full);
    std::vector<int32_t> assign((size_t)G * B);
    std::vector<int32_t> assign_e((size_t)G * E);
    std::vector<uint8_t> used((size_t)B);
    std::vector<int32_t> tmpl_out((size_t)B);
    for (int i = 0; i < N; ++i) {
        pack_bins(s, F, tmpl_full,
                  g_count_rows + (size_t)i * G,
                  e_avail_rows + (size_t)i * E * R,
                  assign.data(), assign_e.data(), used.data(),
                  tmpl_out.data());
        for (int g = 0; g < G; ++g) {
            int64_t total = 0;
            for (int b = 0; b < B; ++b) total += assign[(size_t)g * B + b];
            for (int e = 0; e < E; ++e) total += assign_e[(size_t)g * E + e];
            placed_g[(size_t)i * G + g] = (int32_t)total;
        }
        int32_t u = 0;
        for (int b = 0; b < B; ++b) u += used[b] ? 1 : 0;
        used_out[i] = u;
    }
    return 0;
}

}  // extern "C"
