"""Deployable operator entrypoint: `python -m karpenter_tpu`.

The kwok/main.go analog (kwok/main.go:33-48 + operator.go:111-220): wire
the operator runtime — store, kwok cloud provider, the full controller
ring, disruption — and run it against wall-clock time until SIGINT/SIGTERM.
The backing store is the in-memory KubeStore by default (the hermetic
kwok-style deployment this image supports); anything implementing the
KubeClient seam (kube/client.py) can be injected in its place to front a
real apiserver.

    python -m karpenter_tpu --manifest cluster.json [--tick 1.0] [--metrics]

Manifests are JSON documents (a single object or a list) in EITHER
karpenter.sh API version — NodePool/NodeClaim wire docs run through the
conversion layer (api/conversion.py), the kwok catalog backs instance
types, and a `pods` shorthand ({"kind": "Pod", "name", "cpu", "memory",
"replicas"}) seeds workload. The /metrics endpoint serves the Prometheus
registry on KARPENTER_METRICS_PORT (operator.go:160's mux analog).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

from karpenter_tpu.utils.envknobs import env_str

GIB = 2**30


def load_manifest(env, path: str) -> int:
    """Apply a JSON manifest file (v1 or v1beta1 docs) to the store."""
    from karpenter_tpu.api.conversion import decode
    from karpenter_tpu.api.objects import ObjectMeta, Pod

    with open(path) as f:
        docs = json.load(f)
    if isinstance(docs, dict):
        docs = [docs]
    n = 0
    for doc in docs:
        kind = doc.get("kind", "")
        if kind == "NodePool":
            n += _apply(env, "nodepools", doc)
        elif kind == "NodeClaim":
            n += _apply(env, "nodeclaims", doc)
        elif kind == "Pod":
            replicas = int(doc.get("replicas", 1))
            for i in range(replicas):
                name = doc.get("name", "pod")
                env.store.create("pods", Pod(
                    metadata=ObjectMeta(
                        name=f"{name}-{i}" if replicas > 1 else name,
                        labels=dict(doc.get("labels", {})),
                    ),
                    requests={
                        "cpu": float(doc.get("cpu", 1.0)),
                        "memory": float(doc.get("memory", 1.0)) * GIB,
                    },
                ))
                n += 1
        else:
            raise SystemExit(f"unsupported manifest kind {kind!r}")
    return n


def _apply(env, plural: str, doc: dict) -> int:
    from karpenter_tpu.api.conversion import ConversionError, decode

    try:
        env.store.create(plural, decode(doc))
    except ConversionError as e:
        raise SystemExit(
            f"manifest {doc.get('kind')}/{doc.get('metadata', {}).get('name')}: {e}"
        ) from e
    return 1


def serve_metrics(registry, port: int, host: str = ""):
    """Prometheus text endpoint (the operator.go:160 metrics mux analog)
    plus the health/SLO/introspection surfaces: `/healthz` liveness,
    `/slo` (a JSON snapshot of the device-plane SLO trackers and the
    compile ledger, obs/devplane.py), and `/introspect` (the decision
    plane: per-site rung mixes, last-K round rung summaries, the solve-
    quality series, per-tenant rung mixes, retained anomalous rounds —
    obs/decisions.py; `python -m karpenter_tpu.obs report` renders it),
    and `/usage` (the fleet ledger's per-tenant device-time billing,
    obs/timeline.py — deploy/README.md "Fleet ledger").
    `host` defaults to all interfaces for containerized scrapes; deploys
    without a NetworkPolicy narrow it via KARPENTER_METRICS_BIND
    (deploy/README.md, network exposure)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path not in ("/metrics", "/healthz", "/slo",
                                 "/introspect", "/usage"):
                self.send_response(404)
                self.end_headers()
                return
            if self.path == "/slo":
                from karpenter_tpu.obs import devplane

                body = json.dumps(devplane.slo_snapshot()).encode()
                ctype = "application/json"
            elif self.path == "/introspect":
                from karpenter_tpu.obs import decisions

                body = json.dumps(decisions.introspect_snapshot()).encode()
                ctype = "application/json"
            elif self.path == "/usage":
                from karpenter_tpu.obs import timeline

                body = json.dumps(timeline.usage_snapshot()).encode()
                ctype = "application/json"
            else:
                body = (
                    registry.expose() if self.path == "/metrics" else "ok"
                ).encode()
                ctype = "text/plain; version=0.0.4"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    # default is all interfaces: a container's Prometheus scrape arrives on
    # the pod IP (operator.go's mux binds the same way); loopback would be
    # dead in the deployment this entrypoint exists for, so narrowing is an
    # explicit override (KARPENTER_METRICS_BIND)
    server = HTTPServer((host, port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="karpenter_tpu")
    ap.add_argument("--manifest", action="append", default=[],
                    help="JSON manifest file(s) applied at startup")
    ap.add_argument("--tick", type=float, default=1.0,
                    help="reconcile tick seconds (controller poll cadence)")
    ap.add_argument("--metrics", action="store_true",
                    help="serve /metrics + /healthz on KARPENTER_METRICS_PORT")
    ap.add_argument("--max-ticks", type=int, default=0,
                    help="exit after N ticks (0 = run until signal)")
    ap.add_argument("--solver", default=None,
                    help="host:port of a solver service (the two-plane "
                         "split); also KARPENTER_SOLVER_TARGET")
    args = ap.parse_args(argv)

    from karpenter_tpu.operator import Environment
    from karpenter_tpu.operator.logging import make_logger
    from karpenter_tpu.operator.options import Options
    from karpenter_tpu.utils.clock import Clock

    options = Options.from_env()
    solver = None
    target = args.solver or env_str("KARPENTER_SOLVER_TARGET")
    if target:
        from karpenter_tpu.service import RemoteSolver

        # KARPENTER_SOLVER_TENANT opts this operator into the fleet
        # service's streaming delta protocol (session mode): one full
        # snapshot, then per-round deltas + per-tenant SLO on the server
        solver = RemoteSolver(
            target, tenant=env_str("KARPENTER_SOLVER_TENANT") or None)
    env = Environment(
        clock=Clock(),  # wall-clock: budgets/TTLs run in real time
        sync=False,  # production batching window (1s idle / 10s max)
        enable_disruption=True,
        options=options,
        solver=solver,
        log=make_logger(options.log_level),
    )
    if target:
        # fallback counter + warn must land on THIS environment's registry
        # and logging plane (the ones /metrics and stderr actually serve)
        solver.bind_observability(registry=env.registry, log=env.log)
        print(f"karpenter-tpu operator: solver plane at {target}", file=sys.stderr)

    applied = sum(load_manifest(env, m) for m in args.manifest)
    print(f"karpenter-tpu operator: {applied} manifest objects applied, "
          f"tick={args.tick}s", file=sys.stderr)

    server = (
        serve_metrics(env.registry, options.metrics_port,
                      host=options.metrics_bind_addr)
        if args.metrics else None
    )

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)

    ticks = 0
    try:
        while not stop.is_set():
            env.run_until_idle()
            ticks += 1
            if args.max_ticks and ticks >= args.max_ticks:
                break
            stop.wait(args.tick)
    finally:
        if server is not None:
            server.shutdown()
    nodes = len(env.store.list("nodes"))
    bound = sum(1 for p in env.store.list("pods") if p.node_name)
    print(f"karpenter-tpu operator: stopped after {ticks} ticks "
          f"({nodes} nodes, {bound} bound pods)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
