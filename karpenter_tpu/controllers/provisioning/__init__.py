from karpenter_tpu.controllers.provisioning.batcher import Batcher  # noqa: F401
from karpenter_tpu.controllers.provisioning.provisioner import Provisioner  # noqa: F401

__all__ = ["Batcher", "Provisioner"]
