"""Provisioner: the L4 singleton controller.

Mirror of the reference's pkg/controllers/provisioning/provisioner.go:
trigger on unschedulable pods (controller.go:52-66), debounce via the
batcher, snapshot cluster state, build the scheduler inputs (NewScheduler
:219-314 — ready nodepools by weight, per-pool instance types, the topology
domain universe :264-296, daemonset overhead), Solve, truncate instance
types (:363), then create NodeClaims and nominate the pods (:149-160).
"""

from __future__ import annotations

from karpenter_tpu import obs
from karpenter_tpu.api import labels as wk
from karpenter_tpu.controllers.provisioning.batcher import Batcher
from karpenter_tpu.models import ClaimTemplate
from karpenter_tpu.models.solver import make_solver
from karpenter_tpu.models.topology import Topology
from karpenter_tpu.scheduling import daemon_schedulable
from karpenter_tpu.utils import pod as pod_util
from karpenter_tpu.utils import resources as resutil


class ClusterStateView:
    """Topology's window onto bound pods, served from the state plane —
    no per-solve full-store rescans: bindings and the anti-affinity index
    are maintained incrementally by Cluster (state/cluster.py)."""

    def __init__(self, cluster, store):
        self.cluster = cluster
        self.store = store

    def pods_matching(self, namespaces, selector):
        for sn in self.cluster.state_nodes():
            labels = sn.labels()
            for pod in sn.pods.values():
                if pod.namespace not in namespaces:
                    continue
                if selector is not None and not selector.matches(pod.metadata.labels):
                    continue
                yield pod, labels

    def pods_with_anti_affinity(self):
        yield from self.cluster.pods_with_anti_affinity()

    def namespaces_matching(self, selector):
        return [
            ns.metadata.name
            for ns in self.store.list("namespaces")
            if selector.matches(ns.metadata.labels)
        ]


class StoreClusterView:
    """Adapter giving the topology engine visibility into bound pods
    (fallback when no state plane is wired, e.g. bare-solver use)."""

    def __init__(self, store):
        self.store = store
        self._node_labels = None

    def _labels_for(self, node_name):
        if self._node_labels is None:
            self._node_labels = {n.name: n.labels for n in self.store.list("nodes")}
        return self._node_labels.get(node_name, {})

    def pods_matching(self, namespaces, selector):
        for pod in self.store.list("pods"):
            if pod.namespace not in namespaces:
                continue
            if selector is not None and not selector.matches(pod.metadata.labels):
                continue
            yield pod, self._labels_for(pod.node_name)

    def pods_with_anti_affinity(self):
        for pod in self.store.list("pods"):
            if not pod.node_name:
                continue
            if (
                pod.affinity
                and pod.affinity.pod_anti_affinity
                and pod.affinity.pod_anti_affinity.required
            ):
                yield pod, self._labels_for(pod.node_name)

    def namespaces_matching(self, selector):
        return [
            ns.metadata.name
            for ns in self.store.list("namespaces")
            if selector.matches(ns.metadata.labels)
        ]


def collect_domains(domains: dict, template, instance_types):
    """Topology domain universe: values from instance-type requirements
    compatible with the nodepool (provisioner.go:264-296). Shared by the
    provisioner and the perf harness (which must assemble the same scheduler
    inputs the product path does)."""
    np_reqs = template.requirements
    for key, req in np_reqs.items():
        if not req.complement:
            domains.setdefault(key, set()).update(req.values)
    for it in instance_types:
        if it.requirements.intersects(np_reqs) is not None:
            continue
        for key, req in it.requirements.items():
            if req.complement:
                continue
            allowed = np_reqs.get_req(key)
            vals = {v for v in req.values if allowed.has(v)}
            if vals:
                domains.setdefault(key, set()).update(vals)


def nodepool_ready(np) -> bool:
    conds = getattr(np.status, "conditions", None) or []
    for c in conds:
        ctype = c.type if hasattr(c, "type") else c.get("type")
        status = c.status if hasattr(c, "status") else c.get("status")
        if ctype == "Ready":
            return status == "True"
    return True


class Provisioner:
    def __init__(self, store, cloud, solver=None, clock=None, batcher=None, recorder=None, cluster=None, registry=None, log=None):
        from karpenter_tpu.operator.logging import NOP
        from karpenter_tpu.utils.pretty import ChangeMonitor
        from karpenter_tpu.operator import metrics as m
        from karpenter_tpu.utils.clock import Clock

        self.log = log if log is not None else NOP
        self.store = store
        self.cloud = cloud
        self.clock = clock or Clock()
        self.registry = registry or m.REGISTRY
        self.solver = solver or make_solver()
        # production default: the reference's 1s idle / 10s max debounce
        # window (options.go:96-97); test environments inject a 0/0 batcher
        self.batcher = batcher or Batcher(self.clock)
        self.recorder = recorder
        self._change_monitor = ChangeMonitor(clock=self.clock)
        self.cluster = cluster  # state plane (M4); optional
        self._admission = None  # admission plane (priority/gang), lazy
        # unschedulable-pod retry clock (see _maybe_retry_pending)
        self._pending_retry_at: float = -1e18

    # -- triggering (provisioning/controller.go:52-107) ------------------
    def on_event(self, event):
        if event.kind == "pods":
            pod = event.obj
            if event.type != "Deleted" and pod_util.is_provisionable(pod):
                self.batcher.trigger()
        elif event.kind == "nodes" and event.type == "Modified":
            if event.obj.metadata.deletion_timestamp is not None:
                self.batcher.trigger()
        elif event.kind == "nodeclaims" and event.type == "Deleted":
            # a reaped UNREGISTERED claim (liveness TTL, insufficient-
            # capacity rollback) strands any pod nominated onto capacity
            # that will now never materialize: re-arm the batcher so the
            # next round re-solves those pods. The reference's scheduler
            # retries unschedulable pods on a timer; the hermetic runtime
            # is event-driven and must be told. (Pre-ISSUE-14 this was
            # masked by the leader re-acquiring its own stale lease and
            # resyncing — a side effect, not a contract.) REGISTERED
            # claims are exempt: their node's drain path owns the pods
            # (evict → recreate → bind), and re-triggering on every
            # consolidation-wave claim deletion would re-solve the whole
            # displaced set the binder is about to place.
            from karpenter_tpu.api.nodeclaim import COND_REGISTERED

            if not event.obj.is_true(COND_REGISTERED):
                self.batcher.trigger()

    def trigger(self):
        self.batcher.trigger()

    # how often unschedulable pending pods are re-examined without any
    # triggering event — the kube-scheduler's unschedulable-queue retry
    # (and the reference provisioner's periodic singleton reconcile)
    # compressed to the hermetic runtime
    PENDING_RETRY_PERIOD = 10.0

    def _maybe_retry_pending(self) -> bool:
        """Re-arm the batcher for unschedulable pending pods on a slow
        clock, with no triggering event required: capacity can return
        WITHOUT one — an in-place offering flip after an ICE storm, a
        reaped unregistered claim, a PDB releasing — and a purely
        event-driven batcher would strand those pods forever. (The
        pre-ISSUE-14 accidental rescue was the leader resyncing on its
        own stale lease.) At most one pod-list scan per
        PENDING_RETRY_PERIOD of wall clock, so idle rounds between clock
        steps stay free; the fake clock only moves between test rounds,
        bounding this to one retry per step."""
        now = self.clock.now()
        if now - self._pending_retry_at < self.PENDING_RETRY_PERIOD:
            return False
        self._pending_retry_at = now
        if any(pod_util.is_provisionable(p)
               for p in self.store.list("pods")):
            self.batcher.trigger()
            return True
        return False

    @property
    def pending_trigger(self) -> bool:
        return self.batcher.triggered and self.batcher.ready()

    # -- the solve round (provisioner.go Schedule:316) -------------------
    def reconcile(self) -> bool:
        if not self.batcher.triggered and not self._maybe_retry_pending():
            return False
        if not self.batcher.ready():
            return False
        self.batcher.reset()
        from karpenter_tpu.operator import metrics as m

        if self.cluster is not None:
            synced = self.cluster.synced()
            self.registry.gauge(
                m.CLUSTER_STATE_SYNCED, "cluster state mirror in sync"
            ).set(1.0 if synced else 0.0)
            if not synced:
                self.batcher.trigger()  # retry next round
                return False

        # one trace per solve round: the flight recorder keeps the span
        # tree and dumps it if the round trips an anomaly (host-routed
        # pods being the provisioning trigger)
        with obs.round_trace("provision", registry=self.registry):
            with self.registry.measure(m.SCHEDULING_DURATION):
                results = self.schedule()
            if results is None:
                return False
            with obs.span("provision.create"):
                return self.create_node_claims(results)

    def pending_pods(self) -> list:
        """Provisionable pods, excluding ones nominated onto capacity that
        is still materializing (the reference's cluster-state nomination
        serves this role, state/cluster.go Nominate)."""
        out = []
        for p in self.store.list("pods"):
            if not pod_util.is_provisionable(p):
                continue
            if p.nominated_node_name:
                nominated_alive = self.store.try_get(
                    "nodes", p.nominated_node_name
                ) is not None or any(
                    nc.name == p.nominated_node_name
                    for nc in self.store.list("nodeclaims")
                )
                if nominated_alive:
                    continue  # capacity is materializing; the binder lands it
                p.nominated_node_name = ""  # stale nomination: re-provision
            out.append(p)
        return out

    def schedule(self, pods=None, state_nodes=None, inputs=None,
                 enodes_base=None, existing_base=None):
        # nodes are snapshotted BEFORE pods are listed: a pod that binds in
        # between appears both as pending and in its node's usage, which
        # over-provisions (safe); the reverse order would under-provision
        # (provisioner.go:318-329). The disruption simulation passes its own
        # candidate-free snapshot (disruption/helpers.go:51).
        live_batch = pods is None  # explicit pods = a disruption simulation
        if state_nodes is None:
            state_nodes = self.cluster.nodes() if self.cluster is not None else []
        if pods is None:
            with obs.span("provision.pending"):
                pods = self.pending_pods()
                pods.extend(self.deleting_node_pods(state_nodes, pods))
            if not pods:
                return None
        # disruption simulations may hand in the round's cached solver
        # inputs (ops/consolidate.py SnapshotCache.inputs_for) — identical
        # content to a fresh assembly within one cluster-state generation,
        # which the cache verifies before releasing them
        if inputs is not None:
            templates, its_by_pool, overhead, limits, domains = inputs
        else:
            with obs.span("provision.inputs", kind="cache"):
                templates, its_by_pool, overhead, limits, domains = (
                    self.solver_inputs()
                )

        # pods with unresolvable PVCs can't schedule: report and drop from
        # the batch (ValidatePersistentVolumeClaims, volumetopology.go:155)
        from karpenter_tpu.operator import metrics as m
        from karpenter_tpu.scheduling.volumetopology import PVCError, VolumeTopology

        vt = VolumeTopology(self.store)
        valid_pods = []
        with obs.span("provision.volumes", pods=len(pods)):
            for p in pods:
                try:
                    vt.validate(p)
                    valid_pods.append(p)
                except PVCError as e:
                    if self.recorder is not None:
                        self.recorder.publish("FailedScheduling", str(e), obj=p)
        # provisioning/metrics.go: queue depth at solve entry + pods the
        # batch dropped as unresolvable. Only the LIVE batch reports —
        # disruption counterfactuals must not clobber the gauges (the
        # reference mutes its simulations the same way, helpers.go:84)
        if live_batch:
            self.registry.gauge(
                m.SCHEDULING_QUEUE_DEPTH, "pods entering the solve"
            ).set(len(valid_pods))
            self.registry.gauge(
                m.IGNORED_PODS, "pods ignored this batch (unresolvable PVCs)"
            ).set(len(pods) - len(valid_pods))
        pods = valid_pods
        if not pods:
            # explicit-pods callers (disruption simulation) expect a results
            # object, never None — an all-filtered batch solves to nothing
            from karpenter_tpu.models.scheduler import SchedulerResults

            return SchedulerResults(new_claims=[], existing_nodes=[], pod_errors={})

        view = (
            ClusterStateView(self.cluster, self.store)
            if self.cluster is not None
            else StoreClusterView(self.store)
        )
        with obs.span("provision.topology"):
            topology = Topology(cluster=view, domains=domains, pods=pods)
        with obs.span("provision.existing"):
            if enodes_base is not None:
                # disruption fast path (helpers.simulate_scheduling): the
                # round's snapshot bundle supplies generation-current
                # ExistingNode prototypes; forking re-binds them to THIS
                # solve's topology and fresh mutable state, skipping the O(E)
                # constructor sweep per confirming simulation
                existing_nodes = [en.fork(topology) for en in enodes_base]
            else:
                existing_nodes = self._existing_nodes(state_nodes, topology)
        # live batches with admission markers (pod priorities, gang
        # annotations, a tiering default class) route through the
        # admission plane — the tiered cascade, gang atomicity, and the
        # preemption ladder (karpenter_tpu/admission). Disruption
        # counterfactuals and marker-free batches keep the single solve.
        plane = self.admission_plane() if live_batch else None
        if plane is not None and plane.engages(pods):
            with obs.span("provision.admission", pods=len(pods)):
                results = plane.solve_round(
                    self.solver,
                    pods,
                    templates,
                    its_by_pool,
                    topology=topology,
                    existing_nodes=existing_nodes,
                    daemon_overhead=overhead,
                    limits=limits or None,
                    volume_topology=vt,
                )
        else:
            results = self.solver.solve(
                pods,
                templates,
                its_by_pool,
                topology=topology,
                existing_nodes=existing_nodes,
                daemon_overhead=overhead,
                limits=limits or None,
                volume_topology=vt,
                existing_base=existing_base,
            )
        # host-routed accounting (live batches only — disruption
        # counterfactuals must not inflate the counter, helpers.go:84
        # stance): pods the device compiler handed to the host engine,
        # by reason, so a grid regression is attributable from the scrape
        if live_batch:
            # admission rounds aggregate host-routed reasons across the
            # whole cascade (the solver's last_device_stats only reflects
            # its final inner call); plain rounds read the solver directly
            adm = getattr(results, "admission", None)
            if adm is not None:
                routed = adm.get("host_routed") or {}
            else:
                routed = getattr(
                    self.solver, "last_device_stats", None
                ) or {}
                routed = routed.get("host_routed") or {}
            if routed:
                ctr = self.registry.counter(
                    m.PROVISIONING_HOST_ROUTED,
                    "pods routed to the host engine per live solve, by reason",
                )
                for reason, count in routed.items():
                    if count:
                        ctr.inc(count, reason=reason)
                # anomaly trigger: a live batch leaving the device path is
                # the grid-regression signature — keep this round's span
                # tree (obs flight recorder) so the reason is causal, not
                # just a counter spike. The CALIBRATED crossovers are
                # exempt: routing a tiny batch to the host/C++ engine
                # (small-batch) or having no ready nodepool (no-templates)
                # is by-design, and flagging them would dump every quiet
                # production round
                refused = {
                    r: n for r, n in routed.items()
                    if r not in ("small-batch", "no-templates")
                }
                total_refused = sum(refused.values())
                if total_refused:
                    obs.anomaly(
                        "host-routed", registry=self.registry,
                        pods=total_refused,
                        reasons=",".join(sorted(refused)),
                    )
        results.truncate_instance_types()
        return results

    def admission_plane(self):
        """The admission plane (priority tiers / gangs / preemption),
        built lazily — marker-free fleets never pay the import."""
        if self._admission is None:
            from karpenter_tpu.admission import AdmissionPlane

            self._admission = AdmissionPlane(
                self.store, registry=self.registry, recorder=self.recorder,
                log=self.log,
            )
        return self._admission

    def solver_inputs(self):
        """Per-nodepool solver inputs: (templates, instance types by pool,
        daemon overhead, remaining limits, topology domain universe) — the
        NewScheduler assembly (scheduler.go:160-230), shared by the solve
        path and the batched consolidation probe."""
        nodepools = [np for np in self.store.list("nodepools") if nodepool_ready(np)]
        templates, its_by_pool, overhead, limits = [], {}, {}, {}
        domains: dict = {}
        for np in nodepools:
            its = self.cloud.get_instance_types(np)
            if not its:
                continue
            template = ClaimTemplate(np)
            templates.append(template)
            its_by_pool[np.name] = its
            self._collect_domains(domains, template, its)
            overhead[np.name] = self._daemon_overhead(template)
            if np.spec.limits:
                in_use = self._nodepool_usage(np)
                limits[np.name] = {
                    r: v - in_use.get(r, 0.0)
                    for r, v in resutil.parse_resources(np.spec.limits).items()
                }
        return templates, its_by_pool, overhead, limits, domains

    def _collect_domains(self, domains, template, instance_types):
        collect_domains(domains, template, instance_types)

    def _daemon_overhead(self, template) -> dict:
        """Sum of daemonset pod requests that would land on this pool's
        nodes (scheduler.go:335 getDaemonOverhead)."""
        total: dict = {}
        for ds in self.store.list("daemonsets"):
            p = ds.template
            if p is None:
                continue
            if not daemon_schedulable(
                p, template.taints, template.requirements, allow_undefined=wk.WELL_KNOWN_LABELS
            ):
                continue
            total = resutil.merge(total, p.effective_requests())
        return total

    def _nodepool_usage(self, np) -> dict:
        # live aggregation, not status.resources: the counter controller's
        # status snapshot lags within a reconcile round, and a stale zero
        # would let a launch overshoot the limit (the reference tolerates
        # this transient; we don't have to)
        from karpenter_tpu.controllers.nodepool.counter import aggregate_pool_usage

        return aggregate_pool_usage(self.store, np)

    def deleting_node_pods(self, state_nodes, already: list) -> list:
        """Reschedulable pods bound to nodes being drained or marked for
        deletion: capacity must be pre-provisioned for them
        (provisioner.go:340 GetPodsFromNodes)."""
        seen = {p.uid for p in already}
        out = []
        for sn in state_nodes:
            if not (sn.deleting() or sn.marked_for_deletion):
                continue
            for p in sn.reschedulable_pods():
                if p.uid not in seen:
                    out.append(p)
        return out

    def _existing_nodes(self, state_nodes, topology):
        """Existing/in-flight capacity as scheduling targets, each carrying
        the daemonset requests that will land on it (scheduler.go
        NewScheduler's per-node daemon filtering)."""
        from karpenter_tpu.models.existing import ExistingNode

        from karpenter_tpu.scheduling import label_requirements

        daemons = [ds.template for ds in self.store.list("daemonsets") if ds.template is not None]
        out = []
        for sn in state_nodes:
            if sn.marked_for_deletion or sn.deleting():
                continue
            taints = sn.taints()
            node_reqs = label_requirements(sn.labels()) if daemons else None
            daemon_resources: dict = {}
            for p in daemons:
                if daemon_schedulable(p, taints, node_reqs):
                    daemon_resources = resutil.merge(daemon_resources, p.effective_requests())
            out.append(ExistingNode(sn, topology, daemon_resources, kube=self.store))
        return out

    # -- claim creation (provisioner.go CreateNodeClaims:149) ------------
    def create_node_claims(self, results) -> bool:
        from karpenter_tpu.operator import metrics as m

        created = False
        for claim in results.new_claims:
            nc = claim.to_node_claim()
            self.store.create("nodeclaims", nc)
            self.registry.counter(m.NODECLAIMS_CREATED, "nodeclaims created").inc(
                nodepool=claim.template.nodepool_name)
            created = True
            for p in claim.pods:
                if p.node_name:
                    continue  # drain pre-provisioning: pod is still bound
                p.nominated_node_name = nc.name
                self.store.update("pods", p)
        # pods placed on existing capacity are nominated so the next solve
        # round doesn't re-provision for them (Results.Record, scheduler.go:96)
        for node in results.existing_nodes:
            pods = getattr(node, "scheduled_pods", None) or []
            for p in pods:
                if p.node_name:
                    continue  # drain pre-provisioning: pod is still bound
                p.nominated_node_name = node.name
                self.store.update("pods", p)
            if pods and self.cluster is not None:
                self.cluster.nominate(node.name)
        if results.new_claims:
            # provisioner.go:149's "created nodeclaim" log line, one per round
            self.log.info(
                "launched nodeclaims",
                claims=len(results.new_claims),
                pods=sum(len(c.pods) for c in results.new_claims),
                pools=",".join(sorted({
                    c.template.nodepool_name for c in results.new_claims})),
            )
        for pod_key, err in results.pod_errors.items():
            if self.recorder is not None and self._change_monitor.has_changed(
                pod_key, err
            ):
                # emit-on-change (pretty.ChangeMonitor): a pod stuck with
                # the SAME error re-solves every batch but reports once;
                # a different error (or a day of stasis) reports again
                self.recorder.publish(
                    "FailedScheduling", f"pod {pod_key} incompatible: {err}"
                )
        # pods that scheduled this round (onto new claims OR existing
        # capacity) drop out of the monitor so a later relapse reports
        # immediately
        for claim in results.new_claims:
            for p in claim.pods:
                self._change_monitor.forget(p.key())
        for node in results.existing_nodes:
            for p in getattr(node, "scheduled_pods", None) or []:
                self._change_monitor.forget(p.key())
        return created
