"""Pod-arrival debounce window.

Mirror of the reference's Batcher (pkg/controllers/provisioning/
batcher.go:29-75): after the first trigger, wait until `idle_duration`
passes without new triggers, capped at `max_duration` total — batching a
burst of pending pods into one solve.
"""

from __future__ import annotations

DEFAULT_IDLE = 1.0
DEFAULT_MAX = 10.0


class Batcher:
    def __init__(self, clock, idle_duration: float = DEFAULT_IDLE, max_duration: float = DEFAULT_MAX):
        self.clock = clock
        self.idle_duration = idle_duration
        self.max_duration = max_duration
        self._last_trigger: float | None = None
        self._window_start: float | None = None

    def trigger(self):
        now = self.clock.now()
        self._last_trigger = now
        if self._window_start is None:
            self._window_start = now

    @property
    def triggered(self) -> bool:
        return self._window_start is not None

    def ready(self) -> bool:
        """True when the batch window has closed and a solve should run."""
        if self._window_start is None:
            return False
        now = self.clock.now()
        if now - self._window_start >= self.max_duration:
            return True
        return now - (self._last_trigger or now) >= self.idle_duration

    def reset(self):
        self._last_trigger = None
        self._window_start = None
