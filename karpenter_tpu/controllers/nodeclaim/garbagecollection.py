"""NodeClaim garbage collection: cloud ↔ claim orphan reconciliation.

Mirror of the reference's pkg/controllers/nodeclaim/garbagecollection
(controller.go:62-121): periodically List() the cloud provider and

- delete cloud instances whose NodeClaim no longer exists (leaked
  instances — e.g. the claim was deleted while the controller was down),
  respecting a grace period so freshly-launched instances whose claim
  status hasn't round-tripped yet aren't reaped;
- delete NodeClaims whose cloud instance is gone (the machine died
  underneath us), so the workload reprovisions elsewhere.
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.types import NodeClaimNotFoundError

# instances younger than this are never treated as leaked; mirrors the
# reference's use of nodeclaim creation recency to avoid racing Launch
GRACE_PERIOD = 5 * 60.0


class NodeClaimGarbageCollectionController:
    def __init__(self, store, cloud, clock=None, recorder=None):
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.cloud = cloud
        self.clock = clock or Clock()
        self.recorder = recorder

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        claims = self.store.list("nodeclaims")
        by_pid = {c.status.provider_id: c for c in claims if c.status.provider_id}

        # one LIST serves both directions (the reference GC also works off a
        # single cloudProvider.List per resync, controller.go:62)
        cloud_claims = list(self.cloud.list())
        cloud_pids = {c.status.provider_id for c in cloud_claims}

        # leaked cloud instances: exist in the cloud, no claim references them
        for cloud_claim in cloud_claims:
            pid = cloud_claim.status.provider_id
            if pid in by_pid:
                continue
            created = cloud_claim.metadata.creation_timestamp or 0.0
            if self.clock.now() - created < GRACE_PERIOD:
                continue
            try:
                self.cloud.delete(cloud_claim)
            except NodeClaimNotFoundError:
                pass
            if self.recorder is not None:
                self.recorder.publish(
                    "GarbageCollected", f"deleted leaked instance {pid}")
            progressed = True

        # dead instances: claim is Launched+Registered but the cloud lost it
        for claim in claims:
            if not claim.status.provider_id or claim.metadata.deletion_timestamp is not None:
                continue
            if not claim.registered:
                continue  # lifecycle liveness handles pre-registration death
            if claim.status.provider_id not in cloud_pids:
                self.store.delete("nodeclaims", claim)
                if self.recorder is not None:
                    self.recorder.publish(
                        "GarbageCollected",
                        f"deleted nodeclaim {claim.name}: instance disappeared")
                progressed = True
        return progressed
