"""NodeClaim consistency: invariant checks between a claim and its node.

Mirror of the reference's pkg/controllers/nodeclaim/consistency
(controller.go:78-143): once a claim is initialized, verify the machine the
cloud delivered matches what was promised — the node advertises at least
the claim's requested resources (NodeShape check) and carries the labels
the claim's requirements demanded. Violations emit FailedConsistencyCheck
events and set the ConsistentStateFound condition False; the check is a
canary for provider bugs, not an enforcement path.
"""

from __future__ import annotations

from karpenter_tpu.api.nodeclaim import COND_CONSISTENT
from karpenter_tpu.scheduling import label_requirements, node_selector_requirements


class NodeClaimConsistencyController:
    def __init__(self, store, clock=None, recorder=None):
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.clock = clock or Clock()
        self.recorder = recorder
        # event-driven re-check set: the invariant between a claim and its
        # node only moves when one of THEM moves, so re-deriving every
        # claim's requirement objects each poll is O(claims × labels) of
        # pure waste on an idle fleet. A claim is (re)checked when its
        # condition is missing or when a node/claim event names it.
        self._dirty: set = set()

    def on_event(self, event):
        obj = event.obj
        if event.kind == "nodes":
            if obj.provider_id:
                self._dirty.add(obj.provider_id)
            self._dirty.add(obj.name)
        elif event.kind == "nodeclaims":
            self._dirty.add(obj.name)
            if obj.status.provider_id:
                self._dirty.add(obj.status.provider_id)

    def poll(self) -> bool:
        progressed = False
        limits = None  # built once per poll, only if something terminates
        # provider-id index built once per poll: the per-claim linear node
        # scan was O(claims × nodes) and showed up in fleet-scale benches
        self._nodes_by_pid = {
            n.provider_id: n for n in self.store.list("nodes") if n.provider_id
        }
        self._pods_by_node = None  # built lazily, only if something terminates
        dirty, self._dirty = self._dirty, set()
        for claim in list(self.store.list("nodeclaims")):
            if claim.metadata.deletion_timestamp is not None:
                # stuck-termination canary (consistency/termination.go:46):
                # a terminating claim whose drain a PDB is blocking gets a
                # visible reason instead of hanging silently. Pure
                # observability: never counts as progress (the recorder
                # dedupes repeats), or a stuck drain would spin the ring.
                if limits is None:
                    from karpenter_tpu.utils.pdb import PdbLimits

                    limits = PdbLimits(self.store)
                self._report_stuck_termination(claim, limits)
                continue
            if not claim.initialized:
                continue
            if (
                claim.get_condition(COND_CONSISTENT) is not None
                and claim.name not in dirty
                and claim.status.provider_id not in dirty
            ):
                continue  # nothing about this pair moved since the last check
            node = self._node_for(claim)
            if node is None:
                continue
            failures = self._check(claim, node)
            want = "False" if failures else "True"
            cond = claim.get_condition(COND_CONSISTENT)
            if cond is None or cond.status != want:
                claim.set_condition(
                    COND_CONSISTENT, status=want,
                    reason="ConsistencyCheckFailed" if failures else "ConsistentStateFound",
                    message="; ".join(failures), now=self.clock.now())
                self.store.update("nodeclaims", claim)
                if failures and self.recorder is not None:
                    self.recorder.publish(
                        "FailedConsistencyCheck", "; ".join(failures), obj=claim)
                progressed = True
        return progressed

    def _report_stuck_termination(self, claim, limits):
        from karpenter_tpu.utils import pod as pod_util

        node = self._node_for(claim)
        if node is None or self.recorder is None:
            return
        if self._pods_by_node is None:
            # one pass over the store instead of one per terminating claim
            # (a consolidation wave terminates hundreds at once)
            self._pods_by_node = {}
            for p in self.store.list("pods"):
                self._pods_by_node.setdefault(p.node_name, []).append(p)
        for pod in self._pods_by_node.get(node.name, ()):
            if pod.metadata.deletion_timestamp:
                continue
            # mirror the drain's own filter (node/termination.py): pods the
            # terminator never evicts cannot block it, so their PDBs must
            # not trigger a false canary
            if pod.owned_by_daemonset() or pod_util.is_owned_by_node(pod):
                continue
            if not pod_util.is_evictable(pod):
                continue
            blocking = limits.can_evict(pod)
            if blocking is not None:
                # emit-once semantics ride the recorder's dedupe TTL
                self.recorder.publish(
                    "FailedConsistencyCheck",
                    f'can\'t drain node, PDB "{pod.namespace}/{blocking}" '
                    "is blocking evictions",
                    obj=claim,
                )
                return

    def _check(self, claim, node) -> list[str]:
        failures = []
        # NodeShape: the node must register at least the allocatable the
        # claim's instance type promised (consistency/nodeshape.go)
        for r, want in (claim.status.allocatable or {}).items():
            got = node.allocatable.get(r, 0.0)
            if got < want * 0.9:  # kubelet reserves a little; 10% slack
                failures.append(
                    f"node {node.name} allocatable {r}={got} below claim's {want}")
        # node labels must satisfy the claim's requirements
        # two-way overlap only: an Exists/complement requirement stamps no
        # node label by design (Requirements.labels() skips unbounded sets),
        # so a one-way Compatible check would false-positive on it forever
        reqs = node_selector_requirements(claim.spec.requirements)
        err = label_requirements(node.labels).intersects(reqs)
        if err is not None:
            failures.append(f"node {node.name} labels conflict with claim requirements: {err}")
        return failures

    def _node_for(self, claim):
        if not claim.status.provider_id:
            return None
        return self._nodes_by_pid.get(claim.status.provider_id)
