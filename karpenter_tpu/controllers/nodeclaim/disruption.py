"""NodeClaim disruption-condition controller.

Mirror of the reference's pkg/controllers/nodeclaim/disruption
(controller.go:70): maintains the status conditions the disruption
controller consumes —

- Drifted (drift.go:46-141): static-field hash mismatch against the owning
  NodePool's annotation, requirement drift (node no longer satisfies the
  pool's requirements), or the cloud provider reporting drift.
- Empty (emptiness.go:45): no reschedulable pods on the node, only under
  the WhenEmpty consolidation policy.
- Expired (expiration.go:38): claim older than the pool's expireAfter.

Conditions only ever flip for initialized claims; deleting claims are
skipped.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodeclaim import COND_DRIFTED, COND_EMPTY, COND_EXPIRED
from karpenter_tpu.api.nodepool import CONSOLIDATION_WHEN_EMPTY
from karpenter_tpu.scheduling import label_requirements, node_selector_requirements


class NodeClaimDisruptionController:
    def __init__(self, store, cloud, cluster, clock=None, registry=None):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.cloud = cloud
        self.cluster = cluster
        self.clock = clock or Clock()
        self.registry = registry or _m.REGISTRY
        self._disrupted = self.registry.counter(
            _m.NODECLAIMS_DISRUPTED, "nodeclaims disrupted by reason")

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        pools = {np.name: np for np in self.store.list("nodepools")}
        for claim in list(self.store.list("nodeclaims")):
            if claim.metadata.deletion_timestamp is not None:
                continue
            np = pools.get(claim.metadata.labels.get(wk.NODEPOOL_LABEL))
            if np is None:
                continue
            if self._reconcile_drift(claim, np):
                progressed = True
            if self._reconcile_empty(claim, np):
                progressed = True
            if self._reconcile_expired(claim, np):
                progressed = True
        return progressed

    # -- drift (nodeclaim/disruption/drift.go:46) ------------------------
    def _reconcile_drift(self, claim, np) -> bool:
        if not claim.launched:
            return False
        reason = self._drift_reason(claim, np)
        if reason and not claim.is_true(COND_DRIFTED):
            claim.set_condition(COND_DRIFTED, reason=reason, now=self.clock.now())
            self.store.update("nodeclaims", claim)
            return True
        if not reason and claim.get_condition(COND_DRIFTED) is not None:
            claim.clear_condition(COND_DRIFTED)
            self.store.update("nodeclaims", claim)
            return True
        return False

    def _drift_reason(self, claim, np) -> str | None:
        # static-field hash (drift.go areStaticFieldsDrifted)
        pool_hash = np.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION)
        pool_ver = np.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION)
        claim_hash = claim.metadata.annotations.get(wk.NODEPOOL_HASH_ANNOTATION)
        claim_ver = claim.metadata.annotations.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION)
        if pool_hash and claim_hash and pool_ver == claim_ver and pool_hash != claim_hash:
            return "NodePoolDrifted"
        # requirement drift (drift.go areRequirementsDrifted): the pool's
        # requirements must still admit the claim's labels
        pool_reqs = node_selector_requirements(np.spec.template.requirements)
        claim_labels = label_requirements(claim.metadata.labels)
        for key, req in pool_reqs.items():
            have = claim_labels.get_req(key)
            if len(req.intersection(have)) == 0:
                return "RequirementsDrifted"
        # cloud-provider drift (e.g. AMI drift in real providers)
        cloud_reason = self.cloud.is_drifted(claim)
        if cloud_reason:
            return cloud_reason
        return None

    # -- emptiness (nodeclaim/disruption/emptiness.go:45) ----------------
    def _reconcile_empty(self, claim, np) -> bool:
        if np.spec.disruption.consolidation_policy != CONSOLIDATION_WHEN_EMPTY:
            if claim.get_condition(COND_EMPTY) is not None:
                claim.clear_condition(COND_EMPTY)
                self.store.update("nodeclaims", claim)
                return True
            return False
        if not claim.initialized:
            return False
        sn = self.cluster.node_for(claim.status.provider_id)
        if sn is None:
            return False
        empty = not sn.reschedulable_pods()
        if empty and not claim.is_true(COND_EMPTY):
            claim.set_condition(COND_EMPTY, now=self.clock.now())
            self.store.update("nodeclaims", claim)
            return True
        if not empty and claim.get_condition(COND_EMPTY) is not None:
            claim.clear_condition(COND_EMPTY)
            self.store.update("nodeclaims", claim)
            return True
        return False

    # -- expiration (nodeclaim/disruption/expiration.go:38-58) -----------
    def _reconcile_expired(self, claim, np) -> bool:
        expire_after = np.spec.disruption.expire_after
        if not expire_after:
            if claim.get_condition(COND_EXPIRED) is not None:
                claim.clear_condition(COND_EXPIRED)
                self.store.update("nodeclaims", claim)
                return True
            return False
        age = self.clock.now() - claim.metadata.creation_timestamp
        if age < expire_after:
            return False
        if not claim.is_true(COND_EXPIRED):
            claim.set_condition(COND_EXPIRED, now=self.clock.now())
            self.store.update("nodeclaims", claim)
        # the reference FORCEFULLY expires: the claim is deleted outright —
        # no simulation, no budget, no pre-provisioned replacement
        # (expiration.go:52 "we can forcefully expire the nodeclaim");
        # the termination finalizer ring still drains the node gracefully,
        # and displaced pods re-provision through the normal pending path.
        # (poll() already skips terminating claims, so delete runs once.)
        self.store.delete("nodeclaims", claim)
        self._disrupted.inc(
            type="expiration",
            nodepool=claim.metadata.labels.get(wk.NODEPOOL_LABEL, ""))
        return True
