"""NodeClaim lifecycle: Launch → Register → Initialize (+ liveness,
termination finalizer).

Mirror of the reference's pkg/controllers/nodeclaim/lifecycle
(controller.go:78-126, launch.go:45, registration.go:43,
initialization.go:49, liveness.go:40-58) and nodeclaim/termination
(controller.go:67-140): claims are launched through the CloudProvider,
joined to their Node by providerID, initialized once the node is ready with
startup taints cleared and requested resources registered, deleted and
retried if registration doesn't happen within the liveness TTL, and on
deletion the finalizer tears down the cloud instance then the node.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.nodeclaim import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
)
from karpenter_tpu.cloudprovider.types import InsufficientCapacityError, NodeClaimNotFoundError
from karpenter_tpu.obs import timeline
from karpenter_tpu.operator import metrics as m
from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS

REGISTRATION_TTL = 15 * 60.0  # liveness.go:40


class NodeClaimLifecycleController:
    def __init__(self, store, cloud, clock=None, recorder=None, registry=None):
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.cloud = cloud
        self.clock = clock or Clock()
        self.recorder = recorder
        self.registry = registry or m.REGISTRY

    def _count(self, family: str, claim):
        """Machine-lifecycle counter labelled by nodepool
        (pkg/metrics/metrics.go:30)."""
        self.registry.counter(family).inc(
            nodepool=claim.metadata.labels.get(wk.NODEPOOL_LABEL, ""))

    def on_event(self, event):
        pass  # reconciled via poll() sweeps in the hermetic runtime

    def poll(self) -> bool:
        claims = list(self.store.list("nodeclaims"))
        if not claims:
            return False
        # one providerID→node index per poll: `_node_for` per claim was a
        # full node scan, O(claims × nodes) per poll — it dominated the
        # post-command wave at fleet scale, where every retired claim's
        # finalizer walks the lookup several times. A node _launch creates
        # mid-poll belongs to the claim that just launched (which already
        # returned for this poll), so the index cannot serve a stale miss
        # to any OTHER claim; deletion timestamps are visible through the
        # shared object identity.
        self._nodes_by_pid = {}
        for node in self.store.list("nodes"):
            if node.provider_id:
                self._nodes_by_pid.setdefault(node.provider_id, node)
        try:
            progressed = False
            for claim in claims:
                if self.reconcile(claim):
                    progressed = True
            return progressed
        finally:
            self._nodes_by_pid = None
            self._catalog = None

    def reconcile(self, claim) -> bool:
        if claim.metadata.deletion_timestamp is not None:
            return self._finalize(claim)
        if not claim.is_true(COND_LAUNCHED):
            return self._launch(claim)
        changed = False
        if not claim.is_true(COND_REGISTERED):
            changed = self._register(claim)
            if not claim.is_true(COND_REGISTERED):
                changed = self._liveness(claim) or changed
                return changed
        if not claim.is_true(COND_INITIALIZED):
            changed = self._initialize(claim) or changed
        return changed

    # -- launch (lifecycle/launch.go:45) ---------------------------------
    def _launch(self, claim) -> bool:
        try:
            launched = self.cloud.create(claim)
        except InsufficientCapacityError as e:
            # terminal: delete so scheduling retries elsewhere (launch.go:80)
            if self.recorder is not None:
                self.recorder.publish("InsufficientCapacity", str(e))
            claim.metadata.finalizers = []
            self.store.delete("nodeclaims", claim)
            return True
        claim.status.provider_id = launched.status.provider_id
        claim.status.node_name = launched.status.node_name
        claim.status.capacity = launched.status.capacity
        claim.status.allocatable = launched.status.allocatable
        claim.metadata.labels = dict(launched.metadata.labels)
        claim.set_condition(COND_LAUNCHED, now=self.clock.now())
        self.store.update("nodeclaims", claim)
        self._count(m.NODECLAIMS_LAUNCHED, claim)
        timeline.note_launch(
            claim.metadata.name, node=claim.status.node_name,
            price=self._launch_price(claim.metadata.labels),
            registry=self.registry,
            nodepool=claim.metadata.labels.get(wk.NODEPOOL_LABEL, ""))
        return True

    # -- registration (lifecycle/registration.go:43) ---------------------
    def _register(self, claim) -> bool:
        node = self._node_for(claim)
        if node is None:
            return False
        # sync labels/taints from the claim onto the node; drop the
        # unregistered NoExecute taint
        node.metadata.labels.update(claim.metadata.labels)
        node.metadata.labels[wk.NODE_REGISTERED_LABEL] = "true"
        node.taints = [t for t in node.taints if t.key != wk.UNREGISTERED_TAINT_KEY]
        # managed nodes drain through the termination finalizer
        # (registration.go syncs it onto the node)
        if wk.TERMINATION_FINALIZER not in node.metadata.finalizers:
            node.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
        self.store.update("nodes", node)
        claim.status.node_name = node.name
        claim.set_condition(COND_REGISTERED, now=self.clock.now())
        self.store.update("nodeclaims", claim)
        self._count(m.NODECLAIMS_REGISTERED, claim)
        self._count(m.NODES_CREATED, claim)  # node joined the cluster
        timeline.record_event("register", node.name,
                              claim=claim.metadata.name,
                              registry=self.registry)
        return True

    # -- initialization (lifecycle/initialization.go:49) -----------------
    def _initialize(self, claim) -> bool:
        node = self._node_for(claim)
        if node is None or not node.ready:
            return False
        ephemeral = {t.key for t in KNOWN_EPHEMERAL_TAINTS}
        startup_keys = {t.key for t in claim.spec.startup_taints}
        if any(t.key in ephemeral or t.key in startup_keys for t in node.taints):
            return False
        # requested resources must be registered on the node
        for r, v in (claim.status.allocatable or {}).items():
            if node.allocatable.get(r, 0.0) <= 0 and v > 0:
                return False
        node.metadata.labels[wk.NODE_INITIALIZED_LABEL] = "true"
        self.store.update("nodes", node)
        claim.set_condition(COND_INITIALIZED, now=self.clock.now())
        self.store.update("nodeclaims", claim)
        self._count(m.NODECLAIMS_INITIALIZED, claim)
        return True

    # -- liveness (lifecycle/liveness.go:40) -----------------------------
    def _liveness(self, claim) -> bool:
        age = self.clock.now() - claim.metadata.creation_timestamp
        if age > REGISTRATION_TTL:
            self.store.delete("nodeclaims", claim)
            return True
        return False

    # -- termination finalizer (nodeclaim/termination/controller.go:67) --
    def _finalize(self, claim) -> bool:
        if wk.TERMINATION_FINALIZER not in claim.metadata.finalizers:
            return False
        node = self._node_for(claim)
        if node is not None:
            if node.metadata.deletion_timestamp is None:
                # start the graceful drain; the node.termination controller
                # evicts pods and releases the node's finalizer
                self.store.delete("nodes", node)
                return True
            return False  # drain in progress: wait for the node to go away
        if claim.status.provider_id:
            try:
                self.cloud.delete(claim)
            except NodeClaimNotFoundError:
                pass
        claim.metadata.finalizers = [
            f for f in claim.metadata.finalizers if f != wk.TERMINATION_FINALIZER
        ]
        self.store.update("nodeclaims", claim)
        self._count(m.NODECLAIMS_TERMINATED, claim)
        if claim.metadata.deletion_timestamp is not None:
            # delete-request → instance-gone latency (the reference's
            # NodeClaimTerminationDuration summary)
            self.registry.histogram(
                m.NODECLAIM_TERMINATION_DURATION,
                "seconds from nodeclaim deletion to finalizer release",
            ).observe(self.clock.now() - claim.metadata.deletion_timestamp,
                      nodepool=claim.metadata.labels.get(wk.NODEPOOL_LABEL, ""))
        return True

    _nodes_by_pid = None  # per-poll providerID index (see poll)
    _catalog = None  # per-poll CatalogView memo (see poll)

    def _launch_price(self, labels) -> float:
        """Effective hourly price of the launched offering — the fleet
        ledger's launch-rate input (obs/timeline.py). The CatalogView is
        memoized per poll like ``_nodes_by_pid``; a direct ``reconcile``
        call pays one transient view."""
        from karpenter_tpu.cloudprovider.types import CatalogView, effective_price

        view = self._catalog
        if view is None:
            view = CatalogView(self.store.list("nodepools"), self.cloud)
            if self._nodes_by_pid is not None:  # inside a poll: memoize
                self._catalog = view
        off = view.offering(labels)
        return float(effective_price(off)) if off is not None else 0.0

    def _node_for(self, claim):
        if not claim.status.provider_id:
            return None
        if self._nodes_by_pid is not None:
            return self._nodes_by_pid.get(claim.status.provider_id)
        for node in self.store.list("nodes"):
            if node.provider_id == claim.status.provider_id:
                return node
        return None
