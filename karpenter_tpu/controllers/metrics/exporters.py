"""Node / NodePool / Pod state exporters.

Each poll() rebuilds its gauge families from the store — the
delete-then-set sweep the reference's metrics controllers use
(pkg/controllers/metrics/node/controller.go etc.).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.controllers.nodepool.counter import aggregate_pool_usage
from karpenter_tpu.operator import metrics as m
from karpenter_tpu.utils import resources as resutil


class NodeMetricsController:
    def __init__(self, store, registry=None):
        self.store = store
        self.registry = registry or m.REGISTRY

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        alloc = self.registry.gauge(m.NODES_ALLOCATABLE, "node allocatable by resource")
        total = self.registry.gauge(m.NODES_TOTAL, "nodes by nodepool")
        alloc.clear()
        total.clear()
        counts: dict = {}
        for node in self.store.list("nodes"):
            pool = node.labels.get(wk.NODEPOOL_LABEL, "")
            counts[pool] = counts.get(pool, 0) + 1
            for r, v in node.allocatable.items():
                alloc.inc(v, node_name=node.name, nodepool=pool, resource_type=r)
        for pool, n in counts.items():
            total.set(n, nodepool=pool)
        return False  # metrics sweeps never change cluster state


class PodMetricsController:
    def __init__(self, store, registry=None):
        self.store = store
        self.registry = registry or m.REGISTRY

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        g = self.registry.gauge(m.PODS_STATE, "pods by phase/binding")
        g.clear()
        for pod in self.store.list("pods"):
            g.inc(1, phase=pod.phase, bound=str(bool(pod.node_name)).lower(),
                  namespace=pod.namespace)
        return False


class NodePoolMetricsController:
    def __init__(self, store, registry=None):
        self.store = store
        self.registry = registry or m.REGISTRY

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        usage = self.registry.gauge(m.NODEPOOL_USAGE, "owned capacity by resource")
        limit = self.registry.gauge(m.NODEPOOL_LIMIT, "spec.limits by resource")
        usage.clear()
        limit.clear()
        for np in self.store.list("nodepools"):
            for r, v in aggregate_pool_usage(self.store, np).items():
                usage.set(v, nodepool=np.name, resource_type=r)
            for r, v in resutil.parse_resources(np.spec.limits or {}).items():
                limit.set(v, nodepool=np.name, resource_type=r)
        return False
