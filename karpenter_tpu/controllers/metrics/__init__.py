"""Metric exporter controllers: cluster state as Prometheus gauges.

Mirror of the reference's pkg/controllers/metrics/{node,nodepool,pod}
(controller.go in each): periodic sweeps rebuilding gauge families for
node allocatable, pod phase/state counts, and nodepool usage vs limit.
"""

from karpenter_tpu.controllers.metrics.exporters import (
    NodeMetricsController,
    NodePoolMetricsController,
    PodMetricsController,
)

__all__ = [
    "NodeMetricsController",
    "NodePoolMetricsController",
    "PodMetricsController",
]
