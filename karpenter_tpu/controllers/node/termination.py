"""Node termination: the graceful drain finalizer.

Mirror of the reference's pkg/controllers/node/termination
(controller.go:70-170) + terminator (terminator.go:51-109,
eviction.go:129-193): a deleting node is tainted, its evictable pods are
evicted through the PDB-gated Eviction subresource (429s retried on later
polls), and only when the drain completes does the finalizer release the
node object. Daemonset- and node-owned pods are not evicted — they die with
the node.

Batched drain wave (ISSUE 14): a consolidation command retires whole
node SETS, so one poll may face thousands of deleting nodes. The old
per-node reconcile rescanned the full pod list per node (O(deleting ×
pods) — it dominated the 2k-node global-consolidation wave) and paid a
full PDB recount per eviction. Now each poll builds ONE pods-by-node
index, collects every node's evictable pods, and ships them through the
store's :meth:`~karpenter_tpu.kube.store.KubeStore.evict_wave` — one
PDB-checked wave with memoized allowances, semantically identical to
sequential per-pod evictions in the same order. The wave opens a
``drain`` flight-recorder round (``drain.evict`` / ``drain.finalize``
spans) and feeds the module ``STATS`` the perf harness surfaces as
``evict_ms`` (deploy/README.md "Global consolidation", perf-row schema).
"""

from __future__ import annotations

import time

from karpenter_tpu import obs
from karpenter_tpu.api import labels as wk
from karpenter_tpu.obs import timeline
from karpenter_tpu.controllers.disruption.queue import add_disruption_taint
from karpenter_tpu.utils import pod as pod_util

# process-wide drain accounting, delta'd by `python -m perf global`
STATS = {
    "evict_ms": 0.0,  # time inside the PDB-checked eviction wave
    "drain_ms": 0.0,  # whole drain poll (evict + finalizer decisions)
    "evict_waves": 0,
    "evicted": 0,
    "evict_blocked": 0,
}


class NodeTerminationController:
    def __init__(self, store, clock=None, recorder=None, registry=None):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.clock = clock or Clock()
        self.recorder = recorder
        self.registry = registry or _m.REGISTRY

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        deleting = [
            node
            for node in self.store.list("nodes")
            if node.metadata.deletion_timestamp is not None
            and wk.TERMINATION_FINALIZER in node.metadata.finalizers
        ]
        if not deleting:
            return False
        t0 = time.perf_counter()
        # the drain wave is the root of its own reconcile round, like the
        # binder's pass: the post-command orchestration's wall clock is
        # attributable from its span tree instead of vanishing between
        # disruption rounds
        with obs.round_trace("drain", registry=self.registry,
                             nodes=len(deleting)):
            progressed = self._drain_wave(deleting)
            if not progressed:
                obs.discard_round()  # pure waiting: no story this tick
        STATS["drain_ms"] += (time.perf_counter() - t0) * 1000.0
        return progressed

    def _drain_wave(self, deleting) -> bool:
        progressed = False
        plan = []  # (node, evictable pods) in store order
        wave = []
        with obs.span("drain.evict", kind="host", nodes=len(deleting)):
            # ONE pods-by-node index per poll instead of a full pod scan
            # per deleting node
            pods_by_node: dict = {}
            for pod in self.store.list("pods"):
                if pod.node_name:
                    pods_by_node.setdefault(pod.node_name, []).append(pod)
            for node in deleting:
                if add_disruption_taint(self.store, node):
                    progressed = True
                evictable = [
                    pod
                    for pod in pods_by_node.get(node.name, ())
                    if pod.metadata.deletion_timestamp is None
                    and not pod.owned_by_daemonset()
                    and not pod_util.is_owned_by_node(pod)
                    and pod_util.is_evictable(pod)
                ]
                plan.append((node, evictable))
                wave.extend(evictable)
            t1 = time.perf_counter()
            evicted, blocked = self.store.evict_wave(wave)
            STATS["evict_ms"] += (time.perf_counter() - t1) * 1000.0
            STATS["evict_waves"] += 1
            STATS["evicted"] += len(evicted)
            STATS["evict_blocked"] += len(blocked)
        if evicted:
            progressed = True
        blocked_keys = {p.key() for p in blocked}
        # evict events stage on the drain round's trace: an eviction always
        # means progress, so the round keeps and the events commit
        for node, evictable in plan:
            n = sum(1 for p in evictable if p.key() not in blocked_keys)
            if n:
                timeline.record_event("evict", node.name, pods=n)
        with obs.span("drain.finalize", kind="host"):
            for node, evictable in plan:
                if evictable:
                    # still draining; PDB-blocked pods retry on a later
                    # poll (eviction.go 429 path)
                    if self.recorder is not None:
                        for pod in evictable:
                            if pod.key() in blocked_keys:
                                self.recorder.publish(
                                    "EvictionBlocked",
                                    f"pdb blocks eviction of {pod.key()}",
                                )
                    continue
                if self._finalize(node):
                    progressed = True
        return progressed

    def _finalize(self, node) -> bool:
        """Drain complete for this node: hold for attached CSI volumes,
        else release the termination finalizer (unchanged semantics from
        the per-node reconcile)."""
        if self._blocking_volume_attachments(node):
            # drain done but CSI volumes still attached: hold the finalizer
            # until the attach/detach controller catches up, so a stateful
            # workload's data is flushed before the instance disappears
            # (the reference's await-volume-detach step between drain and
            # finalizer release)
            if self.recorder is not None:
                self.recorder.publish(
                    "AwaitingVolumeDetachment",
                    f"volumes still attached to {node.name}",
                )
            return False
        node.metadata.finalizers = [
            f for f in node.metadata.finalizers if f != wk.TERMINATION_FINALIZER
        ]
        self.store.update("nodes", node)
        # lifecycle counters + graceful-drain latency (the reference's
        # NodesTerminatedCounter + TerminationSummary, termination
        # controller removeFinalizer)
        from karpenter_tpu.operator import metrics as m

        pool = node.labels.get(wk.NODEPOOL_LABEL, "")
        self.registry.counter(m.NODES_TERMINATED, "nodes terminated").inc(
            nodepool=pool)
        # retire closes the node's timeline (and counts a reclaim when an
        # interrupt notice preceded it — the observed interruption feed)
        timeline.record_event(
            "retire", node.name, pool=pool,
            instance_type=node.labels.get(wk.INSTANCE_TYPE_LABEL, ""),
            zone=node.labels.get(wk.TOPOLOGY_ZONE_LABEL, ""),
            registry=self.registry)
        if node.metadata.deletion_timestamp is not None:
            self.registry.histogram(
                m.NODE_TERMINATION_DURATION,
                "seconds from node deletion to finalizer release",
            ).observe(self.clock.now() - node.metadata.deletion_timestamp,
                      nodepool=pool)
        return True

    def _blocking_volume_attachments(self, node) -> list:
        """VolumeAttachments on this node that gate finalizer release.
        Attachments whose PV is used only by pods that survive the drain
        (daemonset- or node-owned) never detach, so they don't block."""
        vas = [
            va
            for va in self.store.list("volumeattachments")
            if va.node_name == node.name and va.metadata.deletion_timestamp is None
        ]
        if not vas:
            return []
        from karpenter_tpu.scheduling.volumes import VolumeUsage

        undrainable_pvs = set()
        for pod in self.store.list("pods"):
            if pod.node_name != node.name:
                continue
            if not (pod.owned_by_daemonset() or pod_util.is_owned_by_node(pod)):
                continue
            for _driver, vol_id in VolumeUsage.pod_volumes(pod, kube=self.store):
                undrainable_pvs.add(vol_id)
        return [va for va in vas if va.pv_name not in undrainable_pvs]
