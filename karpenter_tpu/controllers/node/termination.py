"""Node termination: the graceful drain finalizer.

Mirror of the reference's pkg/controllers/node/termination
(controller.go:70-170) + terminator (terminator.go:51-109,
eviction.go:129-193): a deleting node is tainted, its evictable pods are
evicted through the PDB-gated Eviction subresource (429s retried on later
polls), and only when the drain completes does the finalizer release the
node object. Daemonset- and node-owned pods are not evicted — they die with
the node.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.controllers.disruption.queue import add_disruption_taint
from karpenter_tpu.kube.store import TooManyRequests
from karpenter_tpu.utils import pod as pod_util


class NodeTerminationController:
    def __init__(self, store, clock=None, recorder=None, registry=None):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.utils.clock import Clock

        self.store = store
        self.clock = clock or Clock()
        self.recorder = recorder
        self.registry = registry or _m.REGISTRY

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        for node in list(self.store.list("nodes")):
            if node.metadata.deletion_timestamp is None:
                continue
            if wk.TERMINATION_FINALIZER not in node.metadata.finalizers:
                continue
            if self._reconcile(node):
                progressed = True
        return progressed

    def _reconcile(self, node) -> bool:
        progressed = add_disruption_taint(self.store, node)
        draining = False
        for pod in self.store.list("pods"):
            if pod.node_name != node.name:
                continue
            if pod.metadata.deletion_timestamp is not None:
                continue
            if pod.owned_by_daemonset() or pod_util.is_owned_by_node(pod):
                continue
            if not pod_util.is_evictable(pod):
                continue
            draining = True
            try:
                self.store.evict(pod)
                progressed = True
            except TooManyRequests:
                # PDB-blocked: retry on a later poll (eviction.go 429 path)
                if self.recorder is not None:
                    self.recorder.publish(
                        "EvictionBlocked", f"pdb blocks eviction of {pod.key()}"
                    )
        if draining:
            return progressed
        if self._blocking_volume_attachments(node):
            # drain done but CSI volumes still attached: hold the finalizer
            # until the attach/detach controller catches up, so a stateful
            # workload's data is flushed before the instance disappears
            # (the reference's await-volume-detach step between drain and
            # finalizer release)
            if self.recorder is not None:
                self.recorder.publish(
                    "AwaitingVolumeDetachment",
                    f"volumes still attached to {node.name}",
                )
            return progressed
        # drain complete: release the node
        node.metadata.finalizers = [
            f for f in node.metadata.finalizers if f != wk.TERMINATION_FINALIZER
        ]
        self.store.update("nodes", node)
        # lifecycle counters + graceful-drain latency (the reference's
        # NodesTerminatedCounter + TerminationSummary, termination
        # controller removeFinalizer)
        from karpenter_tpu.operator import metrics as m

        pool = node.labels.get(wk.NODEPOOL_LABEL, "")
        self.registry.counter(m.NODES_TERMINATED, "nodes terminated").inc(
            nodepool=pool)
        if node.metadata.deletion_timestamp is not None:
            self.registry.histogram(
                m.NODE_TERMINATION_DURATION,
                "seconds from node deletion to finalizer release",
            ).observe(self.clock.now() - node.metadata.deletion_timestamp,
                      nodepool=pool)
        return True

    def _blocking_volume_attachments(self, node) -> list:
        """VolumeAttachments on this node that gate finalizer release.
        Attachments whose PV is used only by pods that survive the drain
        (daemonset- or node-owned) never detach, so they don't block."""
        vas = [
            va
            for va in self.store.list("volumeattachments")
            if va.node_name == node.name and va.metadata.deletion_timestamp is None
        ]
        if not vas:
            return []
        from karpenter_tpu.scheduling.volumes import VolumeUsage

        undrainable_pvs = set()
        for pod in self.store.list("pods"):
            if pod.node_name != node.name:
                continue
            if not (pod.owned_by_daemonset() or pod_util.is_owned_by_node(pod)):
                continue
            for _driver, vol_id in VolumeUsage.pod_volumes(pod, kube=self.store):
                undrainable_pvs.add(vol_id)
        return [va for va in vas if va.pv_name not in undrainable_pvs]
