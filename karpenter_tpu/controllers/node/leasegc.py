"""Lease garbage collection: delete orphaned kubelet heartbeat leases.

Mirror of the reference's pkg/controllers/leasegarbagecollection
(controller.go:48): kubelets heartbeat through Lease objects in the
kube-node-lease namespace, owned by their Node. When a node is deleted the
kubelet can't clean its lease up; this controller deletes leases whose
owning Node no longer exists.
"""

from __future__ import annotations

NODE_LEASE_NAMESPACE = "kube-node-lease"


class LeaseGarbageCollectionController:
    def __init__(self, store, recorder=None):
        self.store = store
        self.recorder = recorder

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        nodes = {n.metadata.name for n in self.store.list("nodes")}
        for lease in list(self.store.list("leases", namespace=NODE_LEASE_NAMESPACE)):
            owners = [o for o in lease.metadata.owner_references if o.get("kind") == "Node"]
            if not owners:
                continue  # not a kubelet node lease
            if any(o.get("name") in nodes for o in owners):
                continue
            self.store.delete("leases", lease)
            if self.recorder is not None:
                self.recorder.publish(
                    "GarbageCollected", f"deleted orphaned lease {lease.metadata.name}")
            progressed = True
        return progressed
