"""NodePool runtime validation.

Mirror of the reference's pkg/controllers/nodepool/validation
(controller.go:46): checks that can only be done at runtime — budget cron
schedules must parse, percentages/counts must be well-formed, requirement
label keys must not be restricted — and records the result as the
ValidationSucceeded condition the readiness controller folds into Ready.
"""

from __future__ import annotations

import re

from karpenter_tpu.api import labels as wk
from karpenter_tpu.utils.cron import parse_schedule

COND_VALIDATION = "ValidationSucceeded"

_BUDGET_NODES_RE = re.compile(r"((100|[0-9]{1,2})%)|([0-9]+)")


def validate_nodepool(np) -> list[str]:
    """All validation errors for a NodePool spec (empty = valid)."""
    errs = []
    for i, b in enumerate(np.spec.disruption.budgets):
        if b.schedule is not None:
            try:
                parse_schedule(b.schedule)
            except ValueError as e:
                errs.append(f"budgets[{i}].schedule: {e}")
            if b.duration is None:
                errs.append(f"budgets[{i}]: schedule requires duration (CEL rule)")
        elif b.duration is not None:
            errs.append(f"budgets[{i}]: duration requires schedule (CEL rule)")
        # CEL pattern on Budget.Nodes: non-negative integer, or 0-100%
        # (nodepool.go kubebuilder marker ^((100|[0-9]{1,2})(%|$))|([0-9]+)$);
        # a negative count would silently zero allowed_disruptions
        if not _BUDGET_NODES_RE.fullmatch(str(b.nodes).strip()):
            errs.append(f"budgets[{i}].nodes: invalid count/percent {b.nodes!r}")
    for r in np.spec.template.requirements:
        err = wk.is_restricted_label(r.key)
        if err:
            errs.append(f"requirements[{r.key}]: {err}")
    for key in np.spec.template.labels:
        err = wk.is_restricted_label(key)
        if err:
            errs.append(f"labels[{key}]: {err}")
    return errs


class NodePoolValidationController:
    def __init__(self, store, recorder=None):
        self.store = store
        self.recorder = recorder

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        for np in list(self.store.list("nodepools")):
            errs = validate_nodepool(np)
            want = "False" if errs else "True"
            msg = "; ".join(errs)
            cond = np.get_condition(COND_VALIDATION)
            if cond is None or cond.status != want or cond.message != msg:
                np.set_condition(COND_VALIDATION, status=want,
                                 reason="ValidationFailed" if errs else "ValidationSucceeded",
                                 message=msg)
                self.store.update("nodepools", np)
                if errs and self.recorder is not None:
                    self.recorder.publish("NodePoolValidationFailed", msg, obj=np)
                progressed = True
        return progressed
