"""NodePool readiness: Ready condition from the referenced NodeClass.

Mirror of the reference's pkg/controllers/nodepool/readiness
(controller.go:52-100): a NodePool is Ready when its nodeClassRef resolves
to an existing NodeClass whose Ready condition is not False, and runtime
validation (ValidationSucceeded, set by the validation controller) hasn't
failed. The provisioner skips not-Ready pools (provisioner.go:239
OrderByWeight over ready pools).
"""

from __future__ import annotations

COND_READY = "Ready"
COND_VALIDATION = "ValidationSucceeded"


class NodePoolReadinessController:
    def __init__(self, store):
        self.store = store

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        for np in list(self.store.list("nodepools")):
            ready, reason, msg = self._readiness(np)
            cond = np.get_condition(COND_READY)
            want = "True" if ready else "False"
            if cond is None or cond.status != want or cond.reason != reason or cond.message != msg:
                np.set_condition(COND_READY, status=want, reason=reason, message=msg)
                self.store.update("nodepools", np)
                progressed = True
        return progressed

    def _readiness(self, np):
        vc = np.get_condition(COND_VALIDATION)
        if vc is not None and vc.status == "False":
            return False, "ValidationFailed", vc.message
        ref = np.spec.template.node_class_ref or {}
        name = ref.get("name")
        if not name:
            return True, "NodeClassRefUnset", ""
        nc = self.store.try_get("nodeclasses", name)
        if nc is None:
            return False, "NodeClassNotFound", f"nodeclass {name} not found"
        if not nc.ready():
            return False, "NodeClassNotReady", f"nodeclass {name} is not ready"
        return True, "NodeClassReady", ""
