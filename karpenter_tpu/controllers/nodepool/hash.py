"""NodePool hash controller: stamps the drift-detection basis.

Mirror of the reference's pkg/controllers/nodepool/hash/controller.go:49-106:
the static-field hash of each NodePool spec is written to its annotations;
NodeClaims stamped from the pool carry the same annotation, and the drift
condition controller compares the two.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk

HASH_VERSION = wk.NODEPOOL_HASH_VERSION


class NodePoolHashController:
    def __init__(self, store):
        self.store = store

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        for np in self.store.list("nodepools"):
            h = np.static_hash()
            ann = np.metadata.annotations
            if ann.get(wk.NODEPOOL_HASH_ANNOTATION) != h or ann.get(
                wk.NODEPOOL_HASH_VERSION_ANNOTATION
            ) != HASH_VERSION:
                if ann.get(wk.NODEPOOL_HASH_VERSION_ANNOTATION) != HASH_VERSION:
                    # hash-version migration: re-stamp owned claims so a
                    # version bump alone never reads as drift
                    # (hash/controller.go updateNodeClaimHash :89-106)
                    from karpenter_tpu.api.nodeclaim import COND_DRIFTED

                    for claim in self.store.list("nodeclaims"):
                        if claim.metadata.labels.get(wk.NODEPOOL_LABEL) != np.name:
                            continue
                        if (
                            claim.metadata.annotations.get(
                                wk.NODEPOOL_HASH_VERSION_ANNOTATION
                            )
                            == HASH_VERSION
                        ):
                            continue
                        # an already-drifted claim keeps its stale hash: the
                        # old hashing scheme is gone, so its drift verdict
                        # cannot be re-derived and must stand
                        if not claim.is_true(COND_DRIFTED):
                            claim.metadata.annotations[wk.NODEPOOL_HASH_ANNOTATION] = h
                        claim.metadata.annotations[
                            wk.NODEPOOL_HASH_VERSION_ANNOTATION
                        ] = HASH_VERSION
                        self.store.update("nodeclaims", claim)
                ann[wk.NODEPOOL_HASH_ANNOTATION] = h
                ann[wk.NODEPOOL_HASH_VERSION_ANNOTATION] = HASH_VERSION
                self.store.update("nodepools", np)
                progressed = True
        return progressed
