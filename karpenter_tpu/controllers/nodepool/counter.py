"""NodePool counter: aggregate owned-node resources into status.

Mirror of the reference's pkg/controllers/nodepool/counter
(controller.go:69-110): sums the capacity of every node (and launched-but-
unregistered nodeclaim) owned by the pool into NodePool.status.resources,
including a synthetic "nodes" count. This aggregate is the input to limits
enforcement (Limits.ExceededBy) in the provisioner.
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.utils import resources as resutil


def aggregate_pool_usage(store, np) -> dict:
    """Capacity owned by the pool right now: registered nodes plus
    launched-but-unregistered claims (merged by providerID the way cluster
    state does), with a synthetic "nodes" count."""
    total: dict = {"nodes": 0.0}
    counted_pids = set()
    for node in store.list("nodes"):
        if node.labels.get(wk.NODEPOOL_LABEL) != np.name:
            continue
        total = resutil.merge(total, node.capacity)
        total["nodes"] += 1
        counted_pids.add(node.provider_id)
    for claim in store.list("nodeclaims"):
        if claim.metadata.labels.get(wk.NODEPOOL_LABEL) != np.name:
            continue
        if claim.status.provider_id in counted_pids:
            continue
        if not claim.status.capacity:
            continue
        total = resutil.merge(total, claim.status.capacity)
        total["nodes"] += 1
    return total


class NodePoolCounterController:
    def __init__(self, store):
        self.store = store

    def on_event(self, event):
        pass

    def poll(self) -> bool:
        progressed = False
        for np in list(self.store.list("nodepools")):
            total = aggregate_pool_usage(self.store, np)
            if total != np.status.resources:
                np.status.resources = total
                self.store.update("nodepools", np)
                progressed = True
        return progressed
