from karpenter_tpu.controllers.disruption.controller import DisruptionController
from karpenter_tpu.controllers.disruption.types import Candidate, Command

__all__ = ["DisruptionController", "Candidate", "Command"]
