"""Disruption candidates and commands.

Mirror of the reference's pkg/controllers/disruption/types.go: a `Candidate`
is a disruptable StateNode annotated with its pool, instance type, offering
price, reschedulable pods, and disruption cost (types.go:53-101); a
`Command` is a set of candidates plus the replacement claims that the
simulation produced, with the resulting action (types.go:103-169).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as wk
from karpenter_tpu.cloudprovider.types import effective_price
from karpenter_tpu.utils.disruption import disruption_cost


class Candidate:
    def __init__(self, state_node, node_pool, instance_type, clock):
        from karpenter_tpu.cloudprovider.types import risk_lambda

        # λ snapshotted at discovery: candidates live one round, and the
        # price property is read across thousands of candidates per round
        # — one env parse per candidate, not one per access
        self._risk_lambda = risk_lambda()
        self.state_node = state_node
        self.node_pool = node_pool
        self.instance_type = instance_type
        labels = state_node.labels()
        self.zone = labels.get(wk.TOPOLOGY_ZONE_LABEL, "")
        self.capacity_type = labels.get(wk.CAPACITY_TYPE_LABEL, wk.CAPACITY_TYPE_ON_DEMAND)
        self.reschedulable_pods = state_node.reschedulable_pods()
        self.disruption_cost = disruption_cost(
            self.reschedulable_pods,
            state_node=state_node,
            expire_after=node_pool.spec.disruption.expire_after,
            now=clock.now(),
        )

    @property
    def name(self) -> str:
        return self.state_node.name

    @property
    def provider_id(self) -> str:
        return self.state_node.provider_id

    @property
    def price(self) -> float:
        """Current EFFECTIVE offering price for this node's (zone,
        capacity type): risk-discounted per cloudprovider/types.
        effective_price, so a risky spot node reads as more expensive to
        keep and consolidation prefers retiring it first — bit-identical
        to the nominal price at λ=0 (the risk-blind default)."""
        o = self.current_offering()
        return (effective_price(o, self._risk_lambda)
                if o is not None else 0.0)

    def current_offering(self):
        """The catalog Offering this node runs on, or None (delisted)."""
        if self.instance_type is None:
            return None
        for o in self.instance_type.offerings:
            if o.zone == self.zone and o.capacity_type == self.capacity_type:
                return o
        return None

    def __repr__(self):
        return f"Candidate({self.name}, cost={self.disruption_cost:.2f})"


DELETE = "delete"
REPLACE = "replace"
NOOP = "no-op"


class Command:
    def __init__(self, candidates, replacements=(), reason: str = ""):
        self.candidates = list(candidates)
        self.replacements = list(replacements)  # [InFlightNodeClaim]
        self.reason = reason
        # orchestration bookkeeping
        self.replacement_names: list = []
        self.created_at: float = 0.0
        self.last_error: str | None = None
        # criterion-predicted savings rate, stamped at execution for the
        # fleet ledger's reconciliation (obs/timeline.py); None when the
        # command was unpriceable
        self.predicted_savings: float | None = None

    @property
    def action(self) -> str:
        if self.replacements:
            return REPLACE
        if self.candidates:
            return DELETE
        return NOOP

    def __repr__(self):
        return (
            f"Command({self.action}, reason={self.reason}, "
            f"candidates={[c.name for c in self.candidates]}, "
            f"replacements={len(self.replacements)})"
        )
