"""The disruption singleton controller.

Mirror of the reference's pkg/controllers/disruption/controller.go: a 10 s
polling loop (:65) that — after the cluster-state sync gate (:116) and
idempotent cleanup of taints left by a dead process (:121-128) — tries each
method in order, executing the first command produced (:130-141). Commands
from consolidation methods are held for a validation TTL (15 s,
consolidation.go:44) and revalidated against fresh state before execution
(validation.go:55-212); our synchronous runtime models the reference's
blocking TTL wait as a pending-command slot re-checked on later polls.
"""

from __future__ import annotations

from karpenter_tpu import obs
from karpenter_tpu.api.nodepool import REASON_EMPTY
from karpenter_tpu.controllers.disruption.helpers import (
    build_disruption_budgets,
    get_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.disruption.methods import (
    Drift,
    Emptiness,
    EmptyNodeConsolidation,
    GlobalConsolidation,
    InterruptionDrain,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.queue import (
    OrchestrationQueue,
    add_disruption_taint,
)

POLL_PERIOD = 10.0  # controller.go:65
VALIDATION_TTL = 15.0  # consolidation.go:44
ABNORMAL_RUN_GAP = 15 * 60.0  # logAbnormalRuns threshold (controller.go:274-283)


class DisruptionContext:
    def __init__(self, provisioner, cluster, store, clock, options=None,
                 registry=None, cloud=None):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.ops.consolidate import SnapshotCache

        self.provisioner = provisioner
        self.cluster = cluster
        self.store = store
        self.clock = clock
        self.options = options or {}
        self.registry = registry or _m.REGISTRY
        # the cloud provider seam: InterruptionDrain rebuilds candidates
        # for noticed nodes the voluntary-disruption filters excluded
        self.cloud = cloud
        # one tensorization per cluster-state generation, shared by every
        # consolidation probe and confirming simulation in a round
        # (ops/consolidate.py documents the invalidation contract)
        self.snapshot_cache = SnapshotCache()
        # the round's joint-dispatch seed (ops/consolidate.py JointSeed):
        # published by GlobalConsolidation, consumed by the MultiNode/
        # SingleNode probes of the SAME generation so one state bump pays
        # one device dispatch, not three (ISSUE 14 short-circuit)
        self.joint_seed = None
        # per-(generation, pool) memo of the shared candidate order
        # (methods._candidate_order): all three consolidation methods
        # sort the same objects within one round — pay it once
        self.order_memo = None


class DisruptionController:
    def __init__(
        self,
        store,
        cluster,
        cloud,
        provisioner,
        clock=None,
        recorder=None,
        options=None,
        poll_period: float = POLL_PERIOD,
        validation_ttl: float = VALIDATION_TTL,
        registry=None,
        log=None,
    ):
        from karpenter_tpu.operator import metrics as _m
        from karpenter_tpu.operator.logging import NOP
        from karpenter_tpu.utils.clock import Clock

        self.log = log if log is not None else NOP
        self.registry = registry or _m.REGISTRY
        self.store = store
        self.cluster = cluster
        self.cloud = cloud
        self.provisioner = provisioner
        self.clock = clock or Clock()
        self.recorder = recorder
        self.poll_period = poll_period
        self.validation_ttl = validation_ttl
        self.ctx = DisruptionContext(
            provisioner, cluster, store, self.clock, options,
            registry=self.registry, cloud=cloud,
        )
        self.queue = OrchestrationQueue(store, cluster, self.clock, recorder)
        self.methods = [
            # interruption FIRST: a reclaim deadline outranks every
            # voluntary method — the node is leaving whether we act or
            # not, and acting early is the whole resilience story
            # (deploy/README.md "Spot resilience")
            InterruptionDrain(self.ctx),
            Drift(self.ctx),
            Emptiness(self.ctx),
            EmptyNodeConsolidation(self.ctx),
            # the joint device-solved retirement runs FIRST among the
            # underutilized methods: when it ships, the per-candidate
            # ladder below never runs (first success wins); every fallback
            # cause hands the round to the ladder, its oracle duty
            # (deploy/README.md "Global consolidation")
            GlobalConsolidation(self.ctx),
            MultiNodeConsolidation(self.ctx),
            SingleNodeConsolidation(self.ctx),
        ]
        self._last_run: float = -1e18
        self._pending = None  # (command, method, computed_at)
        # per-nodepool instance-type catalog memo for candidate discovery
        # (helpers.get_candidates): cleared on nodepool events, shared by
        # compute and validate so repeated rounds stop re-listing the
        # cloud provider. Offering mutations stay visible — the catalog
        # objects are shared by identity.
        self._catalog_cache: dict = {}
        # fence from the last consolidation round that found nothing: while
        # cluster state is unchanged, re-searching is pointless
        # (consolidation.go isConsolidated)
        self._noop_fence = None

    def on_event(self, event):
        if event.kind == "nodepools":
            # a nodepool change can change which instance types it may use
            self._catalog_cache.clear()

    def poll(self) -> bool:
        progressed = self.queue.poll()
        # interruption notices are pulled on EVERY poll (not only on the
        # 10 s round cadence): a two-minute warning must reach cluster
        # state the moment it exists — the pull is one drained list
        self._pull_interruption_notices()
        now = self.clock.now()
        if now - self._last_run < self.poll_period:
            return progressed
        self._log_abnormal_run(now)
        self._last_run = now
        self._observe_fleet_cost(now)
        if not self.cluster.synced():
            return progressed
        # one trace per disruption round: the method ladder, every probe
        # dispatch, and every confirming simulation nest under it, so an
        # anomalous round (probe fallback, >1 MultiNode confirm, snapshot
        # rebuild) dumps with its full causal span tree
        with obs.round_trace("disrupt", registry=self.registry):
            with obs.span("disrupt.taint_cleanup"):
                self._cleanup_orphan_taints()
            if self._pending is not None:
                return self._handle_pending() or progressed
            return self._compute_round() or progressed

    # -- interruption notices (spot resilience) --------------------------
    def _pull_interruption_notices(self):
        """Drain the cloud provider's interruption feed onto cluster
        state: each notice marks its StateNode with the reclaim deadline
        (``Cluster.note_interruption`` — a node-scoped journal entry, so
        the cached disruption snapshot delta-advances), lands a store
        event through the recorder, and counts on
        ``karpenter_interruption_notices_total{outcome}``."""
        from karpenter_tpu.operator import metrics as m

        fn = getattr(self.cloud, "interruption_notices", None)
        if fn is None:
            return
        try:
            notices = fn()
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "interruption-notice pull failed; retrying next poll",
                exc_info=True)
            return
        if not notices:
            return
        counter = self.registry.counter(
            m.INTERRUPTION_NOTICES,
            "spot interruption notices pulled from the cloud provider")
        for n in notices:
            marked = self.cluster.note_interruption(n.provider_id,
                                                    n.deadline)
            counter.inc(outcome="marked" if marked else "unknown-node")
            if marked and self.recorder is not None:
                sn = self.cluster.node_for(n.provider_id)
                self.recorder.publish(
                    "SpotInterruptionNotice",
                    f"capacity behind {sn.name if sn else n.provider_id} "
                    f"will be reclaimed at {n.deadline:.0f}",
                )

    def _has_interruptions(self) -> bool:
        return any(sn.interruption_pending()
                   for sn in self.cluster.state_nodes())

    # -- realized-cost observation (fleet ledger) ------------------------
    def _observe_fleet_cost(self, now: float):
        """Advance the fleet ledger's realized-cost integral once per
        disruption round cadence (piecewise-constant between rounds): one
        CatalogView per sweep resolves every store node's offering, the
        integral accrues into ``karpenter_fleet_cost_realized_total``,
        and exposure-hours feed the observed interruption-rate
        denominators (obs/timeline.py; deploy/README.md "Fleet ledger")."""
        from karpenter_tpu.cloudprovider.types import CatalogView
        from karpenter_tpu.obs import timeline

        view = CatalogView(self.store.list("nodepools"), self.cloud)
        timeline.observe_fleet(self.store.list("nodes"), view, now,
                               registry=self.registry)

    # -- watchdog (logAbnormalRuns, controller.go:274-283) ---------------
    def _log_abnormal_run(self, now: float):
        """Flag pathological gaps between disruption-loop runs — a method
        that silently hangs (unbounded simulation, stuck cloud call) shows
        up here long before anything else notices."""
        from karpenter_tpu.operator import metrics as m

        if self._last_run <= -1e17:  # first run ever
            return
        gap = now - self._last_run
        if gap < ABNORMAL_RUN_GAP:
            return
        self.registry.counter(
            m.DISRUPTION_ABNORMAL_RUNS, "disruption loop gaps exceeding 15 min"
        ).inc()
        if self.recorder is not None:
            self.recorder.publish(
                "AbnormalDisruptionRun",
                f"disruption loop ran {gap:.0f}s after the previous run",
            )

    # -- taint hygiene (controller.go:121-128) ---------------------------
    def _cleanup_orphan_taints(self):
        from karpenter_tpu.api import labels as wk

        queued = {
            c.provider_id for cmd in self.queue.commands for c in cmd.candidates
        }
        for node in self.store.list("nodes"):
            if not any(t.key == wk.DISRUPTION_TAINT_KEY for t in node.taints):
                continue
            sn = self.cluster.node_by_name(node.name)
            pid = sn.provider_id if sn is not None else None
            if pid not in queued and node.metadata.deletion_timestamp is None:
                from karpenter_tpu.controllers.disruption.queue import (
                    remove_disruption_taint,
                )

                remove_disruption_taint(self.store, node)

    # -- the method ladder (controller.go:130-141) -----------------------
    def _compute_round(self) -> bool:
        from karpenter_tpu.operator import metrics as m

        with obs.span("disrupt.candidates"):
            candidates = get_candidates(
                self.cluster, self.store, self.cloud, self.clock,
                queue=self.queue, catalog_cache=self._catalog_cache,
            )
        self.registry.gauge(m.DISRUPTION_ELIGIBLE_NODES, "disruptable candidates").set(
            len(candidates))
        with obs.span("disrupt.budgets"):
            budgets = build_disruption_budgets(
                self.cluster, self.store, self.clock)
        # allowed-disruptions gauge per (nodepool, reason), refreshed every
        # round — including candidate-free ones, so closed budget windows
        # and deleted pools never serve stale values
        # (disruption/helpers.go:242's budget gauge)
        bg = self.registry.gauge(m.DISRUPTION_BUDGETS, "allowed disruptions")
        bg.clear()
        for pool, by_reason in budgets.items():
            for reason, allowed in by_reason.items():
                bg.set(allowed, nodepool=pool, reason=reason)
        if not candidates and not self._has_interruptions():
            # noticed nodes must reach InterruptionDrain even when every
            # node fails the VOLUNTARY-disruption filters (do-not-disrupt,
            # PDB) — the reclaim doesn't care about those
            obs.discard_round()  # idle tick: nothing disruptable
            return False
        fence = self.cluster.consolidation_state()
        ran_search = False
        bundle_warmed = False
        for method in self.methods:
            if method.is_consolidation and fence == self._noop_fence:
                continue  # nothing moved since the last fruitless search
            ran_search = ran_search or method.is_consolidation
            if getattr(method, "uses_bundle", False) and not bundle_warmed:
                # the round's shared snapshot belongs to the ROUND, not to
                # whichever consolidation method happens to run first:
                # acquire (build or delta-advance) it once here so the
                # joint row's formulate_ms measures formulation, and the
                # tensorization cost is attributable as bundle_ms
                bundle_warmed = True
                self._prewarm_bundle(candidates)
            with obs.span(f"method.{type(method).__name__}"), \
                    self.registry.measure(
                        m.DISRUPTION_EVAL_DURATION,
                        method=type(method).__name__):
                cmd = method.compute_command(list(candidates), budgets)
            if cmd is None or not cmd.candidates:
                if getattr(method, "fence_round", False):
                    # the joint dispatch PROVED round-wide no-retirement
                    # (deploy/README.md "Global consolidation"): the
                    # remaining probes could only re-pay dispatches to
                    # learn nothing — close the consolidation round
                    break
                continue
            if method.needs_validation:
                self._pending = (cmd, method, self.clock.now())
                return True
            return self._execute(cmd, method)
        self._noop_fence = fence
        if not ran_search:
            # candidates exist but every consolidation search sat behind
            # the noop fence and the cheap filters (Drift/Emptiness) found
            # nothing — this tick carries no story; recording it every
            # poll_period would churn the one interesting round out of
            # the flight-recorder ring
            obs.discard_round()
        return False

    def _prewarm_bundle(self, candidates):
        """Acquire the round's shared DisruptionSnapshot before the first
        bundle-consuming method runs. This hoists the tensorization
        (build or delta-advance) out of the joint ladder's formulate
        window — the bundle serves Global/MultiNode/SingleNode AND every
        confirming simulation of the round, so its cost is round
        orchestration, reported as ``bundle_ms`` in the perf breakdown
        (deploy/README.md "Global consolidation", perf-row schema). A
        failed build is not fatal: methods re-attempt on demand and fall
        back to their sequential rungs as before."""
        import time as _time

        from karpenter_tpu.controllers.disruption.methods import (
            _consolidatable,
        )
        from karpenter_tpu.models.solver import TPUSolver
        from karpenter_tpu.ops import consolidate as cons

        if not isinstance(getattr(self.provisioner, "solver", None),
                          TPUSolver):
            return
        pool = _consolidatable(candidates)
        if len(pool) < 2:
            return
        t0 = _time.perf_counter()
        with obs.span("disrupt.bundle", kind="cache",
                      candidates=len(pool)):
            try:
                self.ctx.snapshot_cache.get(
                    self.provisioner, self.cluster, self.store, pool,
                    registry=self.registry)
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "snapshot prewarm failed; methods build on demand",
                    exc_info=True)
        cons.GLOBAL_STATS["bundle_ms"] += (
            _time.perf_counter() - t0) * 1000.0

    # -- validation TTL (validation.go:55-212) ---------------------------
    def _handle_pending(self) -> bool:
        cmd, method, computed_at = self._pending
        if self.clock.now() - computed_at < self.validation_ttl:
            obs.discard_round()  # idle tick: waiting out the TTL
            return False  # still inside the TTL window
        self._pending = None
        with obs.span("disrupt.validate", method=type(method).__name__):
            ok = self._validate(cmd, method)
        if not ok:
            return True  # dropped; next round recomputes
        return self._execute(cmd, method)

    def _validate(self, cmd, method) -> bool:
        """Re-check the command against fresh state (validation.go:67)."""
        budgets = build_disruption_budgets(self.cluster, self.store, self.clock)
        fresh = {
            c.provider_id: c
            for c in get_candidates(
                self.cluster, self.store, self.cloud, self.clock, queue=self.queue,
                catalog_cache=self._catalog_cache,
            )
        }
        spent: dict = {}
        for c in cmd.candidates:
            fc = fresh.get(c.provider_id)
            if fc is None:
                return False  # candidate vanished or became non-disruptable
            pool = fc.node_pool.name
            spent[pool] = spent.get(pool, 0) + 1
            if spent[pool] > budgets.get(pool, {}).get(method.reason, 0):
                return False
            if method.reason == REASON_EMPTY and fc.reschedulable_pods:
                return False  # no longer empty
        if cmd.replacements:
            # re-simulate: the fresh simulation must still produce no more
            # claims than the command launches, and every instance type the
            # command would launch must still be among the types the fresh
            # simulation allows — a cheaper type that vanished (ICE'd,
            # price change) during the validation TTL invalidates the
            # command (validation.go:186: command types ⊆ fresh-sim types)
            # refresh FIRST: a successful delta-advance makes the bundle
            # generation-current, so inputs_for then serves the cached
            # solver inputs instead of a redundant re-assembly. After
            # _execute bumped the state, either path reflects every mark
            # the execute applied (delta-advanced or declined → rebuilt).
            bundle = self.ctx.snapshot_cache.refresh(
                self.provisioner, self.cluster, self.store,
                registry=self.registry,
            )
            sim = simulate_scheduling(
                self.provisioner, self.cluster, self.store, list(cmd.candidates),
                inputs=self.ctx.snapshot_cache.inputs_for(self.cluster),
                bundle=bundle,
            )
            if not sim.all_pods_scheduled() or len(sim.new_claims) > len(cmd.replacements):
                return False
            fresh_types = {
                it.name for claim in sim.new_claims for it in claim.instance_types
            }
            for claim in cmd.replacements:
                claim.instance_types = [
                    it for it in claim.instance_types if it.name in fresh_types
                ]
                if not claim.instance_types:
                    return False
        return True

    # -- execution (controller.go executeCommand:188) --------------------
    def _execute(self, cmd, method=None) -> bool:
        with obs.span("disrupt.execute", action=cmd.action, reason=cmd.reason):
            return self._execute_inner(cmd, method)

    def _execute_inner(self, cmd, method=None) -> bool:
        # 1. taint candidates so nothing schedules onto them (:196)
        for c in cmd.candidates:
            node = self.store.try_get("nodes", c.name)
            if node is not None:
                add_disruption_taint(self.store, node)
        # 2. launch replacements (:203)
        for claim in cmd.replacements:
            nc = claim.to_node_claim()
            self.store.create("nodeclaims", nc)
            cmd.replacement_names.append(nc.name)
        # 2b. open the command's fleet-ledger entry: predicted savings at
        # execution time, the cause chain every launch/drain event will
        # carry, and the pending claim/node sets whose completion
        # reconciles predicted vs realized (obs/timeline.py)
        from karpenter_tpu.controllers.disruption.methods import (
            candidate_prices,
            predicted_command_savings,
        )
        from karpenter_tpu.obs import timeline

        cmd.predicted_savings = predicted_command_savings(cmd)
        cause = {
            "site": getattr(method, "decision_site", "") or "",
            "rung": getattr(method, "last_rung", "") or "",
            "reason": cmd.reason,
        }
        cause["command"] = timeline.begin_command(
            site=cause["site"], rung=cause["rung"], reason=cmd.reason,
            predicted=cmd.predicted_savings,
            retired_rate=candidate_prices(cmd.candidates),
            claims=cmd.replacement_names,
            nodes=[c.name for c in cmd.candidates],
            registry=self.registry,
        )
        for name in cmd.replacement_names:
            timeline.pend_cause(name, cause)
        for c in cmd.candidates:
            timeline.record_event("drain", c.name, cause=cause,
                                  pods=len(c.reschedulable_pods))
        # 3. fence the state (:223)
        self.cluster.mark_for_deletion(*[c.provider_id for c in cmd.candidates])
        # 4. orchestrate deletion (:225)
        self.queue.add(cmd)
        from karpenter_tpu.operator import metrics as m

        self.log.info(
            "disrupting nodes",
            reason=cmd.reason,
            action=cmd.action,
            nodes=",".join(c.name for c in cmd.candidates),
            replacements=len(cmd.replacements),
        )
        self.registry.counter(m.DISRUPTION_ACTIONS, "disruption commands executed").inc(
            action=cmd.action, reason=cmd.reason)
        self.registry.counter(m.DISRUPTION_PODS, "pods displaced by disruption").inc(
            sum(len(c.reschedulable_pods) for c in cmd.candidates), reason=cmd.reason)
        if self.recorder is not None:
            self.recorder.publish(
                "DisruptionLaunching",
                f"{cmd.reason}: {cmd.action} {[c.name for c in cmd.candidates]}",
            )
        return True
